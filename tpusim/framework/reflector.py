"""Reflector: list+watch with relist-on-error, the client-go analogue.

The watch fabric can now fail like the real one: a stream dies mid-flight
(chaos disconnect) or falls too far behind and gets the "410 Gone" analog
(framework/events.py WatchBuffer overflow). A bare watcher silently
diverges from the store at that point. The Reflector is the consumer that
provably reconverges: it mirrors the stream into a ``known`` map, and when
a read raises :class:`WatchExpiredError` (or the stream closes under it)
it RELISTS through the fake apiserver, diffs the authoritative list
against ``known`` into synthetic DELETED/ADDED/MODIFIED events, replays
those through its handler, and re-watches — exactly client-go's
Reflector.ListAndWatch recovery loop (reflector.go), minus the goroutine.

Single-threaded determinism: nothing mutates the store between the relist
and the re-watch, so the fresh stream's replay-as-ADDED prefix mirrors the
list just diffed and is discarded instead of re-applied.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from tpusim.api.types import ResourceType
from tpusim.framework.events import WatchBuffer, WatchExpiredError
from tpusim.framework.restclient import FakeRESTClient, decode_list
from tpusim.framework.store import ADDED, DELETED, MODIFIED
from tpusim.obs import recorder as flight

EventHandler = Callable[[str, object], None]  # (event_type, object)


class Reflector:
    """Mirrors one (resource, namespace, fieldSelector) stream into
    ``known``, forwarding every event — live or synthesized by a relist —
    to ``handler``. Drive it with :meth:`sync` from the simulation loop."""

    def __init__(self, client: FakeRESTClient, resource: ResourceType,
                 handler: Optional[EventHandler] = None, namespace: str = "",
                 field_selector: str = "",
                 on_relist: Optional[Callable[[int], None]] = None):
        """on_relist: called with the relist ordinal after every recovery
        relist completes. The stream runtime (tpusim.stream) hooks this to
        invalidate its device-resident state — a relist means the event
        stream lost frames, so the synthetic diff it replayed may not be
        O(delta)-expressible against the resident arrays."""
        self.client = client
        self.resource = resource
        self.handler = handler
        self.namespace = namespace
        self.field_selector = field_selector
        self.on_relist = on_relist
        self.known: Dict[str, object] = {}
        self.relists = 0
        self._buf: Optional[WatchBuffer] = None

    # -- plumbing ---------------------------------------------------------

    def _request(self):
        req = self.client.get().resource(self.resource.value)
        if self.namespace:
            req.namespace(self.namespace)
        if self.field_selector:
            req.field_selector(self.field_selector)
        return req

    def _apply(self, event_type: str, obj) -> None:
        key = obj.key()
        if event_type == DELETED:
            self.known.pop(key, None)
        else:
            self.known[key] = obj
        if self.handler is not None:
            self.handler(event_type, obj)

    # -- the recovery loop ------------------------------------------------

    def relist(self) -> int:
        """List the authoritative state, diff against ``known`` into
        synthetic events, then re-watch. Returns events applied."""
        self.relists += 1
        flight.instant("reflector:relist", "host",
                       {"resource": self.resource.value,
                        "relists": self.relists})
        current = {o.key(): o
                   for o in decode_list(self._request().do(), self.resource)}
        applied = 0
        for key, obj in list(self.known.items()):
            if key not in current:
                self._apply(DELETED, obj)
                applied += 1
        for key, obj in current.items():
            old = self.known.get(key)
            if old is None:
                self._apply(ADDED, obj)
                applied += 1
            elif old.to_obj() != obj.to_obj():
                self._apply(MODIFIED, obj)
                applied += 1
        self._buf = self._request().watch()
        # the fresh stream front-loads `current` as ADDED (restclient.go:
        # 380-426 replay); the diff above already synced to it — discard
        for _ in range(len(current)):
            try:
                if self._buf.read(timeout=0) is None:
                    break
            except WatchExpiredError:
                break
        if self.on_relist is not None:
            self.on_relist(self.relists)
        return applied

    def sync(self, max_relists: int = 8) -> int:
        """Drain every available frame into ``known``/``handler``; on a
        dead stream (error or plain close) relist and keep draining.
        Returns the number of events applied this call."""
        applied = 0
        relists = 0
        if self._buf is None:
            # initial ListAndWatch: the watch replay serves as the list
            self._buf = self._request().watch()
        while True:
            try:
                ev = self._buf.read(timeout=0)
            except WatchExpiredError:
                if relists >= max_relists:
                    return applied
                relists += 1
                applied += self.relist()
                continue
            if ev is None:
                if self._buf.closed and relists < max_relists:
                    relists += 1
                    applied += self.relist()
                    continue
                return applied
            self._apply(ev.type, ev.object)
            applied += 1

"""Closure-backed read-only store for tests.

Reference: pkg/framework/store/fake.go:30-97 — FakeResourceStore with
per-resource data closures and no-op mutations.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from tpusim.api.types import ResourceType


class FakeResourceStore:
    def __init__(self,
                 pods_data: Optional[Callable[[], list]] = None,
                 nodes_data: Optional[Callable[[], list]] = None,
                 services_data: Optional[Callable[[], list]] = None,
                 pvc_data: Optional[Callable[[], list]] = None,
                 pv_data: Optional[Callable[[], list]] = None):
        self._data: Dict[ResourceType, Callable[[], list]] = {}
        if pods_data:
            self._data[ResourceType.PODS] = pods_data
        if nodes_data:
            self._data[ResourceType.NODES] = nodes_data
        if services_data:
            self._data[ResourceType.SERVICES] = services_data
        if pvc_data:
            self._data[ResourceType.PERSISTENT_VOLUME_CLAIMS] = pvc_data
        if pv_data:
            self._data[ResourceType.PERSISTENT_VOLUMES] = pv_data

    def resources(self):
        return list(self._data.keys())

    def list(self, resource: ResourceType) -> list:
        fn = self._data.get(resource)
        return list(fn()) if fn else []

    def get(self, resource: ResourceType, key: str):
        for obj in self.list(resource):
            if obj.key() == key:
                return obj, True
        return None, False

    # mutations are no-ops (fake.go:99-160)
    def add(self, resource, obj) -> None:
        pass

    def update(self, resource, obj) -> None:
        pass

    def delete(self, resource, obj) -> None:
        pass

    def register_event_handler(self, resource, handler) -> None:
        pass

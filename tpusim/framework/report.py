"""Report model + printers — the tool's real output contract.

Reference: pkg/framework/report.go. Three buckets (success / failed /
scheduled) each with per-pod requirements and a reason histogram, printed as
header + ASCII tables (tablewriter-style).
"""

from __future__ import annotations

from dataclasses import dataclass, field
import datetime
import io
from typing import Dict, List, Optional

from tpusim.api.quantity import Quantity
from tpusim.api.types import RESOURCE_NVIDIA_GPU, Pod, is_scalar_resource_name


@dataclass
class Status:
    """Reference: report.go:240-245 (+ preempted_pods, an extension populated
    only when the PodPriority gate is on)."""

    successful_pods: List[Pod] = field(default_factory=list)
    failed_pods: List[Pod] = field(default_factory=list)
    scheduled_pods: List[Pod] = field(default_factory=list)
    stop_reason: str = ""
    preempted_pods: List[Pod] = field(default_factory=list)


@dataclass
class Resources:
    """Reference: report.go Resources{PrimaryResources, ScalarResources}."""

    cpu: Quantity = field(default_factory=lambda: Quantity(0))
    memory: Quantity = field(default_factory=lambda: Quantity(0))
    nvidia_gpu: Quantity = field(default_factory=lambda: Quantity(0))
    scalar: Dict[str, int] = field(default_factory=dict)


def get_resource_request(pod: Pod) -> Resources:
    """Reference: report.go:96-129 — containers only (no init-container max)."""
    result = Resources()
    for container in pod.spec.containers:
        for name, q in container.requests.items():
            if name == "cpu":
                result.cpu = result.cpu + q
            elif name == "memory":
                result.memory = result.memory + q
            elif name == RESOURCE_NVIDIA_GPU:
                result.nvidia_gpu = result.nvidia_gpu + q
            elif is_scalar_resource_name(name):
                result.scalar[name] = result.scalar.get(name, 0) + q.value()
    return result


@dataclass
class Requirements:
    pod_name: str
    resources: Resources
    node_selectors: Optional[dict]


@dataclass
class PodReviewResult:
    pod_uid: str
    pod_name: str
    host: str
    reason: str
    resources: Resources


@dataclass
class ClusterCapacityReviewSpec:
    pods: List[Pod]
    pod_requirements: List[Requirements]


@dataclass
class ClusterCapacityReviewStatus:
    creation_timestamp: datetime.datetime
    pods: List[PodReviewResult]
    reason_summary: Dict[str, List[PodReviewResult]]


@dataclass
class ClusterCapacityReview:
    spec: ClusterCapacityReviewSpec
    status: ClusterCapacityReviewStatus


@dataclass
class ScheduleFailReason:
    fail_type: str
    fail_message: str


@dataclass
class GeneralReview:
    review: Dict[str, ClusterCapacityReview]
    fail_reason: ScheduleFailReason


def _review_of(pods: List[Pod]) -> ClusterCapacityReview:
    requirements = [Requirements(pod_name=p.name, resources=get_resource_request(p),
                                 node_selectors=p.spec.node_selector) for p in pods]
    results: List[PodReviewResult] = []
    reason_summary: Dict[str, List[PodReviewResult]] = {}
    for p in pods:
        prr = PodReviewResult(pod_uid=p.metadata.uid, pod_name=p.name,
                              host=p.spec.node_name, reason=p.status.reason,
                              resources=get_resource_request(p))
        reason_summary.setdefault(prr.reason, []).append(prr)
        results.append(prr)
    return ClusterCapacityReview(
        spec=ClusterCapacityReviewSpec(pods=pods, pod_requirements=requirements),
        status=ClusterCapacityReviewStatus(
            creation_timestamp=datetime.datetime.now(), pods=results,
            reason_summary=reason_summary))


def get_report(status: Status) -> GeneralReview:
    """Reference: report.go:168-180 (GetReport)."""
    return GeneralReview(
        review={
            "failed": _review_of(status.failed_pods),
            "success": _review_of(status.successful_pods),
            "scheduled": _review_of(status.scheduled_pods),
        },
        fail_reason=ScheduleFailReason(fail_type="Stopped",
                                       fail_message=status.stop_reason))


# ---------------------------------------------------------------------------
# printing (report.go:182-237; tablewriter-style ASCII tables)
# ---------------------------------------------------------------------------


def _render_table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out = [sep,
           "|" + "|".join(f" {h.upper():<{w}} " for h, w in zip(headers, widths)) + "|",
           sep]
    for row in rows:
        out.append("|" + "|".join(f" {c:<{w}} " for c, w in zip(row, widths)) + "|")
    out.append(sep)
    return "\n".join(out)


def _print_header(title: str, out) -> None:
    print(f"================================= {title} =================================",
          file=out)


def _distribute_pods_print(review: ClusterCapacityReview, out) -> None:
    rows = [[f"CPU: {s.resources.cpu}, Memory: {s.resources.memory}", s.host]
            for s in review.status.pods]
    print(_render_table(["Requirements", "Host"], rows), file=out)


def _status_print(status: ClusterCapacityReviewStatus, out) -> None:
    print("Pods summary:", file=out)
    for reason, pods in status.reason_summary.items():
        print(f"\t- {reason}: {len(pods)}", file=out)


def spec_print(spec: ClusterCapacityReviewSpec, out=None) -> None:
    """Reference: report.go:182-204 — per-pod requirement listing."""
    import sys

    out = out or sys.stdout
    for req in spec.pod_requirements:
        print(f"{req.pod_name} pod requirements:", file=out)
        print(f"\t- CPU: {req.resources.cpu}", file=out)
        print(f"\t- Memory: {req.resources.memory}", file=out)
        if not req.resources.nvidia_gpu.is_zero():
            print(f"\t- NvidiaGPU: {req.resources.nvidia_gpu}", file=out)
        if req.resources.scalar:
            print(f"\t- ScalarResources: {req.resources.scalar}", file=out)
        if req.node_selectors:
            selector = ",".join(f"{k}={v}" for k, v in sorted(req.node_selectors.items()))
            print(f"\t- NodeSelector: {selector}", file=out)
        print(file=out)


def cluster_capacity_review_print(review: GeneralReview, out=None) -> None:
    """Reference: report.go:234-237 — successful then failed pods."""
    import sys

    out = out or sys.stdout
    _print_header("Successful Pods", out)
    _distribute_pods_print(review.review["success"], out)
    _print_header("Failed Pods", out)
    _status_print(review.review["failed"].status, out)
    _distribute_pods_print(review.review["failed"], out)


def review_to_string(review: GeneralReview) -> str:
    buf = io.StringIO()
    cluster_capacity_review_print(review, out=buf)
    return buf.getvalue()

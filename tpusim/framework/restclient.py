"""Fake apiserver REST surface over the ResourceStore.

Reference: pkg/framework/restclient/external/restclient.go — the client-go
RESTClient stand-in whose Do(req) parses URL paths (:428-555), serializes
store contents into list/get JSON bodies (:312-378), spins per-(resource,
fieldSelector) watch streams that replay current objects as Added
(:380-426), fans store events out to matching watchers
(EmitObjectWatchEvent, :218-236), and evaluates field selectors against
objects (ObjectFieldsAccessor, :47-90 — a text/template hack there; a plain
dotted-path lookup over the serialized object here).

Path grammar (relative to the API group root, restclient.go:436-469):

    /{resource}
    /{resource}/{name}
    /namespaces/{ns}/{resource}
    /namespaces/{ns}/{resource}/{name}
    /namespaces/{ns}/{resource}/{name}/status
    /watch/{resource}                      (+ ?fieldSelector=...)
    /watch/namespaces/{ns}/{resource}

The `Request` builder mirrors client-go's chaining (Namespace/Resource/Name/
FieldsSelectorParam/Do/Watch), so the request side of the contract — build a
URL, have the fake parse it back — is exercised exactly as in the reference's
restclient_test.go / watch_test.go.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from tpusim.api.types import ResourceType
from tpusim.framework.events import WatchBuffer, WatchExpiredError
from tpusim.framework.store import ResourceStore

# resources served by the "core" group client (restclient.go NewRESTClient
# registers the core kinds; storageclasses live in the storage group)
_CORE_RESOURCES = (ResourceType.PODS, ResourceType.NODES,
                   ResourceType.SERVICES, ResourceType.PERSISTENT_VOLUMES,
                   ResourceType.PERSISTENT_VOLUME_CLAIMS)


class ApiError(Exception):
    """An apiserver error body (metav1.Status)."""

    def __init__(self, code: int, reason: str, message: str):
        self.code = code
        self.reason = reason
        self.message = message
        super().__init__(message)

    def to_obj(self) -> dict:
        return {"kind": "Status", "apiVersion": "v1", "status": "Failure",
                "reason": self.reason, "message": self.message,
                "code": self.code}


def _field_value(obj_dict: dict, dotted: str) -> str:
    """Dotted-path lookup over the serialized object; missing fields resolve
    to "" (the template hack in ObjectFieldsAccessor.Get does the same)."""
    cur = obj_dict
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return ""
        cur = cur[part]
    if cur is None:
        return ""
    return cur if isinstance(cur, str) else json.dumps(cur)


class FieldSelector:
    """metav1 field selector: comma-separated terms, `=`/`==` and `!=`."""

    def __init__(self, selector: str = ""):
        self.selector = selector or ""
        self.terms: List[Tuple[str, str, bool]] = []  # (path, value, negate)
        for term in filter(None, (t.strip() for t in self.selector.split(","))):
            if "!=" in term:
                path, value = term.split("!=", 1)
                self.terms.append((path.strip(), value.strip(), True))
            elif "==" in term:
                path, value = term.split("==", 1)
                self.terms.append((path.strip(), value.strip(), False))
            elif "=" in term:
                path, value = term.split("=", 1)
                self.terms.append((path.strip(), value.strip(), False))
            else:
                raise ApiError(400, "BadRequest",
                               f"invalid field selector term {term!r}")

    def matches_dict(self, obj_dict: dict) -> bool:
        for path, value, negate in self.terms:
            equal = _field_value(obj_dict, path) == value
            if equal == negate:
                return False
        return True

    def matches(self, obj) -> bool:
        if not self.terms:
            return True
        return self.matches_dict(obj.to_obj())


class Request:
    """client-go rest.Request chaining, minus the transport."""

    def __init__(self, client: "FakeRESTClient"):
        self._client = client
        self._namespace = ""
        self._resource = ""
        self._name = ""
        self._subresource = ""
        self._field_selector = ""

    def namespace(self, ns: str) -> "Request":
        self._namespace = ns
        return self

    def resource(self, resource: str) -> "Request":
        self._resource = resource
        return self

    def name(self, name: str) -> "Request":
        self._name = name
        return self

    def sub_resource(self, sub: str) -> "Request":
        self._subresource = sub
        return self

    def field_selector(self, selector: str) -> "Request":
        self._field_selector = selector
        return self

    def url(self, watch: bool = False) -> str:
        parts = []
        if watch:
            parts.append("watch")
        if self._namespace:
            parts.extend(["namespaces", self._namespace])
        parts.append(self._resource)
        if self._name:
            parts.append(self._name)
        if self._subresource:
            parts.append(self._subresource)
        return "/" + "/".join(parts)

    def do(self) -> dict:
        """GET list/get; returns the decoded JSON body (raises ApiError)."""
        return json.loads(self._client.handle(self.url(),
                                              self._field_selector))

    def watch(self) -> WatchBuffer:
        return self._client.handle_watch(self.url(watch=True),
                                         self._field_selector)


class FakeRESTClient:
    """restclient.go:557-570 NewRESTClient + the Do() dispatch."""

    def __init__(self, store: ResourceStore,
                 resources: tuple = _CORE_RESOURCES):
        self.store = store
        self.resources = {rt.value: rt for rt in resources}
        # (resource, namespace, selector) -> (parsed selector, shared buffer)
        # (restclient.go:380-426 keys watchers per resource+fieldSelector)
        self._watchers: Dict[Tuple[str, str, str],
                             Tuple[FieldSelector, WatchBuffer]] = {}
        # chaos seam (tpusim.chaos.FabricInjector): classifies each
        # watcher-frame delivery as deliver/drop/dup/disconnect
        self.fault_injector = None
        self._handlers = []
        for rt in resources:
            handler = (lambda event, obj, rt=rt:
                       self.emit_object_watch_event(rt, event, obj))
            self._handlers.append((rt, handler))
            self.store.register_event_handler(rt, handler)

    # --- request builder entry (client-go Client.Get()) ---

    def get(self) -> Request:
        return Request(self)

    # --- the event fan-out (restclient.go:218-236) ---

    def emit_object_watch_event(self, resource: ResourceType, event: str,
                                obj) -> None:
        obj_dict = None  # serialized lazily, once per event
        for (res, ns, _), (selector, buf) in list(self._watchers.items()):
            if res != resource.value or buf.closed:
                continue
            if ns and getattr(obj, "namespace", "") != ns:
                continue
            if selector.terms:
                if obj_dict is None:
                    obj_dict = obj.to_obj()
                if not selector.matches_dict(obj_dict):
                    continue
            if self.fault_injector is not None:
                action = self.fault_injector.on_event(res, event)
                if action == "drop":
                    continue
                if action == "disconnect":
                    # transport error mid-stream: already-queued frames
                    # survive, this one is lost, and the consumer's next
                    # read past them raises — a reflector must relist
                    buf.close_with_error(WatchExpiredError(
                        f"chaos: watch stream disconnect on {res}"))
                    continue
                if action == "dup":
                    buf.emit(event, obj)
            buf.emit(event, obj)

    # --- the Do() dispatch (restclient.go:428-555) ---

    def _parse(self, path: str):
        """Returns (watch, namespace, ResourceType, name, subresource)."""
        segments = [s for s in path.split("/") if s]
        watch = False
        if segments and segments[0] == "watch":
            watch = True
            segments = segments[1:]
        namespace = ""
        if len(segments) >= 2 and segments[0] == "namespaces":
            namespace = segments[1]
            segments = segments[2:]
        if not segments:
            raise ApiError(400, "BadRequest", f"unsupported path {path!r}")
        resource, name, subresource = segments[0], "", ""
        if len(segments) > 1:
            name = segments[1]
        if len(segments) > 2:
            subresource = segments[2]
        if len(segments) > 3 or (subresource and subresource != "status"):
            raise ApiError(400, "BadRequest", f"unsupported path {path!r}")
        rt = self.resources.get(resource.lower())
        if rt is None:
            raise ApiError(404, "NotFound",
                           f"the server could not find the requested "
                           f"resource {resource!r}")
        return watch, namespace, rt, name, subresource

    def _list_objects(self, rt: ResourceType, namespace: str,
                      selector: FieldSelector) -> list:
        objs = self.store.list(rt)
        if namespace:
            objs = [o for o in objs
                    if getattr(o, "namespace", "") == namespace]
        return [o for o in objs if selector.matches(o)]

    def handle(self, path: str, field_selector: str = "") -> str:
        """GET dispatch: list or single-object JSON body (the reference's
        createListReadCloser/createGetReadCloser, restclient.go:312-378)."""
        watch, namespace, rt, name, _sub = self._parse(path)
        if watch:
            raise ApiError(400, "BadRequest",
                           "watch paths stream; use handle_watch")
        selector = FieldSelector(field_selector)
        if not name:
            items = self._list_objects(rt, namespace, selector)
            kind = rt.object_type().kind
            return json.dumps({"kind": f"{kind}List", "apiVersion": "v1",
                               "items": [o.to_obj() for o in items]},
                              sort_keys=True)
        key = f"{namespace}/{name}" if namespace else name
        obj, exists = self.store.get(rt, key)
        if not exists and not namespace:
            # cluster-scoped lookups of namespaced kinds fall back to a scan
            # (the reference's accessor matches on metadata.name)
            for o in self.store.list(rt):
                if getattr(o, "name", "") == name:
                    obj, exists = o, True
                    break
        if not exists:
            raise ApiError(404, "NotFound",
                           f'{rt.value} "{name}" not found')
        return json.dumps(obj.to_obj(), sort_keys=True)

    def handle_watch(self, path: str, field_selector: str = "") -> WatchBuffer:
        """Watch dispatch: replay current objects as ADDED on a shared
        per-(resource, namespace, selector) buffer, then stream store events
        (restclient.go:380-426)."""
        watch, namespace, rt, name, _sub = self._parse(path)
        if not watch or name:
            raise ApiError(400, "BadRequest",
                           f"unsupported watch path {path!r}")
        key = (rt.value, namespace, field_selector or "")
        entry = self._watchers.get(key)
        if entry is not None and not entry[1].closed:
            return entry[1]
        selector = FieldSelector(field_selector)
        buf = WatchBuffer(resource=rt.value)
        from tpusim.framework.store import ADDED

        for obj in self._list_objects(rt, namespace, selector):
            buf.emit(ADDED, obj)
        self._watchers[key] = (selector, buf)
        return buf

    def close(self) -> None:
        for _, buf in self._watchers.values():
            buf.close()
        self._watchers.clear()
        # detach from the store so a shared ResourceStore doesn't keep dead
        # clients alive (and pay per-event fan-out to them)
        for rt, handler in self._handlers:
            self.store.unregister_event_handler(rt, handler)
        self._handlers = []


def decode_list(body: dict, rt: ResourceType) -> list:
    """Typed round-trip of a list body (the tests' compare-typed-lists step
    in restclient_test.go)."""
    cls = rt.object_type()
    return [cls.from_obj(item) for item in body.get("items", [])]

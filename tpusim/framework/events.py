"""Watch streams + event recorder.

Reference: pkg/framework/watch/watch.go (WatchBuffer — an io.ReadCloser JSON
frame stream fed by EmitWatchEvent) and pkg/framework/record/recorder.go
(channel-backed EventRecorder, buffer 10, drained one event per Bind/Update).

The WatchBuffer here is a bounded queue of (type, object) frames with
replay-current-objects-as-Added semantics on subscribe (restclient.go:380-426),
instead of the reference's hand-rolled reader/writer lock dance that SURVEY.md
§5 flags as fragile.
"""

from __future__ import annotations

import json
import queue
from dataclasses import dataclass
from typing import Iterator, Optional

from tpusim.api.types import ResourceType
from tpusim.framework.store import ADDED, ResourceStore


@dataclass
class WatchEvent:
    type: str   # ADDED | MODIFIED | DELETED
    object: object

    def to_frame(self) -> str:
        """The JSON wire frame the reference streams (watch.go:99-125);
        event types are capitalized on the wire ("Added"/"Modified"/"Deleted")."""
        return json.dumps({"type": self.type.capitalize(),
                           "object": self.object.to_obj()}, sort_keys=True)


# A lagging watcher's buffered window is finite, like an apiserver's etcd
# watch cache: past this many undrained frames the stream dies with the
# "410 Gone" analog and the consumer must relist (WatchExpiredError).
DEFAULT_WATCH_BUFFER_SIZE = 4096


class WatchExpiredError(Exception):
    """The watch stream's buffered window is gone — the apiserver's
    "410 Gone" / "too old resource version". The consumer cannot resume
    from where it was; it must relist and re-watch (see
    framework/reflector.py)."""

    code = 410


class WatchBuffer:
    """A bounded FIFO of watch events; close() wakes readers.

    Overflow tears the stream: queued-but-undrained frames are discarded
    (that window is exactly what the consumer can no longer trust) and
    every subsequent read() raises :class:`WatchExpiredError`."""

    _CLOSED = object()
    _ERROR = object()

    def __init__(self, maxsize: int = DEFAULT_WATCH_BUFFER_SIZE,
                 resource: str = ""):
        # +1 slot so the error sentinel always fits after a drain
        self._q: queue.Queue = queue.Queue(maxsize=maxsize + 1 if maxsize
                                           else 0)
        self.maxsize = maxsize
        self.resource = resource
        self.closed = False
        self.error: Optional[Exception] = None

    def emit(self, event_type: str, obj) -> None:
        if self.closed:
            return
        if self.maxsize and self._q.qsize() >= self.maxsize:
            self._overflow()
            return
        self._q.put(WatchEvent(event_type, obj))

    def _overflow(self) -> None:
        from tpusim.obs.recorder import note_watch_overflow

        note_watch_overflow(self.resource or "unknown")
        self.close_with_error(WatchExpiredError(
            f"watch buffer overflow ({self.maxsize} undrained frames) on "
            f"{self.resource or 'stream'}: too old resource version"),
            drop_pending=True)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._q.put(self._CLOSED)

    def close_with_error(self, exc: Exception,
                         drop_pending: bool = False) -> None:
        """Terminate the stream with a transport error: readers drain any
        surviving frames, then read() raises `exc` (once per call)."""
        if self.closed:
            return
        self.closed = True
        self.error = exc
        if drop_pending:
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
        self._q.put(self._ERROR)

    def read(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is self._ERROR:
            self._q.put(self._ERROR)  # every subsequent read fails too
            raise self.error
        if item is self._CLOSED:
            return None
        return item

    def __iter__(self) -> Iterator[WatchEvent]:
        while True:
            ev = self.read(timeout=0)
            if ev is None:
                return
            yield ev

    def drain(self, limit: Optional[int] = None) -> list:
        """Non-blocking batch read: every currently queued frame (up to
        `limit`) as a list. An expired stream raises WatchExpiredError like
        read(), but never swallows frames: any surviving frames read before
        the error sentinel are returned and the NEXT drain() raises (the
        sentinel is re-queued by read())."""
        out: list = []
        while limit is None or len(out) < limit:
            try:
                ev = self.read(timeout=0)
            except WatchExpiredError:
                if out:
                    return out
                raise
            if ev is None:
                break
            out.append(ev)
        return out


def watch_resource(store: ResourceStore, resource: ResourceType) -> WatchBuffer:
    """Subscribe to a resource: current objects replay as ADDED, then live
    events stream (restclient.go:380-426 list+watch semantics)."""
    buf = WatchBuffer(resource=resource.value)
    for obj in store.list(resource):
        buf.emit(ADDED, obj)
    store.register_event_handler(resource, buf.emit)
    return buf


def load_event_log(path: str) -> list:
    """Parse a watch-event log: JSON lines in the reference's wire-frame shape
    {"type": "Added|Modified|Deleted", "object": {kind, ...}} (watch.go:99-125
    — the frames the WatchBuffer streams; WatchEvent.to_frame writes the same
    format). Returns [(EVENT_TYPE, obj), ...] ready for
    jaxe.delta.IncrementalCluster.apply_events / run_simulation(events=...)."""
    import io

    from tpusim.api.types import (
        Node,
        PersistentVolume,
        PersistentVolumeClaim,
        Pod,
        Service,
    )
    from tpusim.framework.store import DELETED, MODIFIED

    kinds = {"Pod": Pod, "Node": Node, "Service": Service,
             "PersistentVolume": PersistentVolume,
             "PersistentVolumeClaim": PersistentVolumeClaim}
    valid = {ADDED, MODIFIED, DELETED}
    events = []
    with io.open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                frame = json.loads(line)
            except json.JSONDecodeError as exc:
                raise ValueError(f"{path}:{lineno}: not JSON: {exc}") from exc
            event_type = str(frame.get("type", "")).upper()
            if event_type not in valid:
                raise ValueError(f"{path}:{lineno}: unknown event type "
                                 f"{frame.get('type')!r}")
            obj = frame.get("object") or {}
            if not isinstance(obj, dict):
                raise ValueError(f"{path}:{lineno}: \"object\" must be a "
                                 f"JSON object, got {type(obj).__name__}")
            cls = kinds.get(obj.get("kind", ""))
            if cls is None:
                raise ValueError(f"{path}:{lineno}: unsupported object kind "
                                 f"{obj.get('kind')!r} (expected Pod/Node/"
                                 "Service/PersistentVolume/"
                                 "PersistentVolumeClaim)")
            try:
                events.append((event_type, cls.from_obj(obj)))
            except (TypeError, AttributeError, KeyError) as exc:
                raise ValueError(
                    f"{path}:{lineno}: malformed {obj.get('kind')} object: "
                    f"{exc}") from exc
    return events


@dataclass
class Event:
    """client-go record.Event essentials."""

    object_kind: str = ""
    object_name: str = ""
    event_type: str = ""   # Normal | Warning
    reason: str = ""
    message: str = ""


class Recorder:
    """Bounded event sink. Reference: record/recorder.go:33-61 — the simulator
    creates it with capacity 10 (simulator.go:240) and drains one event per
    Bind/Update completion."""

    def __init__(self, buffer_size: int = 10):
        self.events: queue.Queue = queue.Queue(maxsize=buffer_size)

    def eventf(self, obj, event_type: str, reason: str, message_fmt: str,
               *args) -> None:
        event = Event(object_kind=getattr(obj, "kind", ""),
                      object_name=getattr(obj, "name", ""),
                      event_type=event_type, reason=reason,
                      message=(message_fmt % args) if args else message_fmt)
        try:
            self.events.put_nowait(event)
        except queue.Full:
            pass  # reference behavior: the channel blocks; we drop instead of deadlock

    def drain_one(self, timeout: float = 0.0) -> Optional[Event]:
        try:
            return self.events.get(timeout=timeout) if timeout else self.events.get_nowait()
        except queue.Empty:
            return None

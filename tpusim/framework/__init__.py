"""Cluster-state emulation: store, watch events, recorder, strategy, report.

Reference: pkg/framework/. The fake-REST/HTTP-body machinery is deliberately
not ported (SURVEY.md §7 design stance) — its semantics (snapshot in, watch
events out, placements mutate only in-memory state) are re-founded on a
synchronous in-process event bus; SURVEY.md §5 explicitly flags the reference's
hand-rolled WatchBuffer locking as worth not reproducing.
"""

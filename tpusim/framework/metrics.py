"""Scheduler metrics: per-phase latency histograms and preemption counters.

Reference: vendor/k8s.io/kubernetes/pkg/scheduler/metrics/metrics.go:25-113 —
Prometheus histograms with ExponentialBuckets(1000, 2, 15) (microseconds,
smallest bucket 1ms) under the "scheduler" subsystem, observed at
scheduler.go:425,452-457,492 and core/generic_scheduler.go:148,154,163. The
reference registers these but never serves them (the simulator starts no
metrics HTTP server); here the registry is in-process and can be dumped in
Prometheus text exposition format for the same scrape shape.

The metric names are kept identical so dashboards keyed on the reference's
names keep working.

On TPU the per-phase split changes meaning: the whole
filter→score→select→bind step is one fused device program, so the jax backend
observes per-batch device-dispatch walltime into the same histograms
(SURVEY.md §5 tracing note) rather than per-phase host time.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

SCHEDULER_SUBSYSTEM = "scheduler"


def exponential_buckets(start: float, factor: float, count: int) -> List[float]:
    """prometheus.ExponentialBuckets."""
    return [start * factor**i for i in range(count)]


def escape_label_value(value: str) -> str:
    """Prometheus text-exposition label-value escaping (exposition format
    spec): backslash, double-quote, and line-feed must be escaped or a
    scraper mis-parses the sample line."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class Histogram:
    def __init__(self, name: str, help_text: str, buckets: List[float]):
        self.name = name
        self.help = help_text
        self.buckets = sorted(buckets)
        self.bucket_counts = [0] * len(self.buckets)
        self.count = 0
        self.total = 0.0
        # OpenMetrics exemplar: the last (trace_id, value) observed with a
        # trace attached; None until a traced observation lands, so default
        # expositions are byte-identical to the pre-exemplar format
        self.exemplar: Optional[tuple] = None
        self._lock = threading.Lock()

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        with self._lock:
            self.count += 1
            self.total += value
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    self.bucket_counts[i] += 1
            if exemplar is not None:
                self.exemplar = (exemplar, value)

    def reset(self) -> None:
        with self._lock:
            self.bucket_counts = [0] * len(self.buckets)
            self.count = 0
            self.total = 0.0
            self.exemplar = None

    def expose(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        # bucket_counts are already cumulative (observe() increments every
        # bucket whose bound covers the value)
        for bound, bucket_count in zip(self.buckets, self.bucket_counts):
            lines.append(f'{self.name}_bucket{{le="{bound:g}"}} {bucket_count}')
        inf = f'{self.name}_bucket{{le="+Inf"}} {self.count}'
        if self.exemplar is not None:
            trace_id, value = self.exemplar
            inf += (f' # {{trace_id="{escape_label_value(trace_id)}"}} '
                    f'{value:g}')
        lines.append(inf)
        lines.append(f"{self.name}_sum {self.total:g}")
        lines.append(f"{self.name}_count {self.count}")
        return lines


class Counter:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def reset(self) -> None:
        with self._lock:
            self.value = 0.0

    def expose(self) -> List[str]:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} counter",
                f"{self.name} {self.value:g}"]


class Gauge:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = value

    def reset(self) -> None:
        with self._lock:
            self.value = 0.0

    def expose(self) -> List[str]:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} gauge",
                f"{self.name} {self.value:g}"]


class LabeledCounter:
    """A counter family with one label dimension (prometheus CounterVec).

    Used by the `tpusim_backend_*` families where the interesting fact is
    *which* path/transition fired, not just how often anything did.
    """

    def __init__(self, name: str, help_text: str, label: str):
        self.name = name
        self.help = help_text
        self.label = label
        self.values: Dict[str, float] = {}
        self._lock = threading.Lock()

    def inc(self, label_value: str, amount: float = 1.0) -> None:
        with self._lock:
            self.values[label_value] = self.values.get(label_value, 0.0) + amount

    def get(self, label_value: str) -> float:
        with self._lock:
            return self.values.get(label_value, 0.0)

    def reset(self) -> None:
        with self._lock:
            self.values.clear()

    def expose(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self.values.items())
        for label_value, value in items:
            lines.append(f'{self.name}{{{self.label}='
                         f'"{escape_label_value(label_value)}"}} {value:g}')
        return lines


class LabeledHistogram:
    """A histogram family with one label dimension (prometheus
    HistogramVec). Child histograms are created lazily on first observe —
    the stream runtime's per-path cycle latency is the first user."""

    def __init__(self, name: str, help_text: str, label: str,
                 buckets: List[float]):
        self.name = name
        self.help = help_text
        self.label = label
        self.buckets = sorted(buckets)
        self.children: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def observe(self, label_value: str, value: float,
                exemplar: Optional[str] = None) -> None:
        with self._lock:
            child = self.children.get(label_value)
            if child is None:
                child = Histogram(
                    f'{self.name}{{{self.label}="{label_value}"}}',
                    self.help, self.buckets)
                self.children[label_value] = child
        child.observe(value, exemplar)

    def get(self, label_value: str) -> Optional[Histogram]:
        with self._lock:
            return self.children.get(label_value)

    def reset(self) -> None:
        with self._lock:
            self.children.clear()

    def expose(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            items = sorted(self.children.items())
        for label_value, child in items:
            pair = f'{self.label}="{escape_label_value(label_value)}"'
            for bound, bucket_count in zip(child.buckets,
                                           child.bucket_counts):
                lines.append(f'{self.name}_bucket{{{pair},le="{bound:g}"}} '
                             f'{bucket_count}')
            inf = f'{self.name}_bucket{{{pair},le="+Inf"}} {child.count}'
            if child.exemplar is not None:
                trace_id, value = child.exemplar
                inf += (f' # {{trace_id="{escape_label_value(trace_id)}"}} '
                        f'{value:g}')
            lines.append(inf)
            lines.append(f'{self.name}_sum{{{pair}}} {child.total:g}')
            lines.append(f'{self.name}_count{{{pair}}} {child.count}')
        return lines


class LabeledGauge:
    """A gauge family with one label dimension (prometheus GaugeVec).

    First users: the cluster analytics plane's per-resource
    utilization/fragmentation ratios and per-component HBM residency
    (ISSUE 14). The label must stay bounded — tools/metrics_lint.py
    enforces an allowlist of label names with finite value sets."""

    def __init__(self, name: str, help_text: str, label: str):
        self.name = name
        self.help = help_text
        self.label = label
        self.values: Dict[str, float] = {}
        self._lock = threading.Lock()

    def set(self, label_value: str, value: float) -> None:
        with self._lock:
            self.values[label_value] = value

    def get(self, label_value: str) -> float:
        with self._lock:
            return self.values.get(label_value, 0.0)

    def reset(self) -> None:
        with self._lock:
            self.values.clear()

    def expose(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self.values.items())
        for label_value, value in items:
            lines.append(f'{self.name}{{{self.label}='
                         f'"{escape_label_value(label_value)}"}} {value:g}')
        return lines


class InfoGauge:
    """An info-style gauge (prometheus *_info convention): constant value 1
    with the interesting facts carried as label values. Setting it replaces
    the label set, so exactly one sample is exposed at a time — scrapes see
    the CURRENT chain head / build info, never a history."""

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self.labels: Dict[str, str] = {}
        self.value = 0.0  # 1.0 once set; 0 families are skipped by snapshot
        self._lock = threading.Lock()

    def set_info(self, **labels: str) -> None:
        with self._lock:
            self.labels = {k: str(v) for k, v in labels.items()}
            self.value = 1.0

    def reset(self) -> None:
        with self._lock:
            self.labels = {}
            self.value = 0.0

    def expose(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            if self.value:
                pairs = ",".join(
                    f'{k}="{escape_label_value(v)}"'
                    for k, v in sorted(self.labels.items()))
                lines.append(f"{self.name}{{{pairs}}} {self.value:g}")
        return lines


_LATENCY_BUCKETS = exponential_buckets(1000, 2, 15)


class SchedulerMetrics:
    """The metric set of metrics/metrics.go:29-91, names preserved, plus
    the `tpusim_backend_*` families for the device engine (ISSUE 2)."""

    def __init__(self):
        s = SCHEDULER_SUBSYSTEM
        self._registry: List = []
        self.e2e_scheduling_latency = self._reg(Histogram(
            f"{s}_e2e_scheduling_latency_microseconds",
            "E2e scheduling latency (scheduling algorithm + binding)",
            _LATENCY_BUCKETS))
        self.scheduling_algorithm_latency = self._reg(Histogram(
            f"{s}_scheduling_algorithm_latency_microseconds",
            "Scheduling algorithm latency", _LATENCY_BUCKETS))
        self.predicate_evaluation = self._reg(Histogram(
            f"{s}_scheduling_algorithm_predicate_evaluation",
            "Scheduling algorithm predicate evaluation duration",
            _LATENCY_BUCKETS))
        self.priority_evaluation = self._reg(Histogram(
            f"{s}_scheduling_algorithm_priority_evaluation",
            "Scheduling algorithm priority evaluation duration",
            _LATENCY_BUCKETS))
        self.preemption_evaluation = self._reg(Histogram(
            f"{s}_scheduling_algorithm_preemption_evaluation",
            "Scheduling algorithm preemption evaluation duration",
            _LATENCY_BUCKETS))
        self.binding_latency = self._reg(Histogram(
            f"{s}_binding_latency_microseconds", "Binding latency",
            _LATENCY_BUCKETS))
        self.preemption_victims = self._reg(Gauge(
            f"{s}_pod_preemption_victims",
            "Number of selected preemption victims"))
        self.preemption_attempts = self._reg(Counter(
            f"{s}_total_preemption_attempts",
            "Total preemption attempts in the cluster till now"))
        # device-engine telemetry (no reference analog; new families)
        self.backend_compile_latency = self._reg(Histogram(
            "tpusim_backend_compile_latency_microseconds",
            "Jax backend cluster compile (interning + device tables) walltime",
            _LATENCY_BUCKETS))
        self.backend_dispatch_latency = self._reg(Histogram(
            "tpusim_backend_dispatch_latency_microseconds",
            "Jax backend device dispatch walltime per batch or chunk",
            _LATENCY_BUCKETS))
        self.backend_route = self._reg(LabeledCounter(
            "tpusim_backend_route_total",
            "Scheduling batches by execution route", "route"))
        self.backend_auto_transitions = self._reg(LabeledCounter(
            "tpusim_backend_auto_transitions_total",
            "Fast-path AUTO verify-then-trust state transitions",
            "transition"))
        self.backend_victim_path = self._reg(LabeledCounter(
            "tpusim_backend_victim_path_total",
            "Preemption victim-selection path per attempt", "path"))
        self.fast_fallback = self._reg(LabeledCounter(
            "tpusim_fast_fallback_total",
            "Pallas fast-path plan rejections by blocker class", "reason"))
        # node-sharded route telemetry (ISSUE 16): the TPUSIM_SHARDS mesh
        # shape, real (non-padding) nodes owned per shard, the estimated
        # cross-shard collective payload of the last sharded dispatch, and
        # batches the route declined, by blocker class
        self.shard_count = self._reg(Gauge(
            "tpusim_shard_count",
            "Node-mesh shards in the active sharded scan route (0 = off)"))
        self.shard_node_occupancy = self._reg(LabeledGauge(
            "tpusim_shard_node_occupancy",
            "Real (non-padding) nodes owned by each node-mesh shard",
            "shard"))
        self.shard_collective_bytes = self._reg(Gauge(
            "tpusim_shard_collective_bytes",
            "Estimated cross-shard collective payload of the last sharded "
            "dispatch"))
        self.shard_fallback = self._reg(LabeledCounter(
            "tpusim_shard_fallback_total",
            "TPUSIM_SHARDS batches the sharded route declined, by blocker "
            "class", "reason"))
        # chaos-engine telemetry (ISSUE 3): injected faults by kind, watch
        # buffer overflows by resource, and the dispatch circuit breaker
        self.fault_injected = self._reg(LabeledCounter(
            "tpusim_fault_injected_total",
            "Chaos faults injected, by fault kind", "kind"))
        self.watch_overflow = self._reg(LabeledCounter(
            "tpusim_watch_overflow_total",
            "Watch streams terminated on buffer overflow (410 Gone analog)",
            "resource"))
        self.breaker_transitions = self._reg(LabeledCounter(
            "tpusim_breaker_transitions_total",
            "Device-dispatch circuit breaker transitions", "transition"))
        self.breaker_state = self._reg(Gauge(
            "tpusim_breaker_state",
            "Device-dispatch breaker state (0 closed, 0.5 half-open, 1 open)"))
        # scenario-fleet serving telemetry (ISSUE 6): the what-if capacity
        # service — admission queue, shape-class buckets, dispatch cache
        self.serve_queue_depth = self._reg(Gauge(
            "tpusim_serve_queue_depth",
            "What-if requests admitted and waiting to be bucketed"))
        self.serve_batch_occupancy = self._reg(Histogram(
            "tpusim_serve_batch_occupancy",
            "Real (non-ghost) scenarios per dispatched bucket",
            [1, 2, 4, 8, 16, 32, 64]))
        self.serve_request_latency = self._reg(Histogram(
            "tpusim_serve_request_latency_microseconds",
            "Admission to decoded-result latency per what-if request",
            _LATENCY_BUCKETS))
        self.serve_rejected = self._reg(LabeledCounter(
            "tpusim_serve_rejected_total",
            "What-if requests rejected at admission, by reason", "reason"))
        self.serve_dispatch = self._reg(LabeledCounter(
            "tpusim_serve_dispatch_total",
            "Bucket dispatches by warm-executable-cache outcome", "path"))
        # streaming-runtime telemetry (ISSUE 7): the device-resident cluster
        # path — every residency miss routed through a full restage is
        # classified by cause, and cycles split by execution path so an
        # O(delta) steady state is visible as stream_scan dominating
        self.stream_restage = self._reg(LabeledCounter(
            "tpusim_stream_restage_total",
            "Stream-runtime full restages of device-resident state, by cause",
            "reason"))
        self.stream_cycles = self._reg(LabeledCounter(
            "tpusim_stream_cycles_total",
            "Stream-runtime scheduling cycles, by execution path", "path"))
        # stream v2 telemetry (ISSUE 9): per-path cycle latency plus the
        # pipelining health gauges — depth (0 sync, 1 one cycle in flight)
        # and the fraction of a cycle's host decode that overlapped device
        # execution instead of blocking on it
        self.stream_cycle_latency = self._reg(LabeledHistogram(
            "tpusim_stream_cycle_latency_us",
            "Stream-runtime cycle walltime by execution path",
            "path", _LATENCY_BUCKETS))
        self.stream_pipeline_depth = self._reg(Gauge(
            "tpusim_stream_pipeline_depth",
            "Cycles in flight on the stream pipeline (0 = synchronous)"))
        self.stream_overlap_fraction = self._reg(Gauge(
            "tpusim_stream_overlap_fraction",
            "Fraction of the last pipelined fold that did not block on the "
            "device (1.0 = decode fully hidden behind device execution)"))
        # crash-recovery telemetry (ISSUE 12): the WAL + checkpoint layer
        # for the device-resident twin, and the serve fleet's degraded
        # modes under chaos
        self.recovery_checkpoint_latency = self._reg(Histogram(
            "tpusim_recovery_checkpoint_latency_microseconds",
            "Host-snapshot checkpoint walltime (device_get + atomic write)",
            _LATENCY_BUCKETS))
        self.recovery_replay_latency = self._reg(Histogram(
            "tpusim_recovery_replay_latency_microseconds",
            "Crash-recovery walltime: checkpoint load + WAL tail replay",
            _LATENCY_BUCKETS))
        self.recovery_wal_records = self._reg(Gauge(
            "tpusim_recovery_wal_records",
            "Records in the stream write-ahead journal"))
        self.serve_retry = self._reg(LabeledCounter(
            "tpusim_serve_retry_total",
            "Serve-fleet dispatch retries, by fault reason", "reason"))
        self.serve_degraded = self._reg(LabeledCounter(
            "tpusim_serve_degraded_total",
            "Serve-fleet requests answered via a degraded path", "path"))
        # observability plane (ISSUE 13): bounded flight recorder, SLO
        # tracking against a configurable per-cycle latency target, and the
        # recovery chain head published for /healthz continuity checks
        self.obs_dropped_events = self._reg(LabeledCounter(
            "tpusim_obs_dropped_events_total",
            "Flight-recorder events dropped by the bounded ring buffer, "
            "by span category", "category"))
        # fleet-wide distributed tracing (ISSUE 20): cross-boundary flow
        # events and the bounded /debug/trace ring
        self.trace_flows = self._reg(LabeledCounter(
            "tpusim_trace_flows_total",
            "Cross-boundary trace flow starts (Chrome 's' phase) emitted, "
            "by boundary site", "site"))
        self.trace_ring_events = self._reg(Gauge(
            "tpusim_trace_ring_events",
            "Events currently held in the flight-recorder ring served by "
            "/debug/trace"))
        self.slo_target = self._reg(Gauge(
            "tpusim_slo_cycle_latency_target_microseconds",
            "Configured per-cycle latency SLO target (0 = no SLO armed)"))
        self.slo_cycles = self._reg(LabeledCounter(
            "tpusim_slo_cycles_total",
            "Scheduling cycles judged against the latency SLO target",
            "verdict"))
        self.slo_burn_rate = self._reg(Gauge(
            "tpusim_slo_burn_rate",
            "Windowed error-budget burn rate (breach fraction over the "
            "window divided by the SLO's error budget; 1.0 = burning "
            "exactly at budget)"))
        self.stream_chain_head = self._reg(InfoGauge(
            "tpusim_stream_chain_head_info",
            "Current placement-chain head of the stream WAL (labels: head, "
            "cycle) — proves WAL/chain continuity without reading the "
            "checkpoint dir"))
        self.recovery_last_checkpoint_timestamp = self._reg(Gauge(
            "tpusim_recovery_last_checkpoint_timestamp_seconds",
            "Unix time of the last completed stream checkpoint"))
        self.provenance_records = self._reg(Counter(
            "tpusim_provenance_records_total",
            "Decision-provenance records captured into the explanation ring"))
        # cluster analytics plane (ISSUE 14): fleet-level aggregates reduced
        # on-device from the resident twin, plus HBM residency and
        # compile-cost accounting — refreshed at scrape time by
        # tpusim.obs.analytics.refresh_gauges()
        self.cluster_utilization = self._reg(LabeledGauge(
            "tpusim_cluster_utilization_ratio",
            "Requested / allocatable per resource across valid nodes "
            "(latest analytics sample)", "resource"))
        self.cluster_fragmentation = self._reg(LabeledGauge(
            "tpusim_cluster_fragmentation_ratio",
            "1 - largest-free-slot / total-free per resource (0 = all free "
            "capacity on one node, 1 = fully shredded)", "resource"))
        self.cluster_feasible_nodes = self._reg(Gauge(
            "tpusim_cluster_feasible_nodes",
            "Nodes with free cpu AND memory AND pod slots in the latest "
            "analytics sample"))
        self.cluster_nodes = self._reg(Gauge(
            "tpusim_cluster_nodes",
            "Valid nodes covered by the latest analytics sample"))
        self.analytics_samples = self._reg(Counter(
            "tpusim_analytics_samples_total",
            "On-device analytics reductions captured into the ring"))
        self.hbm_resident_bytes = self._reg(LabeledGauge(
            "tpusim_hbm_resident_bytes",
            "Bytes held resident per component (device twin, staged LRU, "
            "batched device trees)", "component"))
        self.hbm_cache_entries = self._reg(LabeledGauge(
            "tpusim_hbm_cache_entries",
            "Entries held per cache component (staged scenarios, device "
            "batches, compiled executables)", "component"))
        self.compile_traces = self._reg(LabeledCounter(
            "tpusim_compile_traces_total",
            "Cumulative compiles/retraces by observation site", "site"))
        self.compile_cost = self._reg(LabeledCounter(
            "tpusim_compile_cost_us_total",
            "Cumulative compile walltime by observation site", "site"))
        self.gang_admitted = self._reg(Counter(
            "tpusim_gang_admitted_total",
            "Pod groups admitted all-or-nothing (>= min-available placed)"))
        self.gang_rejected = self._reg(LabeledCounter(
            "tpusim_gang_rejected_total",
            "Pod groups rejected whole with one shared FitError", "reason"))
        self.gang_partial_rollback = self._reg(Counter(
            "tpusim_gang_partial_rollback_total",
            "Partially-bound gangs rolled back to zero members (commit "
            "failure, preemption release, or chaos node loss)"))
        self.gang_size = self._reg(Histogram(
            "tpusim_gang_size",
            "Members per admitted-or-rejected pod group",
            [1, 2, 4, 8, 16, 32, 64]))
        # replicated control plane (ISSUE 18): WAL shipping to a hot
        # standby, per-cycle chain cross-checks, and chaos-driven leader
        # failover with an end-to-end RTO
        self.replication_lag_records = self._reg(Gauge(
            "tpusim_replication_lag_records",
            "WAL records appended on the leader but not yet acked by the "
            "follower"))
        self.replication_lag_bytes = self._reg(Gauge(
            "tpusim_replication_lag_bytes",
            "WAL bytes durable on the leader but not yet acked by the "
            "follower"))
        self.replication_lag_seconds = self._reg(Gauge(
            "tpusim_replication_lag_seconds",
            "Age of the oldest unacked WAL record on the ship queue "
            "(0 = follower fully caught up)"))
        self.replication_last_shipped_seq = self._reg(Gauge(
            "tpusim_replication_last_shipped_seq",
            "Highest replication sequence number handed to the wire "
            "(-1 = nothing shipped yet)"))
        self.replication_ship_latency = self._reg(Histogram(
            "tpusim_replication_ship_latency_microseconds",
            "Append-to-ack walltime per shipped WAL record",
            _LATENCY_BUCKETS))
        self.replication_apply_latency = self._reg(Histogram(
            "tpusim_replication_apply_latency_microseconds",
            "Receive-to-applied walltime per record on the follower twin",
            _LATENCY_BUCKETS))
        self.replication_promotions = self._reg(Counter(
            "tpusim_replication_promotions_total",
            "Followers promoted to leader (successful failovers)"))
        self.replication_divergence = self._reg(Counter(
            "tpusim_replication_divergence_total",
            "Per-cycle placement-hash chain cross-check failures on a "
            "follower (any value > 0 latches promotion refusal)"))
        self.replication_rto_seconds = self._reg(Gauge(
            "tpusim_replication_rto_seconds",
            "End-to-end recovery time objective of the last failover: "
            "leader-death detection to promoted-and-serving"))
        self.replication_role = self._reg(InfoGauge(
            "tpusim_replication_role_info",
            "Replication role of this process (labels: role = "
            "leader|follower|candidate|none)"))
        # live-twin overlay queries (ISSUE 19): what-if scenarios answered
        # against the device-resident carry behind a journal mark, plus the
        # multi-tenant residency ledger that evicts cold twins to their
        # checkpoints under HBM pressure
        self.overlay_queries = self._reg(LabeledCounter(
            "tpusim_overlay_queries_total",
            "What-if queries answered by a resident twin overlay "
            "(path = resident|follower)", "path"))
        self.overlay_fallback = self._reg(LabeledCounter(
            "tpusim_overlay_fallback_total",
            "Overlay-ineligible what-if queries routed to the staged path, "
            "by refusal reason", "reason"))
        self.overlay_latency = self._reg(Histogram(
            "tpusim_overlay_latency_microseconds",
            "Route-to-rollback walltime per resident-twin overlay query",
            _LATENCY_BUCKETS))
        self.tenant_evictions = self._reg(LabeledCounter(
            "tpusim_tenant_evictions_total",
            "Tenant twins evicted to their checkpoint directory", "reason"))
        self.tenant_restores = self._reg(Counter(
            "tpusim_tenant_restores_total",
            "Tenant twins restored on demand from checkpoint + WAL tail"))
        self.tenant_resident_bytes = self._reg(LabeledGauge(
            "tpusim_tenant_resident_bytes",
            "HBM bytes held by each tenant's resident twin (0 = evicted)",
            "tenant"))
        self.tenant_restore_latency = self._reg(Histogram(
            "tpusim_tenant_restore_latency_microseconds",
            "Checkpoint-load + WAL-tail-replay walltime per tenant restore",
            _LATENCY_BUCKETS))
        self.tenant_resident_twins = self._reg(Gauge(
            "tpusim_tenant_resident_twins",
            "Tenant twins currently resident in HBM (admitted - evicted)"))
        # one lock for whole-registry reads: /metrics and snapshot() see a
        # single consistent exposition even while runtime threads observe
        self._read_lock = threading.Lock()

    def _reg(self, metric):
        self._registry.append(metric)
        return metric

    def _all(self):
        return list(self._registry)

    def reset(self) -> None:
        for metric in self._all():
            metric.reset()

    def expose(self) -> str:
        """Prometheus text exposition format (the scrape body the reference
        would have served had it started its metrics server). Families are
        emitted in registration order; the registry-level lock makes one
        scrape a consistent snapshot relative to another reader."""
        lines: List[str] = []
        with self._read_lock:
            for metric in self._all():
                lines.extend(metric.expose())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, object]:
        """Compact JSON-able snapshot of every non-empty family; embedded
        in BENCH records so trajectory files say which path produced each
        number."""
        out: Dict[str, object] = {}
        with self._read_lock:
            for metric in self._all():
                if isinstance(metric, Histogram):
                    if metric.count:
                        out[metric.name] = {"count": metric.count,
                                            "sum": round(metric.total, 3)}
                elif isinstance(metric, LabeledHistogram):
                    if metric.children:
                        out[metric.name] = {
                            label: {"count": child.count,
                                    "sum": round(child.total, 3)}
                            for label, child in sorted(
                                metric.children.items())}
                elif isinstance(metric, (LabeledCounter, LabeledGauge)):
                    if metric.values:
                        out[metric.name] = dict(sorted(metric.values.items()))
                elif isinstance(metric, InfoGauge):
                    if metric.value:
                        out[metric.name] = dict(sorted(metric.labels.items()))
                else:
                    if metric.value:
                        out[metric.name] = metric.value
        return out


# module-level default registry, mirroring the Go package-level metrics +
# metrics.Register() sync.Once (metrics.go:95-109)
_default: Optional[SchedulerMetrics] = None
_default_lock = threading.Lock()


def register() -> SchedulerMetrics:
    global _default
    with _default_lock:
        if _default is None:
            _default = SchedulerMetrics()
        return _default


def since_in_microseconds(start: float) -> float:
    """metrics.go SinceInMicroseconds; start is a time.perf_counter() value."""
    return (time.perf_counter() - start) * 1e6

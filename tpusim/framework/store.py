"""In-memory resource store + LIFO pod queue.

Reference: pkg/framework/store/store.go — five keyed caches with per-resource
event handlers fired on Add/Update/Delete/Replace (:61-118,144-169), and the
PodQueue whose Pop takes the LAST element (:223-233) — the simulation feed is
LIFO, which is observable in placement order and therefore preserved.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from tpusim.api.types import ResourceType

# event types (client-go watch.EventType)
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

EventHandler = Callable[[str, object], None]  # (event_type, object)


class ResourceStore:
    """Reference: store.go:32-46 (interface) / :179-201 (impl)."""

    RESOURCES = (ResourceType.PODS, ResourceType.NODES,
                 ResourceType.PERSISTENT_VOLUME_CLAIMS,
                 ResourceType.PERSISTENT_VOLUMES, ResourceType.SERVICES)

    def __init__(self):
        self._caches: Dict[ResourceType, Dict[str, object]] = {
            r: {} for r in self.RESOURCES}
        self._handlers: Dict[ResourceType, List[EventHandler]] = {
            r: [] for r in self.RESOURCES}

    def resources(self) -> List[ResourceType]:
        return list(self._caches.keys())

    def register_event_handler(self, resource: ResourceType,
                               handler: EventHandler) -> None:
        self._handlers[resource].append(handler)

    def unregister_event_handler(self, resource: ResourceType,
                                 handler: EventHandler) -> None:
        """Detach a handler (no client-go analog — informers live as long as
        their store — but per-client consumers like FakeRESTClient.close()
        need it to avoid leaking dead closures on a shared store)."""
        try:
            self._handlers[resource].remove(handler)
        except ValueError:
            pass

    def _emit(self, resource: ResourceType, event: str, obj) -> None:
        for handler in self._handlers[resource]:
            handler(event, obj)

    def add(self, resource: ResourceType, obj) -> None:
        self._caches[resource][obj.key()] = obj
        self._emit(resource, ADDED, obj)

    def update(self, resource: ResourceType, obj) -> None:
        self._caches[resource][obj.key()] = obj
        self._emit(resource, MODIFIED, obj)

    def delete(self, resource: ResourceType, obj) -> None:
        self._caches[resource].pop(obj.key(), None)
        self._emit(resource, DELETED, obj)

    def list(self, resource: ResourceType) -> list:
        return list(self._caches[resource].values())

    def get(self, resource: ResourceType, key: str):
        """Returns (object, exists) like cache.Store.Get."""
        obj = self._caches[resource].get(key)
        return obj, obj is not None

    def replace(self, resource: ResourceType, objects: list) -> None:
        """store.go:144-169 — swap contents, emitting Added for each."""
        self._caches[resource] = {o.key(): o for o in objects}
        for o in objects:
            self._emit(resource, ADDED, o)


class PodQueue:
    """LIFO pod feed. Reference: store.go:213-240 — Pop() returns the *last*
    element, so a podspec expands into reverse-order scheduling."""

    def __init__(self, pods: Optional[list] = None):
        self._pods: list = list(pods or [])

    def push(self, pod) -> None:
        self._pods.append(pod)

    def pop(self):
        if not self._pods:
            return None
        return self._pods.pop()

    def take_matching(self, pred) -> list:
        """Remove and return every queued pod satisfying `pred`, in pop
        (LIFO) order — the gang gather: when a group member pops, its mates
        are pulled forward so the group decides as one unit."""
        taken = [p for p in reversed(self._pods) if pred(p)]
        if taken:
            self._pods = [p for p in self._pods if not pred(p)]
        return taken

    def __len__(self) -> int:
        return len(self._pods)

"""Emulation strategy: how a scheduling decision mutates cluster state.

Reference: pkg/framework/strategy/strategy.go:29-83 — the predictive strategy's
Add marks the pod Running and routes it through ResourceStore.Update so the
Modified event reaches the scheduler's cache; Update/Delete are unimplemented
upstream and raise here.
"""

from __future__ import annotations

from tpusim.api.types import Pod, ResourceType
from tpusim.framework.store import ResourceStore


class PredictiveStrategy:
    def __init__(self, store: ResourceStore):
        self._store = store

    def add(self, pod: Pod) -> None:
        """strategy.go:47-75: the pod must already carry its binding
        (spec.nodeName); phase goes Running and the store emits Modified."""
        if not pod.spec.node_name:
            raise ValueError("predictive strategy requires a bound pod (nodeName set)")
        pod.status.phase = "Running"
        self._store.update(ResourceType.PODS, pod)

    def update(self, pod: Pod) -> None:
        raise NotImplementedError("Not implemented yet")  # strategy.go:77-79

    def delete(self, pod: Pod) -> None:
        raise NotImplementedError("Not implemented yet")  # strategy.go:81-83

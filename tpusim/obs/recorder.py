"""Flight recorder: span/event timeline across the host/device boundary.

Every scheduling attempt — host phases (queue wait, predicates,
priorities, select host, preempt, assume, bind) and device work
(cluster compile, kernel dispatch per variant signature, AUTO
verify-then-trust transitions, victim-path selection) — lands on one
timeline, exported as Chrome ``trace_event`` JSON (loadable in
Perfetto / chrome://tracing) or a raw JSONL span stream.

Design constraints (ISSUE 2):

- **Zero-cost when disabled.** `span()` returns a falsy shared no-op
  singleton when no recorder is installed: no dict, no Span object, no
  per-pod allocation. Call sites guard argument construction with
  ``if sp:`` so label strings are never built on the disabled path.
- **Deterministic under an injected clock.** The recorder never calls
  `time.perf_counter` directly; the clock is a constructor argument so
  goldens can pin span structure byte-for-byte.

Counters/histograms do NOT live here — they land in the
`framework/metrics.py` registry (`tpusim_backend_*` families) so the
reference exposition surface stays unified; the `note_*` helpers below
bridge both sinks.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

from tpusim.framework import metrics as _metrics

PID = 1
# Stable Perfetto track ids per category.
_TIDS = {"host": 1, "device": 2, "tool": 3}


class _NoopSpan:
    """Shared do-nothing span; falsy so call sites can skip building args."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: object) -> bool:
        return False

    def set(self, key: str, value: Any) -> None:
        pass

    def end(self) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class Span:
    __slots__ = ("rec", "name", "cat", "t0", "args")

    def __init__(self, rec: "FlightRecorder", name: str, cat: str, t0: float):
        self.rec = rec
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.args: Optional[Dict[str, Any]] = None

    def __bool__(self) -> bool:
        return True

    def set(self, key: str, value: Any) -> None:
        if self.args is None:
            self.args = {}
        self.args[key] = value

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc: object) -> bool:
        self.end()
        return False

    def end(self) -> None:
        self.rec._finish(self)


class FlightRecorder:
    """Collects complete ('X') and instant ('i') trace events in memory.

    The timeline is a bounded ring (``max_events``): an always-on serve or
    stream process can run for days without growing host memory without
    bound. When the ring is full the OLDEST event is dropped and
    ``tpusim_obs_dropped_events_total`` increments, so an exported trace
    that lost its head says so on the scrape."""

    DEFAULT_MAX_EVENTS = 262_144

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 max_events: Optional[int] = None,
                 pid: Optional[int] = None, process_name: str = "tpusim"):
        self.clock: Callable[[], float] = clock or time.perf_counter
        self._epoch = self.clock()
        self.max_events = (self.DEFAULT_MAX_EVENTS if max_events is None
                           else max(1, int(max_events)))
        self.events: Deque[Dict[str, Any]] = deque(maxlen=self.max_events)
        self.dropped = 0
        self.dropped_by_category: Dict[str, int] = {}
        self.pid = PID if pid is None else int(pid)
        self.process_name = process_name
        # per-instance track registry: unknown categories get their own
        # Perfetto track (ISSUE 20) instead of piling onto the shared
        # "tool" lane — merged multi-process traces stay legible
        self._tids: Dict[str, int] = dict(_TIDS)
        # clock anchors for cross-process alignment (tools/trace_merge.py):
        # name -> recorder-relative microsecond reading of a shared instant
        self.anchors: Dict[str, float] = {}
        self._lock = threading.Lock()

    def _append(self, ev: Dict[str, Any]) -> None:
        # caller holds no lock; the ring drop + counter stay consistent
        with self._lock:
            if len(self.events) == self.max_events:
                cat = self.events[0].get("cat", "meta")
                self.dropped += 1
                self.dropped_by_category[cat] = \
                    self.dropped_by_category.get(cat, 0) + 1
                _metrics.register().obs_dropped_events.inc(cat)
            self.events.append(ev)

    def _tid(self, cat: str) -> int:
        tid = self._tids.get(cat)
        if tid is None:
            with self._lock:
                tid = self._tids.get(cat)
                if tid is None:
                    tid = max(self._tids.values()) + 1
                    self._tids[cat] = tid
        return tid

    # -- timestamps -------------------------------------------------------
    def _ts(self, t: float) -> float:
        return round((t - self._epoch) * 1e6, 3)

    def now_us(self) -> float:
        """Recorder-relative timestamp in microseconds — the clock domain
        shipped in replication hello frames for trace_merge alignment."""
        return self._ts(self.clock())

    def set_anchor(self, name: str, value: Optional[float] = None) -> None:
        """Pin a named clock-anchor reading (now by default); exported in
        ``otherData`` so trace_merge can shift this process's timeline."""
        self.anchors[name] = self.now_us() if value is None else value

    # -- trace-context stamping -------------------------------------------
    def _stamp(self, ev: Dict[str, Any]) -> None:
        """Attach the active TraceContext's ids to an event's args. One
        contextvar read per event — nothing when no context is active."""
        ctx = _current_trace()
        if ctx is not None:
            args = ev.get("args")
            if args is None:
                args = ev["args"] = {}
            args.setdefault("trace_id", ctx.trace_id)
            args.setdefault("span_id", ctx.span_id)

    # -- recording --------------------------------------------------------
    def span(self, name: str, cat: str = "host") -> Span:
        return Span(self, name, cat, self.clock())

    def _finish(self, span: Span) -> None:
        t1 = self.clock()
        ev: Dict[str, Any] = {
            "name": span.name,
            "cat": span.cat,
            "ph": "X",
            "ts": self._ts(span.t0),
            "dur": round((t1 - span.t0) * 1e6, 3),
            "pid": self.pid,
            "tid": self._tid(span.cat),
        }
        if span.args:
            ev["args"] = span.args
        self._stamp(ev)
        self._append(ev)

    def add_span(self, name: str, cat: str, t0: float, t1: float,
                 args: Optional[Dict[str, Any]] = None) -> None:
        """Record a span from explicit clock readings (e.g. queue wait)."""
        ev: Dict[str, Any] = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": self._ts(t0),
            "dur": round((t1 - t0) * 1e6, 3),
            "pid": self.pid,
            "tid": self._tid(cat),
        }
        if args:
            ev["args"] = args
        self._stamp(ev)
        self._append(ev)

    def instant(self, name: str, cat: str = "host",
                args: Optional[Dict[str, Any]] = None) -> None:
        ev: Dict[str, Any] = {
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "g",
            "ts": self._ts(self.clock()),
            "pid": self.pid,
            "tid": self._tid(cat),
        }
        if args:
            ev["args"] = args
        self._stamp(ev)
        self._append(ev)

    def _flow(self, ph: str, name: str, flow_id: str, cat: str,
              args: Optional[Dict[str, Any]] = None) -> None:
        ev: Dict[str, Any] = {
            "name": name,
            "cat": cat,
            "ph": ph,
            "id": str(flow_id),
            "ts": self._ts(self.clock()),
            "pid": self.pid,
            "tid": self._tid(cat),
        }
        if ph == "f":
            # bind to the enclosing slice's end, the Perfetto-recommended
            # terminator so arrows land on the consuming span
            ev["bp"] = "e"
        if args:
            ev["args"] = args
        self._stamp(ev)
        self._append(ev)

    def flow_start(self, name: str, flow_id: str, cat: str = "host",
                   args: Optional[Dict[str, Any]] = None) -> None:
        """Chrome flow start ('s'): the producing side of a cross-thread /
        cross-process hand-off. Matched to flow_end by (cat, id)."""
        self._flow("s", name, flow_id, cat, args)

    def flow_end(self, name: str, flow_id: str, cat: str = "host",
                 args: Optional[Dict[str, Any]] = None) -> None:
        """Chrome flow finish ('f'): the consuming side of the hand-off."""
        self._flow("f", name, flow_id, cat, args)

    def tail(self, limit: int = 100) -> List[Dict[str, Any]]:
        """The most recent events, oldest-first (the /debug/trace body)."""
        with self._lock:
            events = list(self.events)
        _metrics.register().trace_ring_events.set(len(events))
        if limit > 0:
            events = events[-limit:]
        return events

    # -- export -----------------------------------------------------------
    def to_chrome(self) -> Dict[str, Any]:
        with self._lock:
            events = list(self.events)
            tids = sorted(self._tids.items(), key=lambda kv: kv[1])
        meta = [
            {"name": "process_name", "ph": "M", "ts": 0, "pid": self.pid,
             "tid": 0, "args": {"name": self.process_name}},
        ]
        for cat, tid in tids:
            meta.append({"name": "thread_name", "ph": "M", "ts": 0,
                         "pid": self.pid, "tid": tid, "args": {"name": cat}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "otherData": {"pid": self.pid,
                              "process_name": self.process_name,
                              "anchors": dict(self.anchors)}}

    def to_chrome_json(self) -> str:
        return json.dumps(self.to_chrome(), sort_keys=True,
                          separators=(",", ":")) + "\n"

    def to_jsonl(self) -> str:
        with self._lock:
            events = list(self.events)
        return "".join(
            json.dumps(ev, sort_keys=True, separators=(",", ":")) + "\n"
            for ev in events)

    def write(self, path: str) -> None:
        """Chrome trace for ``.json``, raw span stream for ``.jsonl``."""
        text = self.to_jsonl() if path.endswith(".jsonl") else self.to_chrome_json()
        with open(path, "w") as f:
            f.write(text)


# -- trace-context bridge (lazy: tracectx imports this module) -----------

_tracectx: Any = None


def _current_trace() -> Any:
    global _tracectx
    if _tracectx is None:
        from tpusim.obs import tracectx
        _tracectx = tracectx
    return _tracectx.current()


# -- module-level active recorder ----------------------------------------

_active: Optional[FlightRecorder] = None


def install(rec: FlightRecorder) -> FlightRecorder:
    global _active
    _active = rec
    return rec


def uninstall() -> None:
    global _active
    _active = None


def get_recorder() -> Optional[FlightRecorder]:
    return _active


def span(name: str, cat: str = "host") -> Any:
    """A live Span when a recorder is installed, else the shared no-op.

    Deliberately takes no args kwargs: attach labels via ``span.set``
    inside an ``if sp:`` guard so the disabled path allocates nothing.
    """
    rec = _active
    if rec is None:
        return NOOP_SPAN
    return rec.span(name, cat)


def instant(name: str, cat: str = "host",
            args: Optional[Dict[str, Any]] = None) -> None:
    rec = _active
    if rec is not None:
        rec.instant(name, cat, args)


def flow_start(name: str, flow_id: str, cat: str = "host", site: str = "",
               args: Optional[Dict[str, Any]] = None) -> None:
    """Emit a flow start ('s') on the active recorder and count it under
    tpusim_trace_flows_total{site}; no-op when tracing is disabled."""
    rec = _active
    if rec is not None:
        rec.flow_start(name, flow_id, cat, args)
        if site:
            _metrics.register().trace_flows.inc(site)


def flow_end(name: str, flow_id: str, cat: str = "host",
             args: Optional[Dict[str, Any]] = None) -> None:
    rec = _active
    if rec is not None:
        rec.flow_end(name, flow_id, cat, args)


# -- telemetry bridges (metrics registry + recorder instants) ------------

def note_auto_transition(kind: str, sig: Optional[str] = None) -> None:
    """AUTO verify-then-trust transition: verify_pass/verify_fail/pin/
    trust/defer/discard_transient/discard_permanent."""
    _metrics.register().backend_auto_transitions.inc(kind)
    rec = _active
    if rec is not None:
        rec.instant("auto:" + kind, "device",
                    {"sig": sig} if sig is not None else None)


def note_route(route: str, pods: Optional[int] = None) -> None:
    """Batch execution route: fastscan/fastscan_interpret/xla_scan/
    xla_chunked/reference_fallback."""
    _metrics.register().backend_route.inc(route)
    rec = _active
    if rec is not None:
        rec.instant("route:" + route, "device",
                    {"pods": pods} if pods is not None else None)


def note_fast_fallback(reason: str, detail: Optional[str] = None) -> None:
    """plan_fast rejected a batch: `reason` is the low-cardinality blocker
    class (backend._fast_fallback_key), `detail` the full reason string
    (trace-only — too high-cardinality for a metric label)."""
    rec = _active
    if rec is not None:
        rec.instant("fallback:" + reason, "device",
                    {"why": detail} if detail is not None else None)


def note_victim_path(path: str) -> None:
    """Preemption victim-selection path: device/device_verified/host/
    fallback (mirrors jaxe.preempt.PREEMPT_CLASS_STATS)."""
    _metrics.register().backend_victim_path.inc(path)
    rec = _active
    if rec is not None:
        rec.instant("victim:" + path, "device")


def note_gang(event: str, args: Optional[Dict[str, Any]] = None) -> None:
    """A gang admission decision (tpusim/gang): admit/reject/rollback/
    release. The matching counters (gang_admitted/gang_rejected/
    gang_partial_rollback) are incremented by the caller, which knows the
    reason label; this bridge only emits the flight-recorder instant."""
    rec = _active
    if rec is not None:
        rec.instant("gang:" + event, "host", args)


def note_fault(kind: str, args: Optional[Dict[str, Any]] = None) -> None:
    """A chaos-injected fault: node_delete/node_cordon/node_flap/
    node_restore/pod_evict, watch_drop/watch_dup/watch_disconnect,
    device_exception/device_corrupt_*, invariant_violation."""
    _metrics.register().fault_injected.inc(kind)
    rec = _active
    if rec is not None:
        rec.instant("fault:" + kind, "host", args)


def note_breaker(name: str, transition: str, state_value: float,
                 detail: Optional[str] = None) -> None:
    """A dispatch circuit-breaker transition: open/half_open/reopen/close.
    Mirrors the live state into the tpusim_breaker_state gauge."""
    reg = _metrics.register()
    reg.breaker_transitions.inc(transition)
    reg.breaker_state.set(state_value)
    rec = _active
    if rec is not None:
        args: Dict[str, Any] = {"breaker": name}
        if detail:
            args["detail"] = detail
        rec.instant("breaker:" + transition, "device", args)


def note_serve(event: str, args: Optional[Dict[str, Any]] = None) -> None:
    """A scenario-fleet lifecycle point: admit/reject/bucket/flush/
    dispatch/decode. Request-scoped phases additionally open `serve:*`
    SPANS at the call sites (tpusim.serve.*) so a trace shows the
    admission -> bucket -> dispatch -> decode pipeline per request; these
    instants mark the zero-duration transitions between them."""
    rec = _active
    if rec is not None:
        rec.instant("serve:" + event, "host", args)


def note_recovery(event: str, args: Optional[Dict[str, Any]] = None) -> None:
    """A crash-recovery lifecycle point (stream.persist): checkpoint /
    replay. Latencies land in the tpusim_recovery_* histograms at the
    call sites; these instants mark the transitions, and the replay
    itself additionally runs under a `recover:replay` span."""
    rec = _active
    if rec is not None:
        rec.instant("recover:" + event, "host", args)


def note_serve_retry(reason: str,
                     args: Optional[Dict[str, Any]] = None) -> None:
    """The serve fleet retried work after a fault: device_fault (injected
    dispatch death), worker_death (the processing thread died mid-request
    and the request was requeued at most once)."""
    _metrics.register().serve_retry.inc(reason)
    rec = _active
    if rec is not None:
        rec.instant("serve_retry:" + reason, "host", args)


def note_serve_degraded(path: str,
                        args: Optional[Dict[str, Any]] = None) -> None:
    """A serve bucket was answered via a degraded path: breaker_open /
    retry_exhausted (host reference fallback) or verify_divergence (host
    results replaced suspect device output)."""
    _metrics.register().serve_degraded.inc(path)
    rec = _active
    if rec is not None:
        rec.instant("serve_degraded:" + path, "host", args)


def note_stream_restage(reason: str, detail: Optional[str] = None) -> None:
    """The stream runtime invalidated its device-resident state and paid a
    full restage: `reason` is the low-cardinality residency-miss class
    (cold_start/policy_plan_change/node_set/groups_dirty/scalar_set/
    new_signature/sig_evict/group_shape/interpod_delta/watch_expired/
    breaker_open/device_fault/verify_divergence/unsupported/recovered —
    the last classifying a crash-recovered session's first restage),
    `detail` trace-only context."""
    _metrics.register().stream_restage.inc(reason)
    rec = _active
    if rec is not None:
        rec.instant("restage:" + reason, "device",
                    {"why": detail} if detail is not None else None)


def note_stream_cycle(path: str, pods: Optional[int] = None) -> None:
    """One StreamSession scheduling cycle: stream_scan (O(delta) resident
    dispatch), pipelined (resident dispatch with deferred decode),
    restage_scan (full re-stage + dispatch), host (reference fallback under
    chaos/unsupported features), gang (multi-pod all-or-nothing group
    cycle via tpusim/gang), or no_nodes (empty cluster — nothing to
    dispatch)."""
    _metrics.register().stream_cycles.inc(path)
    rec = _active
    if rec is not None:
        rec.instant("stream:" + path, "device",
                    {"pods": pods} if pods is not None else None)


def note_slo(event: str, args: Optional[Dict[str, Any]] = None) -> None:
    """An SLO burn-rate threshold crossing (obs.slo): burn_start when the
    windowed burn rate rises to/above the alerting threshold, burn_end when
    it falls back under. The live burn rate itself is the
    tpusim_slo_burn_rate gauge; these instants put the crossings on the
    trace timeline."""
    rec = _active
    if rec is not None:
        rec.instant("slo:" + event, "host", args)


def note_watch_overflow(resource: str) -> None:
    """A watch stream died on buffer overflow (the "410 Gone" analog):
    the consumer must relist to resync."""
    _metrics.register().watch_overflow.inc(resource)
    rec = _active
    if rec is not None:
        rec.instant("watch_overflow", "host", {"resource": resource})


# -- jax.profiler bridge --------------------------------------------------

_annotation_cls: Any = None


def profiled(name: str) -> Any:
    """`jax.profiler.TraceAnnotation` context so XLA profiles line up
    with recorder spans; degrades to a null context without jax."""
    global _annotation_cls
    if _annotation_cls is None:
        try:
            from jax.profiler import TraceAnnotation
            _annotation_cls = TraceAnnotation
        except Exception:  # pragma: no cover - jax is a hard dep in practice
            import contextlib
            _annotation_cls = contextlib.nullcontext
    return _annotation_cls(name)

"""Distributed trace context: one causal id per request / cycle, fleet-wide.

A ``TraceContext`` is the propagation unit of ISSUE 20's distributed
tracing: a 16-hex ``trace_id`` naming the causal story (one serve
request, one stream cycle), a ``span_id`` naming the current hop, and
``parent_id`` linking back to the hop that spawned it.  Contexts cross

- **threads** by riding the object being handed over (the serve worker
  re-activates the context stashed on the ``WhatIfRequest``), and
- **processes** by riding the WAL-shipping wire frames: ``WalShipper``
  stamps each ``rec``/``ckpt`` frame with ``to_wire()`` and the
  ``FollowerTwin`` rebuilds the context with ``from_wire()`` so replay
  spans carry the leader's trace id.

Design constraints (same contract as the flight recorder):

- **Zero-cost when disabled.**  ``start()`` returns ``None`` unless a
  flight recorder is installed — the scheduling hot paths hold one
  module-attribute ``None``-check and allocate nothing.
- **Deterministic under an injected id source.**  Ids default to a
  per-process random nonce + counter; tests install a counting source
  via ``set_id_source`` so trace goldens are byte-stable.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
from typing import Any, Callable, Dict, Optional

from tpusim.obs import recorder as _flight


class TraceContext:
    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def child(self) -> "TraceContext":
        """A new hop inside the same trace (this hop becomes the parent)."""
        return TraceContext(self.trace_id, _next_id(), self.span_id)

    def to_wire(self) -> Dict[str, str]:
        """The frame-field schema shipped in WAL ``rec``/``ckpt`` frames
        (documented in DEVIATIONS.md): ``{"tid": ..., "sid": ...}``."""
        return {"tid": self.trace_id, "sid": self.span_id}

    @classmethod
    def from_wire(cls, obj: Any) -> Optional["TraceContext"]:
        """Rebuild a remote context from a frame field; None on anything
        malformed — a follower must never die on a bad trace stamp."""
        if not isinstance(obj, dict):
            return None
        tid, sid = obj.get("tid"), obj.get("sid")
        if not (isinstance(tid, str) and isinstance(sid, str)):
            return None
        return cls(tid, sid)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceContext(trace_id={self.trace_id!r}, "
                f"span_id={self.span_id!r}, parent_id={self.parent_id!r})")


# -- id source -------------------------------------------------------------

_id_lock = threading.Lock()
_id_source: Optional[Callable[[], str]] = None
_default_counter = itertools.count(1)
_process_nonce = os.urandom(4).hex()


def _default_ids() -> str:
    # 16 hex chars: process nonce (8) + monotonic counter (8) — unique
    # across the fleet's processes without coordination
    return f"{_process_nonce}{next(_default_counter) & 0xFFFFFFFF:08x}"


def set_id_source(source: Optional[Callable[[], str]]) -> None:
    """Install a deterministic id generator (tests); None restores the
    process-nonce default."""
    global _id_source
    with _id_lock:
        _id_source = source


def _next_id() -> str:
    source = _id_source
    return source() if source is not None else _default_ids()


# -- the active context ----------------------------------------------------

_current: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("tpusim_trace_context", default=None)


def current() -> Optional[TraceContext]:
    return _current.get()


def start(parent: Optional[TraceContext] = None) -> Optional[TraceContext]:
    """A fresh root context (or a child hop of ``parent``) — but ONLY when
    tracing is armed (a flight recorder is installed); None otherwise so
    the disabled path allocates nothing."""
    if _flight.get_recorder() is None:
        return None
    if parent is not None:
        return parent.child()
    return TraceContext(_next_id(), _next_id())


def attach(ctx: Optional[TraceContext]) -> Optional[contextvars.Token]:
    """Make ``ctx`` the current context; returns the token for detach().
    None ctx is a no-op (the disabled path)."""
    if ctx is None:
        return None
    return _current.set(ctx)


def detach(token: Optional[contextvars.Token]) -> None:
    if token is not None:
        _current.reset(token)


class activate:
    """``with activate(ctx): ...`` — scoped attach/detach; ctx may be None
    (disabled path: pure no-op)."""

    __slots__ = ("ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx
        self._token: Optional[contextvars.Token] = None

    def __enter__(self) -> Optional[TraceContext]:
        self._token = attach(self.ctx)
        return self.ctx

    def __exit__(self, *exc: object) -> bool:
        detach(self._token)
        self._token = None
        return False

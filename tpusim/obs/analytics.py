"""Cluster analytics plane: fleet-level telemetry from the twin (ISSUE 14).

PR 13's provenance made individual *decisions* observable; this module is
the fleet-state half: per-resource allocatable vs requested totals, a
fragmentation score (largest-free-slot vs total-free), feasible-node
counts, and top-k hottest/coldest nodes — all reduced **on-device** from
the resident twin's (Statics, Carry) columns by `kernels.analytics_reduce`
and decoded lazily here. The reduction is a separate post-scan dispatch
over arrays the scan already owns, so placement hashes are pinned by
construction and stream cycles pay O(1) extra dispatches.

The device kernel returns integers only (sums, maxes, counts, encoded
top-k keys); ratios are derived at decode time. `host_reduce` recomputes
the same integer ops in numpy so device-vs-host comparison is bit-exact —
`ClusterAnalytics.verify_against_host` is the contract the smoke variant
and tier-1 tests assert across backend/stream/serve routes.

Capture mirrors the provenance pattern exactly: a module-level active
instance behind one None-check on the hot path, lazy decode, a bounded
in-memory ring (`/analytics` on the obs server), and an append-only JSONL
sink (`--analytics-out`). With no instance installed the only cost at a
call site is the None-check.

Two always-on accounting registries ride along (they need no install,
because compiles and residency changes are cold-path by definition):

- HBM residency: components register a weakref'd byte/entry source
  (`register_hbm_source`) polled only at scrape/snapshot time; the
  `tenant` field is the attribution hook for ROADMAP item 2.
- Compile cost: `note_compile(site, signature, latency_us)` accumulates
  cumulative trace count x compile latency per plan signature; the
  per-signature table is surfaced in `/analytics` JSON (deliberately NOT
  as metric labels — signatures are unbounded, which the metrics lint
  now forbids), with bounded per-site counters on `/metrics`.
"""

from __future__ import annotations

import json
import sys
import threading
import time
import weakref
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from tpusim.framework.metrics import register
from tpusim.jaxe.packing import decode_topk_key, encode_topk_keys

UTIL_SCALE = 1_000_000
RESOURCES = ("cpu", "memory", "gpu", "ephemeral", "pods")


# -- numpy mirror of kernels._analytics_reduce_impl ------------------------

def host_reduce(inp, n_valid: int, k: int) -> Dict[str, np.ndarray]:
    """Recompute the device reduction with numpy, integer-for-integer.

    `inp` is an AnalyticsIn of host arrays (np.asarray'd leaves). Returns
    a dict keyed like AnalyticsStats fields; every value must equal the
    device output exactly, including top-k key order (keys are unique by
    construction, so a descending sort is deterministic)."""
    alloc = np.stack([np.asarray(inp.alloc_cpu, dtype=np.int64),
                      np.asarray(inp.alloc_mem, dtype=np.int64),
                      np.asarray(inp.alloc_gpu, dtype=np.int64),
                      np.asarray(inp.alloc_eph, dtype=np.int64),
                      np.asarray(inp.allowed_pods, dtype=np.int64)])
    used = np.stack([np.asarray(inp.used_cpu, dtype=np.int64),
                     np.asarray(inp.used_mem, dtype=np.int64),
                     np.asarray(inp.used_gpu, dtype=np.int64),
                     np.asarray(inp.used_eph, dtype=np.int64),
                     np.asarray(inp.pod_count, dtype=np.int64)])
    n = alloc.shape[1]
    mask = np.arange(n) < n_valid
    alloc = np.where(mask[None, :], alloc, 0)
    used = np.where(mask[None, :], used, 0)
    free = np.maximum(alloc - used, 0)

    util = np.where(alloc[:2] > 0,
                    (used[:2] * UTIL_SCALE) // np.maximum(alloc[:2], 1), 0)
    score = np.clip(np.maximum(util[0], util[1]), 0, UTIL_SCALE)
    # the SAME encode the device kernel runs (jaxe/packing.py) — parity by
    # shared source, not by duplicated shift constants
    idx = np.arange(n, dtype=np.int64)
    hot = encode_topk_keys(score, idx, mask)
    cold = encode_topk_keys(UTIL_SCALE - score, idx, mask)
    return {
        "alloc": alloc.sum(axis=1),
        "used": used.sum(axis=1),
        "free_sum": free.sum(axis=1),
        "free_max": free.max(axis=1),
        "headroom_nodes": (free > 0).sum(axis=1).astype(np.int64),
        "feasible_nodes": np.int64(((free[0] > 0) & (free[1] > 0)
                                    & (free[4] > 0)).sum()),
        "valid_nodes": np.int64(mask.sum()),
        "hot_keys": np.sort(hot)[::-1][:k],
        "cold_keys": np.sort(cold)[::-1][:k],
    }


def _decode_keys(keys: np.ndarray, names, hot: bool) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for key in keys.tolist():
        if key < 0:
            continue  # padding past n_valid
        score, idx = decode_topk_key(key)
        ppm = score if hot else UTIL_SCALE - score
        out.append({"node": names[idx] if names else idx,
                    "utilization_ppm": int(ppm)})
    return out


def decode_stats(stats, names=None) -> Dict[str, Any]:
    """One AnalyticsStats -> the JSON body (ratios derived here, from the
    kernel's integers, so the device never computes a float)."""
    alloc = np.asarray(stats.alloc).tolist()
    used = np.asarray(stats.used).tolist()
    free_sum = np.asarray(stats.free_sum).tolist()
    free_max = np.asarray(stats.free_max).tolist()
    headroom = np.asarray(stats.headroom_nodes).tolist()
    resources: Dict[str, Any] = {}
    for r, name in enumerate(RESOURCES):
        a, u, fs, fm = alloc[r], used[r], free_sum[r], free_max[r]
        resources[name] = {
            "allocatable": a, "requested": u,
            "free": fs, "largest_free": fm,
            "nodes_with_headroom": headroom[r],
            "utilization": (u / a) if a > 0 else None,
            "fragmentation": (1.0 - fm / fs) if fs > 0 else 0.0,
        }
    return {
        "nodes": {"valid": int(np.asarray(stats.valid_nodes)),
                  "feasible": int(np.asarray(stats.feasible_nodes))},
        "resources": resources,
        "hot_nodes": _decode_keys(np.asarray(stats.hot_keys), names, True),
        "cold_nodes": _decode_keys(np.asarray(stats.cold_keys), names, False),
    }


class _Sample:
    __slots__ = ("stats", "source", "cycle", "ts", "seq", "names",
                 "n_valid", "k", "inputs", "decoded")

    def __init__(self, stats, source, cycle, ts, seq, names, n_valid, k,
                 inputs):
        self.stats = stats
        self.source = source
        self.cycle = cycle
        self.ts = ts
        self.seq = seq
        self.names = names
        self.n_valid = n_valid
        self.k = k
        self.inputs = inputs
        self.decoded = None


def _decode_sample(sample: _Sample) -> Dict[str, Any]:
    if sample.decoded is None:  # idempotent; benign under racing readers
        rec = {"seq": sample.seq, "ts": sample.ts, "source": sample.source}
        if sample.cycle is not None:
            rec["cycle"] = sample.cycle
        rec.update(decode_stats(sample.stats, sample.names))
        sample.decoded = rec
    return sample.decoded


class ClusterAnalytics:
    """Bounded ring of on-device aggregate samples + optional JSONL sink.

    capacity: samples retained in the ring (whole samples, one per
        cycle/dispatch). top_k: hottest/coldest depth requested from the
        kernel (clamped to the node count per shape). path: append target
        for `--analytics-out`. keep_inputs: retain the AnalyticsIn and
        n_valid per sample so `verify_against_host` can replay the
        reduction in numpy (tests/smoke only — it pins device arrays).
    sample_interval_s: minimum wall-clock gap between device captures
        (default 4 Hz). Telemetry consumers scrape at seconds granularity,
        but a tight CPU stream loop can run cycles every few ms — without
        the throttle the per-cycle jit-dispatch overhead alone busts the
        <2% budget. Throttled calls cost one clock read + compare. Set
        0.0 to capture every dispatch (the parity tests/smoke do).
    """

    def __init__(self, capacity: int = 512, top_k: int = 8,
                 path: Optional[str] = None, keep_inputs: bool = False,
                 sample_interval_s: float = 0.25):
        self.capacity = max(1, int(capacity))
        self.top_k = max(1, int(top_k))
        self.path = path
        self.keep_inputs = keep_inputs
        self.sample_interval_s = float(sample_interval_s)
        self._last_capture = float("-inf")  # first capture always fires
        self._ring: Deque[_Sample] = deque(maxlen=self.capacity)
        self._pending: List[_Sample] = []
        self._seq = 0
        self._lock = threading.Lock()
        self._file = open(path, "a") if path is not None else None

    # -- capture (hot path) ------------------------------------------------

    def want_sample(self) -> bool:
        """Throttle gate, checked BEFORE any column gathering or dispatch.
        Unsynchronized read: a racing duplicate sample is harmless and
        cheaper than locking the cycle loop."""
        if self.sample_interval_s <= 0.0:
            return True
        return time.monotonic() - self._last_capture >= self.sample_interval_s

    def capture_device(self, inp, n_valid: int, source: str,
                       cycle: Optional[int] = None, names=None,
                       mesh=None) -> None:
        """Dispatch the reduction on device columns and ring the result.

        The jit call is asynchronous — the returned stats are un-forced
        futures and decode happens at query/flush time, so the pipelined
        stream's overlap is preserved. Cost when enabled: one O(N)
        dispatch + a lock'd append.

        mesh: a node-sharded mesh when `inp` holds shard-even padded,
        node-sharded columns (the TPUSIM_SHARDS route) — the reduction then
        runs the two-level merge (per-shard fold + psum/pmax/all_gather of
        packed top-k keys), bit-identical to the single-device reduce."""
        from tpusim.jaxe.kernels import analytics_reduce

        if not self.want_sample():
            return
        self._last_capture = time.monotonic()
        n = int(inp.alloc_cpu.shape[0])
        k = max(1, min(self.top_k, n))
        if mesh is None:
            stats = analytics_reduce(inp, np.int64(n_valid), k=k)
        else:
            from tpusim.jaxe.kernels import analytics_reduce_sharded
            from tpusim.obs import recorder as flight

            with flight.span("shard:topk_collective", "device"):
                stats = analytics_reduce_sharded(mesh, inp,
                                                 np.int64(n_valid), k=k)
        inputs = None
        if self.keep_inputs:
            # host-copy NOW, and force a REAL copy: the carry columns are
            # donated into the next cycle's scan, and on the CPU backend
            # np.asarray can hand back a zero-copy view of the device
            # buffer — which the donated dispatch then scribbles over
            # (keep_inputs is a test/smoke mode; the production path
            # retains nothing and stays fully async)
            inputs = type(inp)(*(np.array(leaf, copy=True) for leaf in inp))
        sample = _Sample(stats, source, cycle, round(time.time(), 3), 0,
                         names, int(n_valid), k, inputs)
        with self._lock:
            sample.seq = self._seq
            self._seq += 1
            self._ring.append(sample)
            if self._file is not None:
                self._pending.append(sample)
        register().analytics_samples.inc()

    # -- query / export (cold path) ----------------------------------------

    def samples(self) -> List[_Sample]:
        with self._lock:
            return list(self._ring)

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            sample = self._ring[-1] if self._ring else None
        return _decode_sample(sample) if sample is not None else None

    def series(self, limit: int = 60) -> List[Dict[str, Any]]:
        """Most recent `limit` samples, decoded, oldest first."""
        with self._lock:
            tail = list(self._ring)[-max(0, limit):]
        return [_decode_sample(s) for s in tail]

    def snapshot(self) -> Dict[str, Any]:
        return {"enabled": True, "samples": self._seq,
                "capacity": self.capacity, "latest": self.latest(),
                "hbm": hbm_snapshot(), "compile": compile_snapshot()}

    def verify_against_host(self) -> List[str]:
        """Replay every retained reduction in numpy; return mismatch
        descriptions (empty = bit-exact). Requires keep_inputs=True."""
        problems: List[str] = []
        for sample in self.samples():
            if sample.inputs is None:
                problems.append(f"seq {sample.seq}: no inputs retained "
                                "(keep_inputs=False)")
                continue
            want = host_reduce(sample.inputs, sample.n_valid, sample.k)
            for field, expect in want.items():
                got = np.asarray(getattr(sample.stats, field))
                if not np.array_equal(got, expect):
                    problems.append(
                        f"seq {sample.seq} [{sample.source}] {field}: "
                        f"device {got.tolist()} != host {expect.tolist()}")
        return problems

    def flush(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        if self._file is None or not pending:
            return
        lines = [json.dumps(_decode_sample(s), sort_keys=True,
                            separators=(",", ":")) for s in pending]
        self._file.write("\n".join(lines) + "\n")
        self._file.flush()

    def close(self) -> None:
        self.flush()
        if self._file is not None:
            self._file.close()
            self._file = None


# -- module-level active instance (mirrors provenance.install) -------------

_active: Optional[ClusterAnalytics] = None


def install(log: ClusterAnalytics) -> ClusterAnalytics:
    global _active
    _active = log
    return log


def uninstall() -> None:
    global _active
    if _active is not None:
        _active.close()
    _active = None


def get() -> Optional[ClusterAnalytics]:
    return _active


def capture(statics, carry, n_valid: int, source: str,
            cycle: Optional[int] = None, names=None, mesh=None) -> None:
    """Reduce one (Statics, final Carry) pair; no-op (one None-check)
    when disabled. mesh routes node-sharded trees through the cross-shard
    two-level reduction (see capture_device)."""
    log = _active
    if log is None or not log.want_sample():
        return
    from tpusim.jaxe.kernels import analytics_in

    log.capture_device(analytics_in(statics, carry), n_valid, source,
                       cycle=cycle, names=names, mesh=mesh)


# -- HBM residency accounting (always on, polled at scrape time) -----------

def tree_nbytes(tree) -> int:
    """Total bytes of every array leaf in a nested tuple/list/dict —
    computed from shape x itemsize, so device futures are never forced."""
    if tree is None:
        return 0
    if isinstance(tree, (tuple, list)):
        return sum(tree_nbytes(leaf) for leaf in tree)
    if isinstance(tree, dict):
        return sum(tree_nbytes(leaf) for leaf in tree.values())
    shape = getattr(tree, "shape", None)
    dtype = getattr(tree, "dtype", None)
    if shape is None or dtype is None:
        return 0
    size = 1
    for dim in shape:
        size *= int(dim)
    return size * np.dtype(dtype).itemsize


_hbm_lock = threading.Lock()
_hbm_sources: List[Dict[str, Any]] = []


def register_hbm_source(component: str, owner, fn,
                        tenant: str = "default") -> None:
    """Register a residency source polled at snapshot time.

    `fn(owner) -> (bytes, entries)` (or `fn() -> ...` when owner is None
    for process-wide sources). Owners are weakref'd: a collected owner
    silently drops its source, so sessions/executors need no teardown
    hook. `tenant` attributes the bytes for ROADMAP item 2."""
    entry = {"component": component, "tenant": tenant, "fn": fn,
             "ref": weakref.ref(owner) if owner is not None else None}
    with _hbm_lock:
        _hbm_sources.append(entry)


def hbm_snapshot() -> Dict[str, Any]:
    """component -> {bytes, entries, tenants:{tenant: bytes}}; aggregates
    across live sources, pruning dead weakrefs as it goes."""
    with _hbm_lock:
        sources = list(_hbm_sources)
    out: Dict[str, Any] = {}
    dead: List[Dict[str, Any]] = []
    for entry in sources:
        owner = None
        if entry["ref"] is not None:
            owner = entry["ref"]()
            if owner is None:
                dead.append(entry)
                continue
        try:
            nbytes, entries = (entry["fn"](owner) if entry["ref"] is not None
                               else entry["fn"]())
        except Exception:
            continue  # a mid-teardown source must not break a scrape
        slot = out.setdefault(entry["component"],
                              {"bytes": 0, "entries": 0, "tenants": {}})
        slot["bytes"] += int(nbytes)
        slot["entries"] += int(entries)
        tenants = slot["tenants"]
        tenants[entry["tenant"]] = (tenants.get(entry["tenant"], 0)
                                    + int(nbytes))
    if dead:
        with _hbm_lock:
            for entry in dead:
                if entry in _hbm_sources:
                    _hbm_sources.remove(entry)
    return out


def _jit_cache_source() -> Tuple[int, int]:
    # executable sizes aren't exposed by jax, so bytes stay 0; entry
    # counts still bound the warm-retrace contract tests
    if "tpusim.jaxe.kernels" not in sys.modules:
        return (0, 0)  # jax never imported: nothing compiled, don't force it
    kernels = sys.modules["tpusim.jaxe.kernels"]
    entries = 0
    for name in ("schedule_scan", "schedule_scan_donated",
                 "schedule_scan_chunked", "apply_delta_donated",
                 "apply_statics_delta_donated", "analytics_reduce"):
        fn = getattr(kernels, name, None)
        try:
            entries += fn._cache_size()
        except (AttributeError, TypeError):
            pass
    return (0, entries)


register_hbm_source("compiled_executables", None, _jit_cache_source)


# -- compile-cost accounting (always on; compiles are cold by definition) --

_compile_lock = threading.Lock()
_compile_costs: Dict[Tuple[str, str], Dict[str, float]] = {}


def note_compile(site: str, signature, latency_us: float,
                 traces: int = 1) -> None:
    """Accumulate trace count x compile latency per (site, signature)."""
    key = (site, str(signature))
    with _compile_lock:
        slot = _compile_costs.setdefault(key, {"traces": 0, "total_us": 0.0})
        slot["traces"] += traces
        slot["total_us"] += float(latency_us)
    reg = register()
    reg.compile_traces.inc(site, traces)
    reg.compile_cost.inc(site, float(latency_us))


def compile_snapshot() -> Dict[str, Any]:
    """site -> {traces, total_us, signatures:{sig: {traces, total_us}}}."""
    with _compile_lock:
        items = [(key, dict(slot)) for key, slot in _compile_costs.items()]
    out: Dict[str, Any] = {}
    for (site, sig), slot in items:
        site_slot = out.setdefault(site, {"traces": 0, "total_us": 0.0,
                                          "signatures": {}})
        site_slot["traces"] += slot["traces"]
        site_slot["total_us"] += slot["total_us"]
        site_slot["signatures"][sig] = slot
    return out


def reset_compile_costs() -> None:
    """Tests/bench isolation only."""
    with _compile_lock:
        _compile_costs.clear()


# -- gauge refresh (scrape-time; zero hot-path cost) -----------------------

def refresh_gauges() -> None:
    """Fold the latest sample + HBM sources into the tpusim_cluster_* /
    tpusim_hbm_* gauge families. Called by the obs server before
    exposition; cheap enough for every scrape."""
    reg = register()
    for component, slot in hbm_snapshot().items():
        reg.hbm_resident_bytes.set(component, slot["bytes"])
        reg.hbm_cache_entries.set(component, slot["entries"])
    log = _active
    if log is None:
        return
    latest = log.latest()
    if latest is None:
        return
    for name, row in latest["resources"].items():
        if row["utilization"] is not None:
            reg.cluster_utilization.set(name, row["utilization"])
        reg.cluster_fragmentation.set(name, row["fragmentation"])
    reg.cluster_feasible_nodes.set(latest["nodes"]["feasible"])
    reg.cluster_nodes.set(latest["nodes"]["valid"])


def read_jsonl(path: str):
    """Stream records back from an --analytics-out file."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)

"""Chain-divergence forensics: ``tpusim audit`` (ISSUE 20).

Two runs that SHOULD have produced byte-identical decision streams — a
leader and its follower, two same-seed simulations, a run and its
recovery replay — occasionally don't (ROADMAP item 1 tracks one live
instance under ``TPUSIM_SHARDS=2``). The placement-hash chain says THAT
they diverged; this module answers WHERE and WHY:

1. **Bisect.** Fold each WAL's per-cycle digests — sha256 over the
   cycle's sorted bind list + emit hash — into a resumable chain (the
   same ``chain_fold`` discipline persist.py uses) and bisect on chain
   equality to the FIRST divergent cycle: O(log n) chain-head
   comparisons over the prefix, then one record-level diff at the
   divergence point.

2. **Replay + re-decide.** Rebuild the shared prefix (checkpoint
   snapshot + WAL replay, the recover_stream_session discipline) into a
   fresh session, then re-run the divergent cycle's batch through the
   scheduler with a ProvenanceLog requesting ``explain_k`` score-
   breakdown lanes — the per-decision forensic record: top-k candidate
   order, per-priority score parts, restage classification, and (when
   the checkpoint carries a shard layout) which shard owned the flipped
   node.

The module is read-only with respect to the audited directories: the
replay session journals nothing, and the report is a plain dict
(rendered by ``render_report`` for the CLI, JSON-dumpable for
artifacts).
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tpusim.engine.providers import DEFAULT_PROVIDER


@dataclass
class CycleDigest:
    """One cycle's comparable identity, extracted from a WAL."""

    cycle: int
    binds: List[Tuple[str, str]] = field(default_factory=list)
    emit_hash: Optional[str] = None
    batch_keys: List[str] = field(default_factory=list)
    events: int = 0

    def digest(self) -> str:
        body = json.dumps({"b": sorted(self.binds), "h": self.emit_hash,
                           "p": self.batch_keys, "e": self.events},
                          sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(body.encode()).hexdigest()


def extract_cycles(wal_path: str) -> Dict[int, CycleDigest]:
    """Per-cycle digest table for one WAL (violations are tolerated —
    a torn tail simply ends the comparable range early)."""
    from tpusim.stream.persist import read_wal

    records, _violations = read_wal(wal_path)
    cycles: Dict[int, CycleDigest] = {}

    def at(c: int) -> CycleDigest:
        if c not in cycles:
            cycles[c] = CycleDigest(cycle=c)
        return cycles[c]

    for _ofs, rec in records:
        k, c = rec.get("k"), int(rec.get("c", -1))
        if k == "batch":
            at(c).batch_keys = [
                f"{(o.get('metadata') or {}).get('namespace') or 'default'}"
                f"/{(o.get('metadata') or {}).get('name')}"
                for o in rec.get("pods", [])]
        elif k == "bind":
            at(c).binds = [(key, node) for key, node in rec.get("b", [])]
        elif k == "emit":
            at(c).emit_hash = rec.get("h")
        elif k == "ev":
            at(c).events += 1
    return cycles


def _chain_heads(cycles: Dict[int, CycleDigest],
                 upto: int) -> List[Tuple[int, str]]:
    """[(cycle, folded chain head AFTER that cycle)] for cycles 0..upto,
    in order — the bisection axis."""
    from tpusim.stream.persist import chain_fold

    heads: List[Tuple[int, str]] = []
    chain = ""
    for c in sorted(k for k in cycles if k <= upto):
        chain = chain_fold(chain, cycles[c].digest())
        heads.append((c, chain))
    return heads


def first_divergence(a: Dict[int, CycleDigest],
                     b: Dict[int, CycleDigest]) -> Optional[int]:
    """The first cycle whose digest differs between the two tables —
    found by bisecting the folded digest chain — or None when the shared
    range agrees everywhere. Cycles present on only one side count as
    divergent (a truncated run diverges at its first missing cycle)."""
    last = max(max(a, default=-1), max(b, default=-1))
    if last < 0:
        return None
    heads_a = dict(_chain_heads(a, last))
    heads_b = dict(_chain_heads(b, last))
    axis = sorted(set(a) | set(b))
    if heads_a.get(axis[-1]) == heads_b.get(axis[-1]) \
            and set(a) == set(b):
        return None
    lo, hi = 0, len(axis) - 1
    # invariant: some cycle in axis[lo..hi] diverges; chains agree
    # strictly below axis[lo]
    while lo < hi:
        mid = (lo + hi) // 2
        c = axis[mid]
        if heads_a.get(c) == heads_b.get(c) and c in a and c in b:
            lo = mid + 1
        else:
            hi = mid
    return axis[lo]


def _classify(da: Optional[CycleDigest],
              db: Optional[CycleDigest]) -> str:
    if da is None or db is None:
        return "missing_cycle"
    if da.batch_keys != db.batch_keys:
        return "batch"
    if da.events != db.events:
        return "events"
    if sorted(da.binds) != sorted(db.binds):
        return "bind"
    if da.emit_hash != db.emit_hash:
        return "emit"
    return "unknown"


def _bind_diff(da: CycleDigest, db: CycleDigest) -> List[Dict[str, Any]]:
    ma, mb = dict(da.binds), dict(db.binds)
    rows = []
    for key in sorted(set(ma) | set(mb)):
        if ma.get(key) != mb.get(key):
            rows.append({"pod": key, "a": ma.get(key), "b": mb.get(key)})
    return rows


def _shard_owner(layout: Optional[dict], node: Optional[str]
                 ) -> Optional[int]:
    """Which shard of the checkpointed node-mesh layout owns ``node``."""
    if not layout or not node:
        return None
    for shard, nodes in enumerate(layout.get("blocks") or []):
        if node in nodes:
            return shard
    owners = layout.get("owners")
    if isinstance(owners, dict):
        return owners.get(node)
    return None


def _replay_prefix(directory: str, divergent: int, *,
                   provider: str, policy=None):
    """Rebuild the host picture as of the divergent cycle's admission:
    checkpoint snapshot + WAL replay of every record strictly BEFORE
    cycle ``divergent``'s batch record (events labeled with the
    divergent cycle included — they precede the batch in host-picture
    order). Returns (session, batch_pods, ck) or (None, reason, None)
    when the directory cannot support a replay (checkpoint already past
    the divergence)."""
    from tpusim.api.snapshot import ClusterSnapshot
    from tpusim.api.types import Pod
    from tpusim.backends import bind_pod
    from tpusim.framework.store import MODIFIED
    from tpusim.jaxe.delta import IncrementalCluster
    from tpusim.stream.persist import (
        _LOADERS,
        StreamPersistence,
        read_wal,
    )
    from tpusim.stream.runtime import StreamSession

    ck_path = os.path.join(directory, StreamPersistence.CHECKPOINT)
    wal_path = os.path.join(directory, StreamPersistence.WAL)
    if not os.path.exists(ck_path):
        return None, "no checkpoint manifest to replay from", None
    with open(ck_path, "r", encoding="utf-8") as f:
        ck = json.load(f)
    if int(ck["cycle"]) > divergent:
        return None, (f"checkpoint already covers cycle {ck['cycle']} > "
                      f"divergent cycle {divergent}; re-run with "
                      "checkpoint_every=0 to audit"), None
    records, _ = read_wal(wal_path)
    inc = IncrementalCluster(ClusterSnapshot.from_obj(ck["snapshot"]))
    session = StreamSession(incremental=inc, provider=provider,
                            policy=policy)
    offset_limit = int(ck["wal_offset"])
    batch_pods: Optional[List] = None
    for ofs, rec in records:
        if ofs < offset_limit:
            continue
        k, c = rec["k"], int(rec["c"])
        if k == "batch":
            if c == divergent:
                batch_pods = [Pod.from_obj(o) for o in rec["pods"]]
                break
            continue
        if c >= divergent and k != "ev":
            break
        if k == "ev":
            inc.apply(rec["t"], _LOADERS[rec["r"]](rec["o"]))
        elif k == "bind":
            pods_by_key = {}
            for rec2 in (r for _o, r in records
                         if r["k"] == "batch" and int(r["c"]) == c):
                pods_by_key = {p.key(): p
                               for p in (Pod.from_obj(o)
                                         for o in rec2["pods"])}
            for key, node in rec["b"]:
                pod = pods_by_key.get(key)
                if pod is not None:
                    inc.apply(MODIFIED, bind_pod(pod, node))
    if batch_pods is None:
        return None, (f"cycle {divergent} has no batch record in "
                      f"{wal_path}"), None
    return session, batch_pods, ck


def _forensic_rerun(session, batch_pods, *, explain_k: int,
                    provider: str) -> Dict[str, Any]:
    """Re-decide the divergent batch, twice: once through the streaming
    session (restage/path classification + the parity placements), and
    once through the batch backend with explain lanes armed — the stream
    restage path does not thread ``explain_k`` into its scan, but the
    stream-vs-restage parity contract makes the backend's decisions (and
    therefore its top-k score-parts lanes) the same decisions."""
    from tpusim.obs import provenance

    out: Dict[str, Any] = {}
    if explain_k > 0:
        from tpusim.backends import get_backend

        snap = session.inc.to_snapshot()
        saved = provenance.get_log()
        log = provenance.ProvenanceLog(capacity=4096,
                                       top_k=int(explain_k))
        provenance._active = log
        try:
            backend = get_backend("jax", provider=provider)
            explained = backend.schedule(batch_pods, snap)
        finally:
            provenance._active = saved
        out["decisions"] = log.tail(limit=max(1, len(batch_pods)))
        out["explain_placements"] = sorted(
            (pl.pod.key(), pl.node_name)
            for pl in explained if pl.node_name)
    placements = session.schedule(batch_pods)
    out["path"] = dict(session.path_counts)
    out["restages"] = dict(session.restage_counts)
    out["placements"] = sorted((pl.pod.key(), pl.node_name)
                               for pl in placements if pl.node_name)
    if "explain_placements" in out \
            and out["explain_placements"] != out["placements"]:
        out["violations"] = ["explain-lane backend re-run disagrees with "
                             "the streaming re-run (parity breach)"]
    return out


def audit_wal_pair(dir_a: str, dir_b: str, *,
                   provider: str = DEFAULT_PROVIDER, policy=None,
                   explain_k: int = 3,
                   replay: bool = True) -> Dict[str, Any]:
    """The ``tpusim audit`` engine: bisect two WAL directories to the
    first divergent cycle and (when the checkpoints allow) re-run that
    cycle with explain lanes for a per-decision forensic diff."""
    from tpusim.stream.persist import StreamPersistence

    wal_a = os.path.join(dir_a, StreamPersistence.WAL)
    wal_b = os.path.join(dir_b, StreamPersistence.WAL)
    cycles_a = extract_cycles(wal_a)
    cycles_b = extract_cycles(wal_b)
    report: Dict[str, Any] = {
        "a": dir_a, "b": dir_b,
        "cycles_a": len(cycles_a), "cycles_b": len(cycles_b),
    }
    divergent = first_divergence(cycles_a, cycles_b)
    report["divergent_cycle"] = divergent
    if divergent is None:
        report["verdict"] = "identical"
        return report
    da, db = cycles_a.get(divergent), cycles_b.get(divergent)
    kind = _classify(da, db)
    report["verdict"] = "diverged"
    report["kind"] = kind
    if da is not None and db is not None:
        report["bind_diff"] = _bind_diff(da, db)
        report["emit_hash"] = {"a": da.emit_hash, "b": db.emit_hash}
        report["batch"] = da.batch_keys
    if not replay:
        return report
    session, batch_or_reason, ck = _replay_prefix(
        dir_a, divergent, provider=provider, policy=policy)
    if session is None:
        report["replay_skipped"] = batch_or_reason
        return report
    rerun = _forensic_rerun(session, batch_or_reason, explain_k=explain_k,
                            provider=provider)
    report["replay"] = rerun
    layout = (ck or {}).get("shard_layout")
    if report.get("bind_diff") and layout:
        for row in report["bind_diff"]:
            row["shard_a"] = _shard_owner(layout, row.get("a"))
            row["shard_b"] = _shard_owner(layout, row.get("b"))
    # which recorded side (if either) the deterministic re-decide agrees
    # with: the side that DISAGREES holds the corrupted/nondeterministic
    # record
    if da is not None and db is not None:
        ours = rerun["placements"]
        agrees_a = ours == sorted(da.binds)
        agrees_b = ours == sorted(db.binds)
        report["replay_agrees_with"] = (
            "both" if agrees_a and agrees_b else
            "a" if agrees_a else "b" if agrees_b else "neither")
    return report


def render_report(report: Dict[str, Any]) -> str:
    """Human-readable forensic report (the CLI's stdout body)."""
    lines = [f"audit: {report['a']}  vs  {report['b']}"]
    if report.get("verdict") == "identical":
        lines.append(f"chains identical across "
                     f"{report['cycles_a']} cycles")
        return "\n".join(lines) + "\n"
    d = report["divergent_cycle"]
    lines.append(f"FIRST DIVERGENT CYCLE: {d}  (kind: "
                 f"{report.get('kind', '?')})")
    eh = report.get("emit_hash") or {}
    if eh.get("a") != eh.get("b"):
        lines.append(f"  emit hash  a={str(eh.get('a'))[:16]}  "
                     f"b={str(eh.get('b'))[:16]}")
    for row in report.get("bind_diff", []):
        extra = ""
        if row.get("shard_a") is not None or row.get("shard_b") is not None:
            extra = (f"  [shard {row.get('shard_a')} -> "
                     f"{row.get('shard_b')}]")
        lines.append(f"  pod {row['pod']}: a={row.get('a')}  "
                     f"b={row.get('b')}{extra}")
    if "replay_skipped" in report:
        lines.append(f"  replay skipped: {report['replay_skipped']}")
    if "replay" in report:
        rr = report["replay"]
        lines.append(f"  re-decide agrees with: "
                     f"{report.get('replay_agrees_with', '?')}")
        diff_pods = {row["pod"] for row in report.get("bind_diff", [])}
        for rec in rr.get("decisions", []):
            if diff_pods and rec.get("pod") not in diff_pods:
                continue
            if rec.get("placed"):
                lines.append(f"    {rec['pod']} -> {rec.get('node')}")
                for cand in rec.get("top_k", [])[:5]:
                    parts = cand.get("parts") or {}
                    parts_s = " ".join(f"{k}={v}"
                                       for k, v in sorted(parts.items()))
                    lines.append(f"      candidate {cand['node']} "
                                 f"score={cand['score']}"
                                 + (f"  {parts_s}" if parts_s else ""))
            else:
                lines.append(f"    {rec['pod']} UNSCHEDULABLE: "
                             f"{rec.get('message')}")
        if rr.get("restages"):
            lines.append(f"  re-run restages: {rr['restages']}")
    return "\n".join(lines) + "\n"

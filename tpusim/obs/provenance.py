"""Decision provenance: structured why/why-not records per pod (ISSUE 13).

The reference simulator's product is an *explained* placement report —
per-pod success/failure with per-predicate reasons. The device path
already computes everything needed: the fused scan emits a per-pod
reason-bit histogram for failures, and `decode_placements` renders it
into `Placement.message` with text byte-identical to the host path's
`FitError.Error()`. This module captures those decoded decisions —
optionally enriched with the top-k score breakdown lanes the scan emits
under `EngineConfig.explain_k` — into:

- a bounded in-memory ring (`/debug/provenance` on the obs server), and
- an append-only JSONL file (`--explain-out`), queryable offline with
  `tpusim explain`.

Record schema (one JSON object per line; see DEVIATIONS.md):

    {"seq": 17, "source": "stream", "cycle": 3,
     "pod": "default/pod-41", "placed": false,
     "reason": "Unschedulable",
     "message": "0/9 nodes are available: 3 Insufficient cpu, ..."}

    {"seq": 18, "source": "backend", "pod": "default/pod-42",
     "placed": true, "node": "node-7",
     "top_k": [{"node": "node-7", "score": 13,
                "parts": {"LeastRequestedPriority": 6, ...}}, ...]}

Capture is deliberately lazy: `capture_batch` stores REFERENCES to the
already-built Placement list (and the device top-k arrays, when
present) and defers all string/dict assembly to export/query time, so
the hot scheduling loop pays one lock + one append per batch — the <2%
overhead budget bench configs 9/10 stamp. Zero-cost when disabled: call
sites hold a module-level None-check, exactly like the flight recorder.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Iterator, List, Optional

import numpy as np

from tpusim.framework.metrics import register


class _Batch:
    __slots__ = ("placements", "source", "cycle", "ts", "seq0", "topk")

    def __init__(self, placements, source, cycle, ts, seq0, topk):
        self.placements = placements
        self.source = source
        self.cycle = cycle
        self.ts = ts
        self.seq0 = seq0
        self.topk = topk


def _pod_name(pod) -> str:
    ns = pod.metadata.namespace or "default"
    return f"{ns}/{pod.metadata.name}"


def _decode_topk(topk: Dict[str, Any], i: int) -> List[Dict[str, Any]]:
    """Render pod i's top-k candidate rows; rows at the sentinel score are
    padding from fewer-than-k feasible nodes and are dropped."""
    names = topk["names"]
    part_names = topk["part_names"]
    sentinel = topk["sentinel"]
    out: List[Dict[str, Any]] = []
    idx = np.asarray(topk["idx"][i])
    scores = np.asarray(topk["scores"][i])
    parts = np.asarray(topk["parts"][i]) if topk["parts"] is not None else None
    for r in range(idx.shape[0]):
        score = int(scores[r])
        if score <= sentinel:
            continue
        row: Dict[str, Any] = {"node": names[int(idx[r])], "score": score}
        if parts is not None and part_names:
            row["parts"] = {part_names[j]: int(parts[r][j])
                            for j in range(len(part_names))}
        out.append(row)
    return out


def decode_batch(batch: _Batch) -> List[Dict[str, Any]]:
    """One Placement list -> provenance record dicts (the lazy half)."""
    records: List[Dict[str, Any]] = []
    for i, pl in enumerate(batch.placements):
        rec: Dict[str, Any] = {"seq": batch.seq0 + i, "ts": batch.ts,
                               "source": batch.source}
        if batch.cycle is not None:
            rec["cycle"] = batch.cycle
        rec["pod"] = _pod_name(pl.pod)
        if pl.node_name:
            rec["placed"] = True
            rec["node"] = pl.node_name
            if batch.topk is not None:
                rec["top_k"] = _decode_topk(batch.topk, i)
        else:
            rec["placed"] = False
            rec["reason"] = pl.reason or "Unschedulable"
            rec["message"] = pl.message
        records.append(rec)
    return records


class ProvenanceLog:
    """Bounded ring of recent decision batches + optional JSONL sink.

    capacity: max PODS (records) retained in the ring; oldest batches
        fall off whole. path: append-target for `--explain-out` (written
        on flush()/close(), formatted lazily). top_k: the score-breakdown
        depth the caller asked the engine for (advertised so backends can
        read one place; 0 = failures-only provenance).
    """

    def __init__(self, capacity: int = 4096, top_k: int = 0,
                 path: Optional[str] = None):
        self.capacity = max(1, int(capacity))
        self.top_k = int(top_k)
        self.path = path
        self._ring: Deque[_Batch] = deque()
        self._ring_pods = 0
        self._pending: List[_Batch] = []
        self._seq = 0
        self._lock = threading.Lock()
        self._file = open(path, "a") if path is not None else None

    # -- capture (hot path) ------------------------------------------------

    def capture_batch(self, placements, source: str,
                      cycle: Optional[int] = None,
                      topk: Optional[Dict[str, Any]] = None) -> None:
        if not placements:
            return
        batch = _Batch(placements, source, cycle, round(time.time(), 3),
                       0, topk)
        with self._lock:
            batch.seq0 = self._seq
            self._seq += len(placements)
            self._ring.append(batch)
            self._ring_pods += len(placements)
            while self._ring_pods > self.capacity and len(self._ring) > 1:
                self._ring_pods -= len(self._ring.popleft().placements)
            if self._file is not None:
                self._pending.append(batch)
        register().provenance_records.inc(len(placements))

    # -- query / export (cold path) ----------------------------------------

    def tail(self, limit: int = 100) -> List[Dict[str, Any]]:
        """Most recent `limit` records, decoded (the /debug/provenance
        body), oldest first."""
        with self._lock:
            batches = list(self._ring)
        records: List[Dict[str, Any]] = []
        for batch in reversed(batches):
            records[:0] = decode_batch(batch)
            if len(records) >= limit:
                break
        return records[-limit:]

    def flush(self) -> None:
        """Format + append pending batches to the JSONL sink."""
        with self._lock:
            pending, self._pending = self._pending, []
        if self._file is None or not pending:
            return
        lines = []
        for batch in pending:
            for rec in decode_batch(batch):
                lines.append(json.dumps(rec, sort_keys=True,
                                        separators=(",", ":")))
        self._file.write("\n".join(lines) + "\n")
        self._file.flush()

    def close(self) -> None:
        self.flush()
        if self._file is not None:
            self._file.close()
            self._file = None


# -- module-level active log (mirrors recorder.install) -------------------

_active: Optional[ProvenanceLog] = None


def install(log: ProvenanceLog) -> ProvenanceLog:
    global _active
    _active = log
    return log


def uninstall() -> None:
    global _active
    if _active is not None:
        _active.close()
    _active = None


def get_log() -> Optional[ProvenanceLog]:
    return _active


def capture(placements, source: str, cycle: Optional[int] = None,
            topk: Optional[Dict[str, Any]] = None) -> None:
    """Capture one decoded batch; no-op (one None-check) when disabled."""
    log = _active
    if log is not None:
        log.capture_batch(placements, source, cycle=cycle, topk=topk)


def requested_top_k() -> int:
    """The explain depth the active log asked for (0 when disabled or
    failures-only) — backends read this to decide whether to pay for the
    score-breakdown lanes."""
    log = _active
    return log.top_k if log is not None else 0


def read_jsonl(path: str) -> Iterator[Dict[str, Any]]:
    """Stream records back from an --explain-out file (tpusim explain)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)

"""Observability: flight recorder spans + backend telemetry helpers.

`tpusim.obs.recorder` holds the span/event subsystem; the metric
families it feeds live in `tpusim.framework.metrics` so the reference
registry stays the single exposition surface.
"""

from tpusim.obs.recorder import (  # noqa: F401
    FlightRecorder,
    get_recorder,
    install,
    uninstall,
)

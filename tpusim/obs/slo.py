"""Per-cycle latency SLO tracking for the always-on runtimes (ISSUE 13).

A `tpusim stream`/`tpusim serve` process armed with `--slo-target-ms`
judges every scheduling cycle against the target and publishes the
`tpusim_slo_*` family:

- `tpusim_slo_cycle_latency_target_microseconds` — the configured target
  (0 when no SLO is armed), so a scrape is self-describing.
- `tpusim_slo_cycles_total{verdict=ok|breach}` — cycles under/over target.
- `tpusim_slo_burn_rate` — windowed error-budget burn: the breach
  fraction over the last `window` cycles divided by the SLO's error
  budget (1 - objective). 1.0 means burning exactly at budget; a
  multiwindow alert rule fires on sustained values above ~1.

Burn-rate threshold crossings additionally land as `slo:burn_start` /
`slo:burn_end` instants on the flight recorder, so a trace shows WHEN the
budget started burning next to the cycles that caused it.

Same zero-cost-when-disabled shape as the recorder: `observe_cycle` is a
None-check when no tracker is installed.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Optional

from tpusim.framework.metrics import register
from tpusim.obs import recorder as flight


class SloTracker:
    """Judge per-cycle latencies against a fixed target.

    target_us: the per-cycle latency objective in microseconds.
    objective: the fraction of cycles that must meet the target
        (error budget = 1 - objective).
    window: cycles of history the burn rate is computed over.
    burn_alert: burn-rate threshold for the recorder instants.
    """

    def __init__(self, target_us: float, objective: float = 0.99,
                 window: int = 512, burn_alert: float = 1.0):
        if target_us <= 0:
            raise ValueError("SLO target must be positive")
        if not 0.0 < objective < 1.0:
            raise ValueError("SLO objective must be in (0, 1)")
        self.target_us = float(target_us)
        self.objective = float(objective)
        self.burn_alert = float(burn_alert)
        self._breaches: Deque[int] = deque(maxlen=max(1, int(window)))
        self._burning = False
        self._lock = threading.Lock()
        register().slo_target.set(self.target_us)

    def reset(self) -> None:
        """Clear the breach window and burn state — a role transition
        (follower promoted to leader, ISSUE 18) starts a clean error
        budget: the replayed cycles were never served to anyone, so
        counting them against the new leader's SLO would be noise."""
        with self._lock:
            self._breaches.clear()
            self._burning = False
        register().slo_burn_rate.set(0.0)

    @property
    def burn_rate(self) -> float:
        with self._lock:
            if not self._breaches:
                return 0.0
            frac = sum(self._breaches) / len(self._breaches)
        return frac / (1.0 - self.objective)

    def observe(self, path: str, latency_us: float) -> None:
        breach = latency_us > self.target_us
        reg = register()
        reg.slo_cycles.inc("breach" if breach else "ok")
        with self._lock:
            self._breaches.append(1 if breach else 0)
            frac = sum(self._breaches) / len(self._breaches)
            burn = frac / (1.0 - self.objective)
            crossed = None
            if burn >= self.burn_alert and not self._burning:
                self._burning, crossed = True, "burn_start"
            elif burn < self.burn_alert and self._burning:
                self._burning, crossed = False, "burn_end"
        reg.slo_burn_rate.set(burn)
        if crossed is not None:
            flight.note_slo(crossed, {"burn_rate": round(burn, 4),
                                      "path": path,
                                      "target_us": self.target_us})


# -- module-level active tracker (mirrors recorder.install) ---------------

_active: Optional[SloTracker] = None


def install(tracker: SloTracker) -> SloTracker:
    global _active
    _active = tracker
    return tracker


def uninstall() -> None:
    global _active
    _active = None
    register().slo_target.set(0.0)


def get_tracker() -> Optional[SloTracker]:
    return _active


def observe_cycle(path: str, latency_us: float) -> None:
    """Judge one cycle; no-op (a single None-check) when no SLO is armed."""
    tracker = _active
    if tracker is not None:
        tracker.observe(path, latency_us)

"""Live telemetry plane: the in-process HTTP endpoint (ISSUE 13).

`tpusim serve --listen HOST:PORT` and `tpusim stream --listen HOST:PORT`
start one of these on a daemon thread next to the runtime:

- `GET /metrics`  — the metrics registry in Prometheus/OpenMetrics text
  exposition format, rendered under the registry-level read lock so one
  scrape is a consistent snapshot.
- `GET /healthz`  — JSON liveness: breaker state (HTTP 503 while the
  device-dispatch breaker is OPEN), WAL record count, checkpoint
  freshness, admission-queue depth, SLO burn rate.
- `GET /debug/provenance` — the ring of recent decision-provenance
  records (`?limit=N`, default 100), JSON.
- `GET /analytics` — the cluster analytics plane (ISSUE 14): latest
  on-device utilization/fragmentation sample, HBM residency, compile
  costs, plus a bounded time-series ring (`?limit=N`, default 60).
  `tpusim top` renders this body live.
- `GET /debug/trace` — the flight recorder's bounded event ring
  (`?limit=N`, default 100), newest events last, plus the per-category
  drop counters (ISSUE 20). Bounded exactly like the provenance ring:
  a deque(maxlen) with drops counted, never an unbounded buffer.

Stdlib-only (http.server): the container bakes no HTTP framework, and a
scrape endpoint needs none. The handler reads shared state exclusively
through the metrics registry and the provenance ring — it holds no
reference to the runtime, so serve/stream/tests all wire it identically.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from tpusim.framework.metrics import register
from tpusim.obs import analytics
from tpusim.obs import provenance
from tpusim.obs import recorder as flight

METRICS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def health_payload() -> Tuple[int, dict]:
    """(http_status, body) for /healthz — 503 while the breaker is open."""
    reg = register()
    breaker = reg.breaker_state.value
    body = {
        "status": "breaker_open" if breaker >= 1.0 else "ok",
        "breaker_state": breaker,
        "wal_records": reg.recovery_wal_records.value,
        "queue_depth": reg.serve_queue_depth.value,
        "slo_burn_rate": reg.slo_burn_rate.value,
    }
    ckpt_ts = reg.recovery_last_checkpoint_timestamp.value
    body["checkpoint_age_s"] = (round(max(0.0, time.time() - ckpt_ts), 3)
                                if ckpt_ts else None)
    chain = reg.stream_chain_head
    if chain.value:
        body["chain_head"] = dict(chain.labels)
    # replication fields (ISSUE 18): role + lag so a probe of either side
    # of a leader/follower pair is self-describing. sys.modules lookup,
    # not an import — a process that never replicated must not pay the
    # stream package's import cost to report role "none".
    import sys

    _replicate = sys.modules.get("tpusim.stream.replicate")
    repl = (_replicate.get_status() if _replicate is not None
            else {"role": "none", "replication_lag_records": 0,
                  "last_shipped_seq": -1})
    body["role"] = repl.get("role", "none")
    body["replication_lag_records"] = repl.get("replication_lag_records", 0)
    body["last_shipped_seq"] = repl.get("last_shipped_seq", -1)
    return (503 if breaker >= 1.0 else 200), body


class _Handler(BaseHTTPRequestHandler):
    def _send(self, status: int, content_type: str, body: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (BaseHTTPRequestHandler API)
        parsed = urlparse(self.path)
        if parsed.path == "/metrics":
            # fold the latest analytics sample + HBM sources into the
            # tpusim_cluster_*/tpusim_hbm_* gauges so every scrape is live
            analytics.refresh_gauges()
            text = register().expose()
            self._send(200, METRICS_CONTENT_TYPE, text.encode())
        elif parsed.path == "/healthz":
            status, body = health_payload()
            self._send(status, "application/json",
                       (json.dumps(body, sort_keys=True) + "\n").encode())
        elif parsed.path == "/debug/provenance":
            try:
                limit = int(parse_qs(parsed.query).get("limit", ["100"])[0])
            except ValueError:
                limit = 100
            log = provenance.get_log()
            records = log.tail(limit) if log is not None else []
            self._send(200, "application/json",
                       (json.dumps(records) + "\n").encode())
        elif parsed.path == "/debug/trace":
            try:
                limit = int(parse_qs(parsed.query).get("limit", ["100"])[0])
            except ValueError:
                limit = 100
            rec = flight.get_recorder()
            if rec is None:
                body = {"enabled": False, "events": [], "dropped": 0,
                        "dropped_by_category": {}}
            else:
                body = {"enabled": True, "events": rec.tail(limit),
                        "dropped": rec.dropped,
                        "dropped_by_category": dict(rec.dropped_by_category)}
            self._send(200, "application/json",
                       (json.dumps(body, sort_keys=True) + "\n").encode())
        elif parsed.path == "/analytics":
            try:
                limit = int(parse_qs(parsed.query).get("limit", ["60"])[0])
            except ValueError:
                limit = 60
            log = analytics.get()
            if log is None:
                body = {"enabled": False,
                        "hbm": analytics.hbm_snapshot(),
                        "compile": analytics.compile_snapshot()}
            else:
                analytics.refresh_gauges()
                body = log.snapshot()
                body["series"] = log.series(limit)
            self._send(200, "application/json",
                       (json.dumps(body, sort_keys=True) + "\n").encode())
        else:
            self._send(404, "text/plain; charset=utf-8", b"not found\n")

    def log_message(self, fmt: str, *args: object) -> None:
        # scrapes every few seconds would flood stderr; stay quiet
        pass


class ObsServer:
    """The telemetry endpoint on a daemon thread; `address` is the bound
    (host, port) — pass port 0 to let the OS pick (tests do)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "ObsServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="tpusim-obs", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def parse_listen(spec: str) -> Tuple[str, int]:
    """'HOST:PORT' | ':PORT' | 'PORT' -> (host, port) for --listen."""
    spec = spec.strip()
    if ":" in spec:
        host, _, port = spec.rpartition(":")
        return host or "127.0.0.1", int(port)
    return "127.0.0.1", int(spec)


def start_server(listen: str) -> ObsServer:
    host, port = parse_listen(listen)
    return ObsServer(host, port).start()

"""Bounded admission queue for the scenario fleet.

Backpressure lives HERE, not in the batcher: a full queue rejects at submit
time (`tpusim_serve_rejected_total{reason="queue_full"}`) so callers see
overload immediately instead of watching latency grow without bound — or,
when the newcomer outranks a waiter, sheds the lowest-priority earliest
entry instead (`offer`; the fleet resolves the victim's future with
REJECT_SHED). Depth is mirrored into the `tpusim_serve_queue_depth` gauge
on every transition.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Optional, Tuple

from tpusim.framework.metrics import register


class AdmissionQueue:
    """Thread-safe bounded FIFO with priority-aware shedding. `put`/`offer`
    never block; `pop` optionally waits. Closing wakes every waiter; a
    closed queue still drains what it holds."""

    def __init__(self, maxsize: int = 256):
        if maxsize < 1:
            raise ValueError(f"maxsize={maxsize}: need at least 1")
        self.maxsize = maxsize
        self._items: deque = deque()   # (item, priority)
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def put(self, item: Any, priority: int = 0) -> bool:
        admitted, _ = self.offer(item, priority=priority, shed=False)
        return admitted

    def offer(self, item: Any, priority: int = 0,
              shed: bool = True) -> Tuple[bool, Optional[Any]]:
        """Admit `item`, returning (admitted, shed_victim). On a full
        queue with `shed`, the lowest-priority earliest waiter is evicted
        — but only when it ranks strictly BELOW the newcomer, so saturated
        same-priority traffic degrades to plain queue_full rejection
        instead of churning the queue."""
        with self._lock:
            if self._closed:
                return False, None
            if len(self._items) < self.maxsize:
                self._items.append((item, priority))
                register().serve_queue_depth.set(len(self._items))
                self._nonempty.notify()
                return True, None
            if not shed:
                return False, None
            # min() is stable: earliest entry among the lowest priority
            vi = min(range(len(self._items)),
                     key=lambda i: self._items[i][1])
            victim, victim_priority = self._items[vi]
            if victim_priority >= priority:
                return False, None
            del self._items[vi]
            self._items.append((item, priority))
            register().serve_queue_depth.set(len(self._items))
            self._nonempty.notify()
            return True, victim

    def pop(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Next item, or None when empty after `timeout` (0/None: no wait).

        The wait loops on a monotonic deadline: a single Condition.wait
        would surface spurious wakeups — and notifies stolen by a racing
        popper — as premature None returns, starving consumers that still
        had time left on the clock."""
        deadline = (time.monotonic() + timeout) if timeout else None
        with self._lock:
            while not self._items:
                if deadline is None or self._closed:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._nonempty.wait(remaining)
            item, _priority = self._items.popleft()
            register().serve_queue_depth.set(len(self._items))
            return item

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()

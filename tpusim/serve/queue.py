"""Bounded admission queue for the scenario fleet.

Backpressure lives HERE, not in the batcher: a full queue rejects at submit
time (`tpusim_serve_rejected_total{reason="queue_full"}`) so callers see
overload immediately instead of watching latency grow without bound. Depth is
mirrored into the `tpusim_serve_queue_depth` gauge on every transition.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Optional

from tpusim.framework.metrics import register


class AdmissionQueue:
    """Thread-safe bounded FIFO. `put` never blocks (False on full/closed);
    `pop` optionally waits. Closing wakes every waiter; a closed queue still
    drains what it holds."""

    def __init__(self, maxsize: int = 256):
        if maxsize < 1:
            raise ValueError(f"maxsize={maxsize}: need at least 1")
        self.maxsize = maxsize
        self._items: deque = deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def put(self, item: Any) -> bool:
        with self._lock:
            if self._closed or len(self._items) >= self.maxsize:
                return False
            self._items.append(item)
            register().serve_queue_depth.set(len(self._items))
            self._nonempty.notify()
            return True

    def pop(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Next item, or None when empty after `timeout` (0/None: no wait)."""
        with self._lock:
            if not self._items and timeout and not self._closed:
                self._nonempty.wait(timeout)
            if not self._items:
                return None
            item = self._items.popleft()
            register().serve_queue_depth.set(len(self._items))
            return item

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()

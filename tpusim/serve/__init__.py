"""Scenario fleet: a sharded what-if capacity-planning service.

An async front over `tpusim.jaxe.whatif`: requests are admitted through a
bounded queue, bucketed into fixed shape classes, and dispatched — full or
ghost-padded — as one device program per bucket, optionally shard_map'd over
a ("scenario", "node") mesh. See service.ScenarioFleet for the lifecycle.
"""

from tpusim.serve.batcher import Bucket, PendingEntry, ShapeClassBatcher
from tpusim.serve.executor import ServeExecutor
from tpusim.serve.queue import AdmissionQueue
from tpusim.serve.request import (
    REJECT_INVALID,
    REJECT_QUEUE_FULL,
    REJECT_SHUTDOWN,
    REJECT_UNKNOWN_SNAPSHOT,
    REJECT_UNSUPPORTED,
    ServeRejected,
    ShapeClass,
    WhatIfRequest,
    WhatIfResponse,
    shape_class_for,
)
from tpusim.serve.service import ScenarioFleet

__all__ = [
    "AdmissionQueue",
    "Bucket",
    "PendingEntry",
    "REJECT_INVALID",
    "REJECT_QUEUE_FULL",
    "REJECT_SHUTDOWN",
    "REJECT_UNKNOWN_SNAPSHOT",
    "REJECT_UNSUPPORTED",
    "ScenarioFleet",
    "ServeExecutor",
    "ServeRejected",
    "ShapeClass",
    "ShapeClassBatcher",
    "WhatIfRequest",
    "WhatIfResponse",
    "shape_class_for",
]

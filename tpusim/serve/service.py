"""ScenarioFleet: the async what-if capacity-planning service.

Request lifecycle (each phase is a `serve:` flight-recorder span, so a trace
shows admission -> bucket -> dispatch -> decode per request):

  submit()      admission — bounded-queue backpressure; rejects resolve the
                future immediately with a REJECT_* reason.
  stage         host staging (worker side): snapshot resolution, policy
                compile, compile_cluster — or a staged-cache hit.
  bucket        shape-class filing; a FULL bucket dispatches at once, a
                partial one waits for siblings until its deadline.
  dispatch      one device program per bucket (ghost-padded if partial),
                warm-executable + device-batch caches applied.
  decode        per-request placements; futures resolve with WhatIfResponse.

The worker thread (`start`/`stop`) gives the service its async shape; tests,
the CLI client, and bench drive the same pipeline synchronously via `pump`/
`drain` or the `run` convenience, which keeps every deadline decision under
the injected clock.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.framework.metrics import register
from tpusim.obs import slo, tracectx
from tpusim.obs.recorder import (
    flow_end,
    flow_start,
    note_serve,
    note_serve_retry,
    span,
)
from tpusim.serve.batcher import Bucket, PendingEntry, ShapeClassBatcher
from tpusim.serve.executor import ServeExecutor
from tpusim.serve.queue import AdmissionQueue
from tpusim.serve.request import (
    REJECT_DEADLINE,
    REJECT_QUEUE_FULL,
    REJECT_SHED,
    REJECT_SHUTDOWN,
    ServeRejected,
    WhatIfRequest,
    WhatIfResponse,
)


class ScenarioFleet:
    def __init__(self, provider: str = "DefaultProvider",
                 bucket_size: int = 4, flush_after_s: float = 0.05,
                 max_queue: int = 256, mesh: Optional[object] = None,
                 clock: Callable[[], float] = time.monotonic,
                 deadline_s: Optional[float] = None, max_retries: int = 2):
        self.executor = ServeExecutor(provider=provider, mesh=mesh,
                                      max_retries=max_retries, clock=clock)
        if mesh is not None and bucket_size % mesh.shape["scenario"] != 0:
            raise ValueError(
                f"bucket_size={bucket_size} does not divide over the "
                f"mesh's scenario axis ({mesh.shape['scenario']} shards)")
        self.queue = AdmissionQueue(max_queue)
        self.batcher = ShapeClassBatcher(bucket_size=bucket_size,
                                         flush_after_s=flush_after_s,
                                         clock=clock)
        self._clock = clock
        self.deadline_s = deadline_s  # fleet-wide default request deadline
        self._requeued: set = set()   # request_ids requeued after a worker
        self._thread: Optional[threading.Thread] = None  # death (at most 1x)
        self._stopping = threading.Event()

    def register_snapshot(self, ref: str, snapshot: ClusterSnapshot) -> str:
        return self.executor.register_snapshot(ref, snapshot)

    def attach_stream(self, session, ref: str = "live") -> str:
        """Serve `ref` from a live StreamSession's resident twin (ISSUE
        19): requests naming the ref ride the overlay fast path and fall
        back to staging the session's current host picture."""
        return self.executor.attach_twin(ref, session)

    def attach_replica(self, follower, ref: str = "live") -> None:
        """Serve `ref`'s overlay reads from a FollowerTwin replica first
        (standby HBM), the leader twin only when the replica refuses."""
        self.executor.attach_replica(ref, follower)

    # -- admission ---------------------------------------------------------

    def _reject(self, request: WhatIfRequest, reason: str,
                message: str) -> WhatIfResponse:
        register().serve_rejected.inc(reason)
        note_serve("reject", {"id": request.request_id, "reason": reason})
        self._end_flows(request)
        return WhatIfResponse(request_id=request.request_id, error=message,
                              rejected=reason)

    def _end_flows(self, request: WhatIfRequest) -> None:
        """Terminate any still-open trace hand-off arrows for a request
        that resolves off the happy path (shed, deadline, shutdown) — a
        flow start without its finish would dangle in the merged graph."""
        ctx = getattr(request, "trace", None)
        if ctx is None:
            return
        if getattr(request, "_queue_flow", False):
            request._queue_flow = False
            flow_end("serve:enqueue", f"{ctx.trace_id}:q")
        if getattr(request, "_bucket_flow", False):
            request._bucket_flow = False
            flow_end("serve:bucket", f"{ctx.trace_id}:b")

    def submit(self, request: WhatIfRequest) -> "Future[WhatIfResponse]":
        """Admit one request; the future resolves to a WhatIfResponse (a
        rejection resolves it immediately — submit never raises for
        per-request problems)."""
        future: "Future[WhatIfResponse]" = Future()
        # one TraceContext per request lifecycle (ISSUE 20): it rides the
        # request object across the worker-thread boundary, and the queue
        # hand-off is a flow arrow keyed on the trace id
        ctx = tracectx.start()
        if ctx is not None:
            request.trace = ctx
        with tracectx.activate(ctx), span("serve:admit") as sp:
            if sp:
                sp.set("id", request.request_id)
            admitted, victim = self.queue.offer(
                (request, future, self._clock()),
                priority=request.priority)
            if victim is not None:
                # a saturated queue shed its lowest-priority earliest
                # waiter to make room for this higher-priority newcomer
                v_request, v_future, _ = victim
                if not v_future.done():
                    v_future.set_result(self._reject(
                        v_request, REJECT_SHED,
                        f"shed by higher-priority {request.request_id} "
                        f"(priority {request.priority} > "
                        f"{v_request.priority}) on a full queue"))
            if not admitted:
                reason = (REJECT_SHUTDOWN if self.queue.closed
                          else REJECT_QUEUE_FULL)
                future.set_result(self._reject(
                    request, reason,
                    "fleet is shutting down" if reason == REJECT_SHUTDOWN
                    else f"admission queue full ({self.queue.maxsize})"))
            else:
                note_serve("admit", {"id": request.request_id})
                if ctx is not None:
                    request._queue_flow = True
                    flow_start("serve:enqueue", f"{ctx.trace_id}:q",
                               site="serve")
        return future

    # -- pipeline ----------------------------------------------------------

    def _deadline_for(self, request: WhatIfRequest) -> Optional[float]:
        return (request.deadline_s if request.deadline_s is not None
                else self.deadline_s)

    def _expired(self, request: WhatIfRequest, admitted_at: float) -> bool:
        limit = self._deadline_for(request)
        return limit is not None and self._clock() - admitted_at > limit

    def _process(self, request: WhatIfRequest, future: Future,
                 admitted_at: float) -> None:
        # re-activate the admission-time TraceContext on this (worker)
        # thread and close the queue hand-off arrow before any span opens
        ctx = getattr(request, "trace", None)
        with tracectx.activate(ctx):
            if ctx is not None and getattr(request, "_queue_flow", False):
                request._queue_flow = False
                flow_end("serve:enqueue", f"{ctx.trace_id}:q")
            self._process_in_ctx(request, future, admitted_at, ctx)

    def _process_in_ctx(self, request: WhatIfRequest, future: Future,
                        admitted_at: float, ctx) -> None:
        if self._expired(request, admitted_at):
            # the request aged out waiting in the admission queue: reject
            # before paying for host staging
            future.set_result(self._reject(
                request, REJECT_DEADLINE,
                f"deadline {self._deadline_for(request)}s expired before "
                "staging"))
            return
        try:
            hit = self.executor.try_overlay(request)
            if hit is not None:
                # the live twin answered in O(scenario): resolve now —
                # overlay queries never bucket (nothing to batch; the
                # resident program already ran)
                result, warm, path = hit
                latency = self._clock() - admitted_at
                reg = register()
                reg.serve_request_latency.observe(
                    latency * 1e6,
                    exemplar=ctx.trace_id if ctx is not None else None)
                slo.observe_cycle("serve", latency * 1e6)
                note_serve("overlay_resolve", {"id": request.request_id,
                                               "path": path})
                future.set_result(WhatIfResponse(
                    request_id=request.request_id, result=result,
                    bucket_real=1, bucket_ghosts=0, compile_cache_hit=warm,
                    latency_s=latency, degraded=None))
                return
            with span("serve:stage") as sp:
                if sp:
                    sp.set("id", request.request_id)
                (staged, shape_class, plan_sig, cp,
                 hard_weight) = self.executor.stage(request)
        except ServeRejected as exc:
            future.set_result(self._reject(request, exc.reason, str(exc)))
            return
        entry = PendingEntry(request=request, staged=staged, future=future,
                             admitted_at=admitted_at,
                             shape_class=shape_class, plan_sig=plan_sig,
                             cp=cp, hard_weight=hard_weight)
        with span("serve:bucket"):
            full = self.batcher.add(entry)
        note_serve("bucket", {"id": request.request_id,
                              "shape": shape_class.describe()})
        if ctx is not None:
            # bucket -> dispatch hand-off: the entry may sit waiting for
            # shape-class siblings; the arrow lands on whichever dispatch
            # (or deadline rejection) finally consumes it
            request._bucket_flow = True
            flow_start("serve:bucket", f"{ctx.trace_id}:b", site="serve")
        if full is not None:
            self._dispatch(full)

    def _dispatch(self, bucket: Bucket) -> None:
        # entries whose deadline lapsed waiting for bucket siblings are
        # rejected here, not run: the bucket shrinks (ghosts grow) so the
        # survivors still dispatch through the same warm executable
        live = []
        for entry in bucket.entries:
            if self._expired(entry.request, entry.admitted_at):
                if not entry.future.done():
                    entry.future.set_result(self._reject(
                        entry.request, REJECT_DEADLINE,
                        f"deadline {self._deadline_for(entry.request)}s "
                        "expired waiting for a bucket"))
            else:
                live.append(entry)
        if not live:
            return
        if len(live) < len(bucket.entries):
            bucket = Bucket(key=bucket.key, size=bucket.size, entries=live)
        reg = register()
        reg.serve_batch_occupancy.observe(len(bucket.entries))
        # land every member's bucket arrow on this dispatch; the shared
        # device program then runs under the first member's context so the
        # dispatch/decode/degraded spans carry a resolvable trace id
        for entry in bucket.entries:
            self._end_flows(entry.request)
        lead = getattr(bucket.entries[0].request, "trace", None)
        try:
            with tracectx.activate(lead):
                results, warm = self.executor.dispatch(bucket)
        except Exception as exc:  # a bucket failure fails its members only
            for entry in bucket.entries:
                if not entry.future.done():
                    entry.future.set_result(WhatIfResponse(
                        request_id=entry.request.request_id,
                        error=f"{type(exc).__name__}: {exc}"))
            return
        now = self._clock()
        degraded = self.executor.last_path
        for entry, result in zip(bucket.entries, results):
            latency = now - entry.admitted_at
            entry_ctx = getattr(entry.request, "trace", None)
            reg.serve_request_latency.observe(
                latency * 1e6,
                exemplar=entry_ctx.trace_id if entry_ctx is not None
                else None)
            slo.observe_cycle("serve", latency * 1e6)
            if not entry.future.done():
                entry.future.set_result(WhatIfResponse(
                    request_id=entry.request.request_id, result=result,
                    bucket_real=len(bucket.entries),
                    bucket_ghosts=bucket.ghosts, compile_cache_hit=warm,
                    latency_s=latency, degraded=degraded))

    def _process_guarded(self, item) -> None:
        """_process with worker-death containment: an unexpected exception
        (a crashed worker, not a per-request rejection — _process resolves
        those itself) requeues the item AT MOST ONCE
        (`tpusim_serve_retry_total{reason="worker_death"}`); a second death
        resolves the future with the error, so no future is ever resolved
        twice and none is lost."""
        request, future, admitted_at = item
        try:
            self._process(request, future, admitted_at)
        except Exception as exc:
            if future.done():
                return
            if request.request_id not in self._requeued:
                self._requeued.add(request.request_id)
                note_serve_retry("worker_death",
                                 {"id": request.request_id,
                                  "error": f"{type(exc).__name__}: {exc}"})
                if self.queue.put(item, priority=request.priority):
                    return
            future.set_result(WhatIfResponse(
                request_id=request.request_id,
                error=f"{type(exc).__name__}: {exc}"))

    def _flush_due(self) -> None:
        for bucket in self.batcher.due():
            note_serve("flush", {"real": len(bucket.entries),
                                 "ghosts": bucket.ghosts})
            self._dispatch(bucket)

    # -- synchronous driving (tests, CLI client, bench) --------------------

    def pump(self) -> None:
        """Process everything already queued, then flush due buckets."""
        while True:
            item = self.queue.pop()
            if item is None:
                break
            self._process_guarded(item)
        self._flush_due()

    def drain(self) -> None:
        """pump() + dispatch every partial bucket regardless of deadline."""
        self.pump()
        for bucket in self.batcher.flush_all():
            self._dispatch(bucket)

    def run(self, requests: Sequence[WhatIfRequest]) -> List[WhatIfResponse]:
        """Synchronous convenience: submit all, drain, return responses in
        submission order."""
        futures = [self.submit(r) for r in requests]
        self.drain()
        return [f.result() for f in futures]

    # -- worker thread (the async service shape) ---------------------------

    def start(self) -> "ScenarioFleet":
        if self._thread is not None:
            raise RuntimeError("fleet already started")
        self._stopping.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="scenario-fleet", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stopping.is_set():
            deadline = self.batcher.next_deadline()
            timeout = (max(0.001, deadline - self._clock())
                       if deadline is not None else 0.05)
            item = self.queue.pop(timeout=timeout)
            if item is not None:
                self._process_guarded(item)
            self._flush_due()
        self.drain()

    def stop(self) -> None:
        """Stop admitting, finish what's queued (incl. partial buckets) —
        then sweep: whatever is STILL pending (a dead worker's leftovers,
        items the join timeout stranded) resolves REJECT_SHUTDOWN, so no
        submitted future is ever left unresolved."""
        self.queue.close()
        self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout=60)
            self._thread = None
        else:
            self.drain()
        leftovers = []
        while True:
            item = self.queue.pop()
            if item is None:
                break
            leftovers.append(item[:2])  # (request, future)
        leftovers.extend((e.request, e.future)
                         for b in self.batcher.flush_all()
                         for e in b.entries)
        for request, future in leftovers:
            if not future.done():
                future.set_result(self._reject(
                    request, REJECT_SHUTDOWN,
                    "fleet stopped before this request dispatched"))

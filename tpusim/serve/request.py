"""Scenario-fleet request/response types and shape classes.

A `WhatIfRequest` is one capacity question — "will these pods fit on this
cluster?" — against either an inline snapshot or a `snapshot_ref` registered
with the fleet (the device-resident snapshot cache). Requests are bucketed by
`ShapeClass`: a fixed (node, pod, axis-budget) padding target, each dimension
rounded up to a power of two, so every bucket of a class dispatches through
ONE warm executable instead of tracing a fresh program per request shape
(ROADMAP item 1: thousands of concurrent queries from a warm engine).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Pod
from tpusim.jaxe.whatif import WhatIfResult

# admission rejection reasons (tpusim_serve_rejected_total{reason})
REJECT_QUEUE_FULL = "queue_full"
REJECT_INVALID = "invalid"
REJECT_UNKNOWN_SNAPSHOT = "unknown_snapshot"
REJECT_UNSUPPORTED = "unsupported"
REJECT_SHUTDOWN = "shutdown"
REJECT_DEADLINE = "deadline"   # per-request deadline expired pre-dispatch
REJECT_SHED = "shed"           # evicted by a higher-priority newcomer


class ServeRejected(Exception):
    """A request the fleet will not run; `reason` is the low-cardinality
    metric label, str(exc) the human detail returned to the caller."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


_ids = itertools.count()


@dataclass
class WhatIfRequest:
    """One capacity query. `cache_key` is an optional caller-chosen identity
    for the (snapshot, pods) content: requests carrying one are eligible for
    the staged-scenario and device-batch caches (repeat queries skip host
    compile and re-upload entirely). Callers must not reuse a key for
    different content."""

    pods: List[Pod]
    snapshot: Optional[ClusterSnapshot] = None
    snapshot_ref: Optional[str] = None
    policy: Any = None
    cache_key: Optional[str] = None
    # deadline_s: max admission->dispatch age before the request is
    # rejected REJECT_DEADLINE instead of running (None: fleet default).
    # priority: higher outranks lower when the admission queue saturates —
    # a full queue sheds its lowest-priority earliest waiter (REJECT_SHED)
    # to admit a strictly higher-priority newcomer.
    deadline_s: Optional[float] = None
    priority: int = 0
    request_id: str = field(default_factory=lambda: f"req-{next(_ids)}")


@dataclass
class WhatIfResponse:
    request_id: str
    result: Optional[WhatIfResult] = None
    error: Optional[str] = None
    rejected: Optional[str] = None  # a REJECT_* reason, None if admitted
    bucket_real: int = 0    # real scenarios in the dispatched bucket
    bucket_ghosts: int = 0  # ghost-scenario padding the bucket carried
    compile_cache_hit: bool = False
    latency_s: float = 0.0  # admission -> decoded result
    # non-None when the bucket was answered via a degraded path under
    # chaos: breaker_open / retry_exhausted (host reference fallback) or
    # verify_divergence (host results replaced suspect device output)
    degraded: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.rejected is None and self.error is None


def _budget(n: int, floor: int = 4) -> int:
    """Next power of two >= n, floored — the shape-class rounding. The floor
    keeps the class count low for tiny scenarios (a 3-node and a 4-node
    cluster share an executable)."""
    return max(floor, 1 << max(0, (int(n) - 1).bit_length()))


@dataclass(frozen=True)
class ShapeClass:
    """A fixed padding target: node/pod extents plus every named non-node
    axis (signature tables, scalar resources, groups) from the kernels axis
    registries. Two requests in the same class produce byte-identical array
    SHAPES after padding, which is what lets them share one bucket and one
    warm executable."""

    n_nodes: int
    n_pods: int
    axes: Tuple[Tuple[str, int], ...]  # sorted (axis name, budget)

    @property
    def targets(self) -> Dict[str, int]:
        return dict(self.axes)

    def describe(self) -> str:
        return f"nodes<={self.n_nodes} pods<={self.n_pods}"


def shape_class_for(staged) -> ShapeClass:
    """Derive the ShapeClass of one staged scenario (whatif.StagedScenario)
    from its host trees — every axis the unifier would pad, rounded up to
    its power-of-two budget. Deterministic: a pure function of the staged
    array shapes."""
    from tpusim.jaxe.whatif import _axis_targets

    targets = _axis_targets([(staged.statics, staged.carry, staged.xs)])
    return ShapeClass(
        n_nodes=_budget(staged.statics.alloc_cpu.shape[0]),
        n_pods=_budget(staged.xs.req_cpu.shape[0]),
        axes=tuple(sorted((name, _budget(size))
                          for name, size in targets.items())))

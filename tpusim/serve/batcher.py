"""Shape-class batcher: group staged requests into fixed-shape buckets.

Bucketing is deterministic — a bucket's key is (ShapeClass, plan signature),
both pure functions of request content, and entries join buckets in arrival
order. A bucket dispatches when FULL (bucket_size entries: one device
program, maximum occupancy) or when its oldest entry has waited
`flush_after_s` (deadline flush: the partial bucket is padded with ghost
scenarios by the executor so the program shape never changes). The clock is
injected for deterministic deadline tests.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpusim.serve.request import ShapeClass, WhatIfRequest

BucketKey = Tuple[ShapeClass, Any]  # (shape class, policy plan signature)


@dataclass
class PendingEntry:
    """One admitted request staged to host trees, waiting for a bucket."""

    request: WhatIfRequest
    staged: Any  # whatif.StagedScenario
    future: Any  # concurrent.futures.Future[WhatIfResponse]
    admitted_at: float
    shape_class: ShapeClass
    plan_sig: Any
    cp: Any = None  # compiled policy (shared across the bucket)
    hard_weight: int = 10


@dataclass
class Bucket:
    key: BucketKey
    size: int  # the class's fixed scenario count (ghosts fill the gap)
    entries: List[PendingEntry] = field(default_factory=list)

    @property
    def ghosts(self) -> int:
        return self.size - len(self.entries)


class ShapeClassBatcher:
    def __init__(self, bucket_size: int = 4, flush_after_s: float = 0.05,
                 clock: Callable[[], float] = time.monotonic):
        if bucket_size < 1:
            raise ValueError(f"bucket_size={bucket_size}: need at least 1")
        self.bucket_size = bucket_size
        self.flush_after_s = flush_after_s
        self._clock = clock
        self._open: Dict[BucketKey, Bucket] = {}

    def pending(self) -> int:
        return sum(len(b.entries) for b in self._open.values())

    def add(self, entry: PendingEntry) -> Optional[Bucket]:
        """File the entry under its bucket key; returns the bucket when this
        entry FILLS it (caller dispatches), else None (it waits for siblings
        or the deadline)."""
        key = (entry.shape_class, entry.plan_sig)
        bucket = self._open.get(key)
        if bucket is None:
            bucket = self._open[key] = Bucket(key=key, size=self.bucket_size)
        bucket.entries.append(entry)
        if len(bucket.entries) >= self.bucket_size:
            del self._open[key]
            return bucket
        return None

    def _deadline(self, bucket: Bucket) -> float:
        return bucket.entries[0].admitted_at + self.flush_after_s

    def next_deadline(self) -> Optional[float]:
        """Earliest partial-bucket deadline (clock units), or None when
        nothing is waiting — the service loop's wait bound."""
        if not self._open:
            return None
        return min(self._deadline(b) for b in self._open.values())

    def due(self) -> List[Bucket]:
        """Remove and return every partial bucket whose oldest entry has
        waited past flush_after_s; the executor pads them with ghosts."""
        now = self._clock()
        ready = [key for key, b in self._open.items()
                 if now >= self._deadline(b)]
        return [self._open.pop(key) for key in ready]

    def flush_all(self) -> List[Bucket]:
        """Drain every open bucket regardless of deadline (shutdown path)."""
        buckets = list(self._open.values())
        self._open.clear()
        return buckets

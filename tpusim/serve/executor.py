"""Bucket executor: staging caches, warm executables, device dispatch.

Three cache tiers keep repeat traffic off the slow paths:

  staged-scenario cache — (cache_key, plan signature) -> host trees: repeat
      queries skip compile_cluster + policy-table builds (the host staging
      that dominates small-request latency).
  warm-executable bookkeeping — (ShapeClass, plan signature): every bucket
      of a class runs the SAME program shape, so jax's jit cache returns the
      compiled executable; the whatif compile counter proves it (the delta
      across a dispatch says whether XLA traced), and the outcome lands in
      `tpusim_serve_dispatch_total{path}` and each response's
      `compile_cache_hit`.
  device-batch cache — a bucket whose every member carries a cache_key keeps
      its stacked DEVICE arrays resident (LRU): an exact-repeat bucket skips
      padding, stacking, and re-upload entirely.

Dispatch runs the manual shard_map route when the executor holds a
("scenario", "node") mesh (sharding.make_scenario_mesh), else the
single-device vmap program. Ghost scenarios (replicas of the bucket's first
real entry) fill deadline-flushed partial buckets; decode only ever walks the
real entries, so ghosts cannot leak into responses.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.backends import ReferenceBackend, placement_hash
from tpusim.framework.metrics import register
from tpusim.jaxe import backend as _backend
from tpusim.jaxe import ensure_x64
from tpusim.jaxe.backend import _KNOWN_PROVIDERS
from tpusim.jaxe.whatif import (
    StagedScenario,
    WhatIfResult,
    _batched,
    _policy_prep,
    _scenario_program,
    _stack_host,
    _stage_scenario,
    _unify,
    batch_config,
    compile_count,
    decode_one,
)
from tpusim.jaxe.sharding import (
    mesh_kind,
    pad_node_axis,
    scenario_shardings,
    stage_tree,
)
from tpusim.obs import analytics
from tpusim.obs import provenance
from tpusim.obs.recorder import (
    note_serve,
    note_serve_degraded,
    note_serve_retry,
    span,
)
from tpusim.serve.batcher import Bucket
from tpusim.serve.request import (
    REJECT_INVALID,
    REJECT_UNKNOWN_SNAPSHOT,
    REJECT_UNSUPPORTED,
    ServeRejected,
    ShapeClass,
    WhatIfRequest,
    _budget,
    shape_class_for,
)


def _twin_session(twin):
    """The StreamSession behind a twin handle (a session itself, or a
    replicate.FollowerTwin wrapping one)."""
    return getattr(twin, "session", twin)


class ServeExecutor:
    def __init__(self, provider: str = "DefaultProvider",
                 mesh: Optional[object] = None,
                 max_staged: int = 128, max_device_batches: int = 8,
                 max_retries: int = 2, backoff_base_s: float = 0.05,
                 clock=None):
        if provider not in _KNOWN_PROVIDERS:
            raise KeyError(f"plugin {provider!r} has not been registered")
        if mesh is not None and mesh_kind(mesh) != "scenario":
            raise ValueError(
                "ServeExecutor shards over scenarios: pass a "
                "('scenario', 'node') mesh (sharding.make_scenario_mesh); "
                f"got axes {tuple(mesh.axis_names)!r}")
        ensure_x64()  # sentinel bits (62) and CPU nanos need int64 lanes
        self.provider = provider
        self.mesh = mesh
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.clock = clock  # a ChaosClock makes backoff deterministic
        # degraded path the LAST dispatch took (None: clean device answer);
        # the fleet copies this into each response's `degraded` field
        self.last_path: Optional[str] = None
        self._snapshots: Dict[str, ClusterSnapshot] = {}
        # id(policy) -> (policy, prep): the policy ref keeps the id stable
        self._policies: Dict[int, Tuple[Any, tuple]] = {}
        self._staged: OrderedDict = OrderedDict()  # (cache_key, sig) -> (staged, sc)
        self._max_staged = max_staged
        self._device_batches: OrderedDict = OrderedDict()
        self._max_device_batches = max_device_batches
        self._warm: Dict[Tuple[ShapeClass, Any], Dict[str, int]] = {}
        # live twins (ISSUE 19): snapshot_ref -> StreamSession answering
        # what-if requests as resident-carry overlays, plus optional
        # FollowerTwin read replicas serving the same ref from standby HBM
        self._twins: Dict[str, Any] = {}
        self._replicas: Dict[str, List[Any]] = {}
        self._overlay_shapes: Dict[str, set] = {}
        self.stats = {"dispatches": 0, "warm_hits": 0, "traces": 0,
                      "staged_hits": 0, "device_batch_hits": 0,
                      "overlay_hits": 0, "overlay_fallbacks": 0}
        # HBM residency accounting (ISSUE 14): byte/entry sources polled
        # only at scrape/snapshot time; weakref'd to this executor
        analytics.register_hbm_source(
            "serve_staged", self,
            lambda ex: (sum(analytics.tree_nbytes(
                (s.statics, s.carry, s.xs))
                for s, _sc in ex._staged.values()), len(ex._staged)))
        analytics.register_hbm_source(
            "serve_device_batches", self,
            lambda ex: (sum(analytics.tree_nbytes(built[1:])
                            for built in ex._device_batches.values()),
                        len(ex._device_batches)))

    # -- snapshot registry (the base clusters requests reference) ---------

    def register_snapshot(self, ref: str, snapshot: ClusterSnapshot) -> str:
        self._snapshots[ref] = snapshot
        return ref

    def snapshot_refs(self) -> List[str]:
        return list(self._snapshots)

    # -- live twins (ISSUE 19): resident-overlay dispatch ------------------

    def attach_twin(self, ref: str, session) -> str:
        """Install a live StreamSession as the resident twin behind `ref`:
        requests naming the ref are answered by an overlay query against
        the session's device-resident carry (O(scenario) per request),
        falling back to staging the session's CURRENT host picture when
        the overlay refuses. The session stays owned by its driver —
        queries interleave with its churn cycles without touching its WAL
        or cycle chain."""
        self._twins[ref] = session
        return ref

    def detach_twin(self, ref: str) -> None:
        self._twins.pop(ref, None)
        self._replicas.pop(ref, None)
        self._overlay_shapes.pop(ref, None)

    def attach_replica(self, ref: str, follower) -> None:
        """Route `ref`'s overlay reads through a FollowerTwin replica
        (stream/replicate): non-diverged standby HBM answers what-if
        queries first, the leader twin only when every replica refuses.
        A replica's answer trails the leader by the un-acked WAL tail —
        bounded staleness, the read-replica contract."""
        self._replicas.setdefault(ref, []).append(follower)

    def _overlay_plan_ok(self, session, request: WhatIfRequest) -> bool:
        # overlay answers ride the twin's compiled plan; a request naming
        # a different policy (or provider) needs the staged path
        if session.provider != self.provider:
            return False
        if request.policy is None:
            return session.policy is None
        return request.policy is session.policy

    def try_overlay(self, request: WhatIfRequest
                    ) -> Optional[Tuple[WhatIfResult, bool, str]]:
        """Answer a request against the live twin behind its snapshot_ref:
        (result, compile_cache_hit, path) with path resident|follower, or
        None when no twin is installed, the request pins its own plan, or
        every overlay refuses — the caller falls back to stage()."""
        ref = request.snapshot_ref
        if request.snapshot is not None or ref is None:
            return None
        twin = self._twins.get(ref)
        if twin is None:
            return None
        if not request.pods:
            raise ServeRejected(REJECT_INVALID,
                                "request carries an empty pod list")
        candidates = [(f, "follower") for f in self._replicas.get(ref, ())]
        candidates.append((twin, "resident"))
        with span("serve:overlay") as osp:
            if osp:
                osp.set("ref", ref)
            eligible = False
            for target, path in candidates:
                if not self._overlay_plan_ok(_twin_session(target), request):
                    continue
                eligible = True
                placements = target.overlay_query(request.pods)
                if placements is None:
                    continue
                scheduled = sum(1 for p in placements if p.node_name)
                result = WhatIfResult(
                    placements=placements, scheduled=scheduled,
                    unschedulable=len(placements) - scheduled)
                shapes = self._overlay_shapes.setdefault(ref, set())
                shape = (_budget(len(request.pods)), path)
                warm = shape in shapes
                shapes.add(shape)
                self.stats["overlay_hits"] += 1
                self.last_path = None
                register().serve_dispatch.inc("overlay")
                if osp:
                    osp.set("path", path)
                note_serve("overlay", {"path": path, "ref": ref,
                                       "pods": len(request.pods)})
                return result, warm, path
            if osp:
                osp.set("path", "fallback")
            if not eligible:
                register().overlay_fallback.inc("plan_mismatch")
            self.stats["overlay_fallbacks"] += 1
            return None

    # -- staging -----------------------------------------------------------

    def _policy(self, policy) -> tuple:
        if policy is None:
            return (None, False, False, 10)
        hit = self._policies.get(id(policy))
        if hit is not None and hit[0] is policy:
            return hit[1]
        try:
            prep = _policy_prep(policy, 10)
        except NotImplementedError as exc:
            raise ServeRejected(REJECT_UNSUPPORTED, str(exc)) from None
        except ValueError as exc:
            raise ServeRejected(REJECT_INVALID, str(exc)) from None
        self._policies[id(policy)] = (policy, prep)
        return prep

    def _resolve_snapshot(self, request: WhatIfRequest) -> ClusterSnapshot:
        """The base cluster a request runs against — inline snapshot, a
        live twin's CURRENT host picture, or a registered ref. Raises
        ServeRejected when none resolves."""
        if request.snapshot is not None:
            return request.snapshot
        if request.snapshot_ref is not None:
            twin = self._twins.get(request.snapshot_ref)
            if twin is not None:
                # staged fallback for a twin ref answers against the SAME
                # logical state the overlay would have (live, not the
                # snapshot the twin was born from)
                return _twin_session(twin).inc.to_snapshot()
            snapshot = self._snapshots.get(request.snapshot_ref)
            if snapshot is None:
                raise ServeRejected(
                    REJECT_UNKNOWN_SNAPSHOT,
                    f"snapshot ref {request.snapshot_ref!r} is not "
                    f"registered (known: {sorted(self._snapshots)})")
            return snapshot
        raise ServeRejected(REJECT_INVALID,
                            "request needs a snapshot or a snapshot_ref")

    def stage(self, request: WhatIfRequest):
        """Resolve + host-stage one request: (staged, shape_class, plan_sig,
        cp, hard_weight). Raises ServeRejected with a metric-ready reason."""
        if not request.pods:
            raise ServeRejected(REJECT_INVALID,
                                "request carries an empty pod list")
        snapshot = self._resolve_snapshot(request)
        cp, need_noexec, need_saa, hard_weight = self._policy(request.policy)
        # the what-if analog of the fast path's plan_signature: the policy
        # spec is the part of the compiled program identity requests choose
        plan_sig = (self.provider, cp.spec if cp is not None else None)
        # twin-backed requests resolve to a LIVE snapshot that changes
        # every cycle — memoizing the staged trees would serve stale state
        live = (request.snapshot is None
                and request.snapshot_ref in self._twins)
        memo_key = ((request.cache_key, plan_sig)
                    if request.cache_key is not None and not live else None)
        if memo_key is not None and memo_key in self._staged:
            staged, shape_class = self._staged[memo_key]
            self._staged.move_to_end(memo_key)
            self.stats["staged_hits"] += 1
            return staged, shape_class, plan_sig, cp, hard_weight
        try:
            staged = _stage_scenario(snapshot, request.pods, cp,
                                     need_noexec, need_saa)
        except ValueError as exc:
            raise ServeRejected(REJECT_INVALID, str(exc)) from None
        except NotImplementedError as exc:
            raise ServeRejected(REJECT_UNSUPPORTED, str(exc)) from None
        shape_class = shape_class_for(staged)
        if memo_key is not None:
            self._staged[memo_key] = (staged, shape_class)
            while len(self._staged) > self._max_staged:
                self._staged.popitem(last=False)
        return staged, shape_class, plan_sig, cp, hard_weight

    # -- dispatch ----------------------------------------------------------

    def _build_device_batch(self, bucket: Bucket):
        shape_class, _ = bucket.key
        targets = shape_class.targets
        entries = bucket.entries
        per_scenario = []
        for e in entries:
            statics, carry, xs = _unify(e.staged.statics, e.staged.carry,
                                        e.staged.xs, targets,
                                        shape_class.n_pods)
            statics, carry, _ = pad_node_axis(statics, carry,
                                              shape_class.n_nodes)
            per_scenario.append((carry, statics, xs))
        # ghost scenarios: replicas of the first real entry, never decoded
        while len(per_scenario) < bucket.size:
            per_scenario.append(per_scenario[0])
        config = batch_config(
            [e.staged.compiled for e in entries], self.provider,
            entries[0].cp, entries[0].hard_weight,
            n_saa_doms=max(e.staged.n_saa_doms for e in entries),
            num_scalars=targets.get("scalar"))
        host_carries, host_statics, host_xs = _stack_host(per_scenario)
        if self.mesh is not None:
            ca_sh, st_sh, xs_sh = scenario_shardings(self.mesh)
            carries = stage_tree(host_carries, ca_sh)
            statics_b = stage_tree(host_statics, st_sh)
            xs_b = stage_tree(host_xs, xs_sh)
        else:
            carries, statics_b, xs_b = (stage_tree(host_carries),
                                        stage_tree(host_statics),
                                        stage_tree(host_xs))
        return config, carries, statics_b, xs_b

    def _device_batch(self, bucket: Bucket):
        """(config, device trees), from the resident cache when the whole
        bucket is cache-keyed and has been dispatched before."""
        keys = [e.request.cache_key for e in bucket.entries]
        dkey = None
        if all(k is not None for k in keys):
            dkey = (bucket.key, tuple(keys), bucket.size)
            hit = self._device_batches.get(dkey)
            if hit is not None:
                self._device_batches.move_to_end(dkey)
                self.stats["device_batch_hits"] += 1
                return hit, True
        built = self._build_device_batch(bucket)
        if dkey is not None:
            self._device_batches[dkey] = built
            while len(self._device_batches) > self._max_device_batches:
                self._device_batches.popitem(last=False)
        return built, False

    def _dispatch_once(self, bucket: Bucket,
                       injector=None) -> Tuple[List[WhatIfResult], bool]:
        """Run one bucket as one device program; returns (results aligned
        with bucket.entries, compile_cache_hit). Ghost scenarios and padded
        pods are dropped here — decode walks only the real entries.

        injector: an armed chaos DeviceInjector. Scripted exceptions raise
        before the program runs; scripted corruptions mangle the device
        output, and the structural validation below (active only under an
        injector, mirroring JaxBackend's post-dispatch check) converts the
        detectable kind into a DeviceOutputError the breaker absorbs."""
        program_key = bucket.key
        self.stats["dispatches"] += 1
        sp = span("serve:dispatch")
        with sp:
            if sp:
                sp.set("real", len(bucket.entries))
                sp.set("ghosts", bucket.ghosts)
                sp.set("shape", program_key[0].describe())
            corrupt_kind = (injector.begin_dispatch()
                            if injector is not None else None)
            (config, carries, statics_b, xs_b), resident = \
                self._device_batch(bucket)
            seen = program_key in self._warm
            before = compile_count()
            program_start = time.perf_counter()
            if self.mesh is not None:
                choices_b, counts_b = _scenario_program(config, self.mesh)(
                    carries, statics_b, xs_b)
            else:
                choices_b, counts_b = _batched(config, carries, statics_b,
                                               xs_b)
            choices_b = np.asarray(choices_b)
            counts_b = np.asarray(counts_b)
            if corrupt_kind is not None:
                choices_b, counts_b = injector.corrupt(
                    corrupt_kind, choices_b, counts_b)
            if injector is not None:
                # structural validation: padded node axis bounds the valid
                # choice range; NaN reason counts are never legitimate
                from tpusim.chaos.engine import DeviceOutputError

                if choices_b.size and int(choices_b.max()) >= \
                        program_key[0].n_nodes:
                    raise DeviceOutputError(
                        f"device returned node choice {int(choices_b.max())}"
                        f" >= padded node count {program_key[0].n_nodes}")
                if counts_b.size and np.isnan(
                        np.asarray(counts_b, dtype=float)).any():
                    raise DeviceOutputError(
                        "device returned NaN unschedulability counts")
            traced = compile_count() - before
            if traced:
                # compile-cost accounting (ISSUE 14): the traced program's
                # walltime upper-bounds its compile cost (execution rides
                # along, but cold dispatches are compile-dominated)
                analytics.note_compile(
                    "serve",
                    f"{program_key[0].describe()}/plan={program_key[1]}",
                    (time.perf_counter() - program_start) * 1e6,
                    traces=traced)
            warm = seen and traced == 0
            stats = self._warm.setdefault(program_key,
                                          {"dispatches": 0, "traces": 0})
            stats["dispatches"] += 1
            stats["traces"] += traced
            self.stats["traces"] += traced
            if warm:
                self.stats["warm_hits"] += 1
            path = ("device_cache" if resident and warm
                    else "warm" if warm else "cold")
            register().serve_dispatch.inc(path)
            note_serve("dispatch", {"path": path,
                                    "real": len(bucket.entries),
                                    "ghosts": bucket.ghosts})
        with span("serve:decode"):
            results = [decode_one(e.request.pods, e.staged.compiled,
                                  choices_b[i], counts_b[i])
                       for i, e in enumerate(bucket.entries)]
        if provenance.get_log() is not None:
            for r in results:
                provenance.capture(r.placements, "serve")
        alog = analytics.get()
        if alog is not None:
            # serve analytics are PRE-bind: the vmapped program discards
            # per-scenario final carries, so each sample reduces the
            # scenario's staged base state (DEVIATIONS.md). Slices of the
            # batched device trees stay lazy; padded nodes past n_valid
            # are masked inside the kernel.
            from tpusim.jaxe.kernels import analytics_in

            a_in = analytics_in(statics_b, carries)
            for i, e in enumerate(bucket.entries):
                names = e.staged.compiled.statics.names
                alog.capture_device(
                    type(a_in)(*(leaf[i] for leaf in a_in)),
                    len(names), "serve", names=names)
        return results, warm

    # -- chaos-hardened dispatch ------------------------------------------

    def _host_results(self, bucket: Bucket) -> List[WhatIfResult]:
        """The host-reference answer for every real entry of a bucket — the
        degraded path (open breaker, exhausted retries) and the verification
        oracle. Byte-identical placement semantics to the device program."""
        results = []
        for e in bucket.entries:
            snapshot = self._resolve_snapshot(e.request)
            placements = ReferenceBackend(
                provider=self.provider,
                policy=e.request.policy).schedule(e.request.pods, snapshot)
            scheduled = sum(1 for p in placements if p.node_name)
            results.append(WhatIfResult(
                placements=placements, scheduled=scheduled,
                unschedulable=len(placements) - scheduled))
            provenance.capture(placements, "serve_host")
        return results

    def _degraded(self, bucket: Bucket,
                  path: str) -> Tuple[List[WhatIfResult], bool]:
        self.last_path = path
        note_serve_degraded(path, {"real": len(bucket.entries),
                                   "shape": bucket.key[0].describe()})
        return self._host_results(bucket), False

    def _backoff(self, attempts: int) -> None:
        """Exponential backoff between retries: base * 2^(attempt-1). Under
        an injected clock the delay advances simulated time (deterministic
        tests); a wall clock sleeps, capped so chaos fuzz stays fast."""
        delay = self.backoff_base_s * (2 ** (attempts - 1))
        if self.clock is not None and hasattr(self.clock, "advance"):
            self.clock.advance(delay)
        else:
            time.sleep(min(delay, 0.2))

    def dispatch(self, bucket: Bucket) -> Tuple[List[WhatIfResult], bool]:
        """_dispatch_once behind the process-wide chaos seam (jaxe.backend
        install_chaos; a transparent pass-through when unarmed). The
        contract mirrors JaxBackend.schedule: a denied or repeatedly
        faulted bucket degrades to the host reference pipeline (at-least-an
        -answer, never a hang), a half-open probe — and every dispatch
        under verify="all" — is host-verified before results are emitted,
        and each retry backs off exponentially under the injected clock."""
        self.last_path = None
        injector = _backend._CHAOS["injector"]
        breaker = _backend._CHAOS["breaker"]
        if injector is None and breaker is None:
            return self._dispatch_once(bucket, None)
        from tpusim.chaos.engine import DeviceFault

        attempts = 0
        while True:
            if breaker is not None and not breaker.allow():
                return self._degraded(bucket, "breaker_open")
            probing = breaker.probing if breaker is not None else False
            try:
                results, warm = self._dispatch_once(bucket, injector)
            except DeviceFault as exc:
                if breaker is not None:
                    breaker.record_failure(f"{type(exc).__name__}: {exc}")
                attempts += 1
                if attempts > self.max_retries:
                    return self._degraded(bucket, "retry_exhausted")
                note_serve_retry("device_fault",
                                 {"attempt": attempts,
                                  "real": len(bucket.entries),
                                  "error": str(exc)})
                self._backoff(attempts)
                continue
            if breaker is not None and (
                    probing or _backend._CHAOS["verify"] == "all"):
                expected = self._host_results(bucket)
                got = tuple(placement_hash(r.placements) for r in results)
                want = tuple(placement_hash(r.placements) for r in expected)
                if got != want:
                    # silent corruption: in-range but wrong — only the
                    # host parity digest catches it
                    breaker.record_failure("device/host what-if divergence")
                    self.last_path = "verify_divergence"
                    note_serve_degraded("verify_divergence",
                                        {"real": len(bucket.entries)})
                    return expected, warm
            if breaker is not None:
                breaker.record_success()
            return results, warm

"""Bucket executor: staging caches, warm executables, device dispatch.

Three cache tiers keep repeat traffic off the slow paths:

  staged-scenario cache — (cache_key, plan signature) -> host trees: repeat
      queries skip compile_cluster + policy-table builds (the host staging
      that dominates small-request latency).
  warm-executable bookkeeping — (ShapeClass, plan signature): every bucket
      of a class runs the SAME program shape, so jax's jit cache returns the
      compiled executable; the whatif compile counter proves it (the delta
      across a dispatch says whether XLA traced), and the outcome lands in
      `tpusim_serve_dispatch_total{path}` and each response's
      `compile_cache_hit`.
  device-batch cache — a bucket whose every member carries a cache_key keeps
      its stacked DEVICE arrays resident (LRU): an exact-repeat bucket skips
      padding, stacking, and re-upload entirely.

Dispatch runs the manual shard_map route when the executor holds a
("scenario", "node") mesh (sharding.make_scenario_mesh), else the
single-device vmap program. Ghost scenarios (replicas of the bucket's first
real entry) fill deadline-flushed partial buckets; decode only ever walks the
real entries, so ghosts cannot leak into responses.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.framework.metrics import register
from tpusim.jaxe import ensure_x64
from tpusim.jaxe.backend import _KNOWN_PROVIDERS
from tpusim.jaxe.whatif import (
    StagedScenario,
    WhatIfResult,
    _batched,
    _policy_prep,
    _scenario_program,
    _stack_host,
    _stage_scenario,
    _unify,
    batch_config,
    compile_count,
    decode_one,
)
from tpusim.jaxe.sharding import (
    mesh_kind,
    pad_node_axis,
    scenario_shardings,
    stage_tree,
)
from tpusim.obs.recorder import note_serve, span
from tpusim.serve.batcher import Bucket
from tpusim.serve.request import (
    REJECT_INVALID,
    REJECT_UNKNOWN_SNAPSHOT,
    REJECT_UNSUPPORTED,
    ServeRejected,
    ShapeClass,
    WhatIfRequest,
    shape_class_for,
)


class ServeExecutor:
    def __init__(self, provider: str = "DefaultProvider",
                 mesh: Optional[object] = None,
                 max_staged: int = 128, max_device_batches: int = 8):
        if provider not in _KNOWN_PROVIDERS:
            raise KeyError(f"plugin {provider!r} has not been registered")
        if mesh is not None and mesh_kind(mesh) != "scenario":
            raise ValueError(
                "ServeExecutor shards over scenarios: pass a "
                "('scenario', 'node') mesh (sharding.make_scenario_mesh); "
                f"got axes {tuple(mesh.axis_names)!r}")
        ensure_x64()  # sentinel bits (62) and CPU nanos need int64 lanes
        self.provider = provider
        self.mesh = mesh
        self._snapshots: Dict[str, ClusterSnapshot] = {}
        # id(policy) -> (policy, prep): the policy ref keeps the id stable
        self._policies: Dict[int, Tuple[Any, tuple]] = {}
        self._staged: OrderedDict = OrderedDict()  # (cache_key, sig) -> (staged, sc)
        self._max_staged = max_staged
        self._device_batches: OrderedDict = OrderedDict()
        self._max_device_batches = max_device_batches
        self._warm: Dict[Tuple[ShapeClass, Any], Dict[str, int]] = {}
        self.stats = {"dispatches": 0, "warm_hits": 0, "traces": 0,
                      "staged_hits": 0, "device_batch_hits": 0}

    # -- snapshot registry (the base clusters requests reference) ---------

    def register_snapshot(self, ref: str, snapshot: ClusterSnapshot) -> str:
        self._snapshots[ref] = snapshot
        return ref

    def snapshot_refs(self) -> List[str]:
        return list(self._snapshots)

    # -- staging -----------------------------------------------------------

    def _policy(self, policy) -> tuple:
        if policy is None:
            return (None, False, False, 10)
        hit = self._policies.get(id(policy))
        if hit is not None and hit[0] is policy:
            return hit[1]
        try:
            prep = _policy_prep(policy, 10)
        except NotImplementedError as exc:
            raise ServeRejected(REJECT_UNSUPPORTED, str(exc)) from None
        except ValueError as exc:
            raise ServeRejected(REJECT_INVALID, str(exc)) from None
        self._policies[id(policy)] = (policy, prep)
        return prep

    def stage(self, request: WhatIfRequest):
        """Resolve + host-stage one request: (staged, shape_class, plan_sig,
        cp, hard_weight). Raises ServeRejected with a metric-ready reason."""
        if not request.pods:
            raise ServeRejected(REJECT_INVALID,
                                "request carries an empty pod list")
        if request.snapshot is not None:
            snapshot = request.snapshot
        elif request.snapshot_ref is not None:
            snapshot = self._snapshots.get(request.snapshot_ref)
            if snapshot is None:
                raise ServeRejected(
                    REJECT_UNKNOWN_SNAPSHOT,
                    f"snapshot ref {request.snapshot_ref!r} is not "
                    f"registered (known: {sorted(self._snapshots)})")
        else:
            raise ServeRejected(REJECT_INVALID,
                                "request needs a snapshot or a snapshot_ref")
        cp, need_noexec, need_saa, hard_weight = self._policy(request.policy)
        # the what-if analog of the fast path's plan_signature: the policy
        # spec is the part of the compiled program identity requests choose
        plan_sig = (self.provider, cp.spec if cp is not None else None)
        memo_key = ((request.cache_key, plan_sig)
                    if request.cache_key is not None else None)
        if memo_key is not None and memo_key in self._staged:
            staged, shape_class = self._staged[memo_key]
            self._staged.move_to_end(memo_key)
            self.stats["staged_hits"] += 1
            return staged, shape_class, plan_sig, cp, hard_weight
        try:
            staged = _stage_scenario(snapshot, request.pods, cp,
                                     need_noexec, need_saa)
        except ValueError as exc:
            raise ServeRejected(REJECT_INVALID, str(exc)) from None
        except NotImplementedError as exc:
            raise ServeRejected(REJECT_UNSUPPORTED, str(exc)) from None
        shape_class = shape_class_for(staged)
        if memo_key is not None:
            self._staged[memo_key] = (staged, shape_class)
            while len(self._staged) > self._max_staged:
                self._staged.popitem(last=False)
        return staged, shape_class, plan_sig, cp, hard_weight

    # -- dispatch ----------------------------------------------------------

    def _build_device_batch(self, bucket: Bucket):
        shape_class, _ = bucket.key
        targets = shape_class.targets
        entries = bucket.entries
        per_scenario = []
        for e in entries:
            statics, carry, xs = _unify(e.staged.statics, e.staged.carry,
                                        e.staged.xs, targets,
                                        shape_class.n_pods)
            statics, carry, _ = pad_node_axis(statics, carry,
                                              shape_class.n_nodes)
            per_scenario.append((carry, statics, xs))
        # ghost scenarios: replicas of the first real entry, never decoded
        while len(per_scenario) < bucket.size:
            per_scenario.append(per_scenario[0])
        config = batch_config(
            [e.staged.compiled for e in entries], self.provider,
            entries[0].cp, entries[0].hard_weight,
            n_saa_doms=max(e.staged.n_saa_doms for e in entries),
            num_scalars=targets.get("scalar"))
        host_carries, host_statics, host_xs = _stack_host(per_scenario)
        if self.mesh is not None:
            ca_sh, st_sh, xs_sh = scenario_shardings(self.mesh)
            carries = stage_tree(host_carries, ca_sh)
            statics_b = stage_tree(host_statics, st_sh)
            xs_b = stage_tree(host_xs, xs_sh)
        else:
            carries, statics_b, xs_b = (stage_tree(host_carries),
                                        stage_tree(host_statics),
                                        stage_tree(host_xs))
        return config, carries, statics_b, xs_b

    def _device_batch(self, bucket: Bucket):
        """(config, device trees), from the resident cache when the whole
        bucket is cache-keyed and has been dispatched before."""
        keys = [e.request.cache_key for e in bucket.entries]
        dkey = None
        if all(k is not None for k in keys):
            dkey = (bucket.key, tuple(keys), bucket.size)
            hit = self._device_batches.get(dkey)
            if hit is not None:
                self._device_batches.move_to_end(dkey)
                self.stats["device_batch_hits"] += 1
                return hit, True
        built = self._build_device_batch(bucket)
        if dkey is not None:
            self._device_batches[dkey] = built
            while len(self._device_batches) > self._max_device_batches:
                self._device_batches.popitem(last=False)
        return built, False

    def dispatch(self, bucket: Bucket) -> Tuple[List[WhatIfResult], bool]:
        """Run one bucket as one device program; returns (results aligned
        with bucket.entries, compile_cache_hit). Ghost scenarios and padded
        pods are dropped here — decode walks only the real entries."""
        program_key = bucket.key
        self.stats["dispatches"] += 1
        sp = span("serve:dispatch")
        with sp:
            if sp:
                sp.set("real", len(bucket.entries))
                sp.set("ghosts", bucket.ghosts)
                sp.set("shape", program_key[0].describe())
            (config, carries, statics_b, xs_b), resident = \
                self._device_batch(bucket)
            seen = program_key in self._warm
            before = compile_count()
            if self.mesh is not None:
                choices_b, counts_b = _scenario_program(config, self.mesh)(
                    carries, statics_b, xs_b)
            else:
                choices_b, counts_b = _batched(config, carries, statics_b,
                                               xs_b)
            choices_b = np.asarray(choices_b)
            counts_b = np.asarray(counts_b)
            traced = compile_count() - before
            warm = seen and traced == 0
            stats = self._warm.setdefault(program_key,
                                          {"dispatches": 0, "traces": 0})
            stats["dispatches"] += 1
            stats["traces"] += traced
            self.stats["traces"] += traced
            if warm:
                self.stats["warm_hits"] += 1
            path = ("device_cache" if resident and warm
                    else "warm" if warm else "cold")
            register().serve_dispatch.inc(path)
            note_serve("dispatch", {"path": path,
                                    "real": len(bucket.entries),
                                    "ghosts": bucket.ghosts})
        with span("serve:decode"):
            results = [decode_one(e.request.pods, e.staged.compiled,
                                  choices_b[i], counts_b[i])
                       for i, e in enumerate(bucket.entries)]
        return results, warm

"""ChaosEngine: applies a FaultPlan against a live ClusterCapacity run.

Injection points, one per layer of the plan:

1. **Cluster churn** (`fire_boundary`) — the simulator calls it at every
   pod-attempt boundary; due churn events mutate the ResourceStore, so
   every downstream consequence rides the EXISTING event fabric: node
   DELETED → cache remove + whole-node equivalence-cache invalidation,
   pod DELETED → move_all_to_active_queue, bind-time Modified → cache
   confirm. Node deletion additionally clears nominations pointing at the
   dead node (queue.clear_nominations_for_node) and keeps the
   orchestrator's authoritative node list in sync.

2. **Fabric faults** (`FabricInjector`) — installed on the
   FakeRESTClient fan-out; classifies each (watcher, frame) delivery by a
   global event index into deliver/drop/dup/disconnect.

3. **Device faults** (`DeviceInjector`) — installed process-wide in
   jaxe.backend; scripts per-dispatch exceptions and corrupted outputs,
   which the dispatch circuit breaker must absorb.

Determinism: the engine never reads wall-clock. `ChaosClock` is a
manually-advanced monotonic counter threaded into PodBackoff (and
available for the flight recorder), advanced a fixed 1s per attempt
boundary, so backoff expiry — and therefore the retry order — is a pure
function of the plan.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set, Tuple

from tpusim.chaos.plan import ChurnEvent, FaultPlan
from tpusim.obs.recorder import note_fault

log = logging.getLogger(__name__)


class ChaosClock:
    """Injectable deterministic clock (the obs/recorder.py pattern): a
    float that only moves when advanced."""

    def __init__(self, start: float = 0.0, tick: float = 1.0):
        self.now = start
        self.tick = tick

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: Optional[float] = None) -> float:
        self.now += self.tick if dt is None else dt
        return self.now


class DeviceFault(RuntimeError):
    """Base of the device-fault family the dispatch circuit breaker
    absorbs. Only these trip the breaker — configuration errors and
    genuine bugs still propagate."""


class InjectedDeviceError(DeviceFault):
    """A scripted device-dispatch failure (the chaos analog of a dead
    accelerator tunnel mid-batch)."""


class DeviceOutputError(DeviceFault):
    """Structurally invalid device output: out-of-range node choices or
    NaN reason counts. Caught by the backend's post-dispatch validation
    regardless of verification mode."""


class ProcessCrash(RuntimeError):
    """A scripted whole-process death (the ``process_crash`` churn
    action). Deliberately NOT a DeviceFault: no breaker absorbs it and no
    retry survives it — it propagates out of the run, and the test
    harness "reboots" by recovering from the WAL + checkpoint pair
    (stream.persist). Raised immediately AFTER the targeted WAL record is
    durably written, the strictest crash model a journal can be fuzzed
    under."""


class DeviceInjector:
    """Scripted per-dispatch device faults, keyed by dispatch index."""

    def __init__(self, faults: Dict[int, str]):
        self.faults = dict(faults)
        self.dispatch_index = 0
        self.injected: List[Tuple[int, str]] = []

    def _take(self) -> Optional[str]:
        idx = self.dispatch_index
        self.dispatch_index += 1
        kind = self.faults.get(idx)
        if kind is not None:
            self.injected.append((idx, kind))
            note_fault("device_" + kind, {"dispatch": idx})
        return kind

    def begin_dispatch(self) -> Optional[str]:
        """Called at device-dispatch start. Raises for scripted exceptions;
        returns a corruption kind (applied post-scan) or None."""
        kind = self._take()
        if kind == "exception":
            raise InjectedDeviceError(
                f"chaos: injected device fault at dispatch "
                f"{self.dispatch_index - 1}")
        return kind

    @staticmethod
    def corrupt(kind: str, choices, counts):
        """Corrupt a scan result in place-ish (returns new arrays).

        corrupt_invalid: out-of-range node index + NaN-poisoned reason
        counts — structurally detectable. corrupt_silent: rotate in-range
        choices — only host verification can catch it."""
        import numpy as np

        choices = np.array(choices, copy=True)
        if kind == "corrupt_invalid":
            counts = np.asarray(counts, dtype=float).copy()
            if choices.size:
                choices[0] = 2 ** 30
            if counts.size:
                counts.flat[0] = float("nan")
            return choices, counts
        if kind == "corrupt_silent":
            if choices.size:
                # shift every decision by one "node": wrong but in-range
                choices = np.where(choices >= 0, (choices + 1) % max(
                    int(choices.max()) + 1, 1), choices)
            return choices, counts
        raise ValueError(f"unknown corruption kind {kind!r}")


class FabricInjector:
    """Classifies each watch-frame delivery by global event index."""

    def __init__(self, drop, dup, disconnect):
        self.drop: Set[int] = set(drop)
        self.dup: Set[int] = set(dup)
        self.disconnect: Set[int] = set(disconnect)
        self.event_index = 0
        self.injected: List[Tuple[int, str]] = []

    def on_event(self, resource: str, event_type: str) -> str:
        """Returns deliver|drop|dup|disconnect for this delivery."""
        idx = self.event_index
        self.event_index += 1
        if idx in self.drop:
            action = "drop"
        elif idx in self.dup:
            action = "dup"
        elif idx in self.disconnect:
            action = "disconnect"
        else:
            return "deliver"
        self.injected.append((idx, action))
        note_fault("watch_" + action,
                   {"event": idx, "resource": resource, "type": event_type})
        return action


class ChaosEngine:
    """Drives one FaultPlan against one ClusterCapacity run."""

    def __init__(self, plan: FaultPlan, clock: Optional[ChaosClock] = None):
        self.plan = plan.validate()
        self.clock = clock or ChaosClock()
        self.cc = None  # attached ClusterCapacity
        self.boundary = 0
        self.fired: List[Tuple[int, str, str]] = []   # (boundary, action, target)
        self.skipped: List[Tuple[int, str, str]] = [] # target vanished first
        self.violations: List[str] = []
        self.fed_keys: List[str] = []
        self.evicted_keys: Set[str] = set()
        self.requeued_keys: Set[str] = set()
        self.retries: Dict[str, int] = {}
        self.deleted_nodes: Set[str] = set()  # currently-deleted node names
        self._pending_restores: List[Tuple[int, object]] = []  # (boundary, Node)
        self._churn = sorted(self.plan.churn,
                             key=lambda ev: (ev.at, ev.action, ev.target))
        self.fabric_injector = (
            None if self.plan.fabric.empty() else FabricInjector(
                self.plan.fabric.drop, self.plan.fabric.dup,
                self.plan.fabric.disconnect))
        self.device_injector = (
            None if self.plan.device.empty() else DeviceInjector(
                self.plan.device.faults))
        # process_crash handler: the persistence layer (stream.persist)
        # registers one to arm itself; without a handler the event is
        # skipped like churn on a vanished target
        self.on_process_crash = None
        # fabric mirror: a FakeRESTClient + Reflector pair consuming the
        # run's store mutations THROUGH the fault injector — built lazily
        # at the first boundary (the store exists by then), audited at the
        # end for reconvergence with the authoritative store
        self._mirror_client = None
        self._mirrors: List[object] = []

    # -- wiring ------------------------------------------------------------

    def attach(self, cc) -> "ChaosEngine":
        """Bind to a ClusterCapacity (the simulator calls this from its
        constructor when built with chaos=...)."""
        self.cc = cc
        return self

    def note_fed(self, pod) -> None:
        key = pod.key()
        if key not in self.fed_keys:
            self.fed_keys.append(key)

    def _ensure_fabric_mirror(self) -> None:
        """Stand up the faulty-fabric consumer: a FakeRESTClient whose
        fan-out runs through the FabricInjector, mirrored by one Reflector
        per resource. The mirror is a pure observer — it proves that a
        consumer behind a lossy stream reconverges (via 410-triggered
        relists) to the authoritative store, which `audit_fabric` checks
        at the end of the run."""
        if self.fabric_injector is None or self._mirror_client is not None:
            return
        from tpusim.api.types import ResourceType
        from tpusim.framework.reflector import Reflector
        from tpusim.framework.restclient import FakeRESTClient

        self._mirror_client = FakeRESTClient(self.cc.resource_store)
        self._mirror_client.fault_injector = self.fabric_injector
        self._mirrors = [Reflector(self._mirror_client, rt)
                         for rt in (ResourceType.PODS, ResourceType.NODES)]

    def _sync_mirrors(self) -> None:
        for refl in self._mirrors:
            refl.sync()

    # -- attempt boundaries ------------------------------------------------

    def fire_boundary(self) -> int:
        """Apply every churn event due at the current boundary (plus any
        flap restores), advance the injected clock one tick, and return
        the number of events fired."""
        self._ensure_fabric_mirror()
        b = self.boundary
        fired = 0
        for when, node in list(self._pending_restores):
            if when <= b:
                self._restore_node(node)
                self._pending_restores.remove((when, node))
                fired += 1
        while self._churn and self._churn[0].at <= b:
            ev = self._churn.pop(0)
            self._apply(ev)
            fired += 1
        self.boundary += 1
        self.clock.advance()
        self._sync_mirrors()
        return fired

    def has_pending_churn(self) -> bool:
        return bool(self._churn or self._pending_restores)

    def flush(self) -> None:
        """Apply whatever the attempt loop never reached (the run drained
        first), so the plan's full end-state is what invariants audit."""
        while self._churn or self._pending_restores:
            self.fire_boundary()

    # -- churn actions -----------------------------------------------------

    def _apply(self, ev: ChurnEvent) -> None:
        action = {"node_delete": self._node_delete,
                  "node_cordon": self._node_cordon,
                  "node_flap": self._node_flap,
                  "pod_evict": self._pod_evict,
                  "process_crash": self._process_crash}[ev.action]
        if action(ev):
            self.fired.append((self.boundary, ev.action, ev.target))
            note_fault(ev.action,
                       {"target": ev.target, "boundary": self.boundary})
        else:
            self.skipped.append((self.boundary, ev.action, ev.target))
            log.info("chaos: %s %s skipped at boundary %d (target gone)",
                     ev.action, ev.target, self.boundary)

    def _process_crash(self, ev: ChurnEvent) -> bool:
        """Hand a scripted crash to the installed handler — the stream
        persistence layer arms itself to raise ProcessCrash at the
        targeted WAL record (``ev.target`` names the record kind,
        ``ev.at`` the cycle). Skipped, like churn on a vanished target,
        when nothing in this run handles crashes."""
        if self.on_process_crash is None:
            return False
        self.on_process_crash(ev)
        return True

    def _find_node(self, name: str):
        from tpusim.api.types import ResourceType

        node, ok = self.cc.resource_store.get(ResourceType.NODES, name)
        return node if ok else None

    def _node_delete(self, ev: ChurnEvent, flap: bool = False) -> bool:
        from tpusim.api.types import ResourceType

        node = self._find_node(ev.target)
        if node is None:
            return False
        # DELETED rides the store fabric: cache.remove_node + whole-node
        # equivalence-cache invalidation via the registered handlers
        self.cc.resource_store.delete(ResourceType.NODES, node)
        self.cc.nodes = [n for n in self.cc.nodes if n.name != node.name]
        self.cc._cached_node_infos.pop(node.name, None)
        self.deleted_nodes.add(node.name)
        # nominated-node cleanup: a nomination on a dead node is a promise
        # the cluster can no longer keep
        queue = self.cc.scheduling_queue
        cleared = queue.clear_nominations_for_node(node.name)
        for pod in cleared:
            pod.status.nominated_node_name = ""
        self._release_gang_members(node.name)
        if flap:
            self._pending_restores.append(
                (self.boundary + ev.restore_after, node))
        return True

    def _release_gang_members(self, node_name: str) -> None:
        """No partial gang bound: losing a node releases every gang with a
        member bound on it — all still-bound mates (on ANY node) are
        evicted through the same store mechanics as _pod_evict, so a
        surviving fraction can never masquerade as an admitted group. Fed
        members are re-fed for a fresh all-or-nothing attempt against the
        shrunken cluster."""
        from tpusim.api.types import ResourceType
        from tpusim.gang.group import gang_name

        store = self.cc.resource_store
        doomed = {gang_name(p) for p in store.list(ResourceType.PODS)
                  if gang_name(p) and p.spec.node_name == node_name}
        if not doomed:
            return
        released = 0
        for pod in list(store.list(ResourceType.PODS)):
            if gang_name(pod) not in doomed or not pod.spec.node_name:
                continue
            store.delete(ResourceType.PODS, pod)
            key = pod.key()
            self.evicted_keys.add(key)
            st = self.cc.status
            st.successful_pods = [p for p in st.successful_pods
                                  if p.key() != key]
            st.scheduled_pods = [p for p in st.scheduled_pods
                                 if p.key() != key]
            if key in self.fed_keys:
                fresh = pod.copy()
                fresh.spec.node_name = ""
                fresh.status.phase = ""
                fresh.status.conditions = []
                fresh.status.reason = ""
                self.cc.pod_queue.push(fresh)
                self.requeued_keys.add(key)
            released += 1
        self.cc.metrics.gang_partial_rollback.inc()
        cleared = self.cc.scheduling_queue.clear_nominations_for_gangs(doomed)
        for pod in cleared:
            pod.status.nominated_node_name = ""
        note_fault("gang_release", {"groups": sorted(doomed),
                                    "released": released})

    def _restore_node(self, node) -> None:
        from tpusim.api.types import ResourceType

        self.cc.resource_store.add(ResourceType.NODES, node)
        self.cc.nodes.append(node)
        self.deleted_nodes.discard(node.name)
        # a returning node may make parked pods schedulable again
        self.cc.scheduling_queue.move_all_to_active_queue()
        note_fault("node_restore", {"target": node.name,
                                    "boundary": self.boundary})

    def _node_cordon(self, ev: ChurnEvent) -> bool:
        from tpusim.api.types import ResourceType

        node = self._find_node(ev.target)
        if node is None:
            return False
        cordoned = node.copy()
        cordoned.spec.unschedulable = True
        self.cc.resource_store.update(ResourceType.NODES, cordoned)
        self.cc.nodes = [cordoned if n.name == node.name else n
                         for n in self.cc.nodes]
        return True

    def _node_flap(self, ev: ChurnEvent) -> bool:
        return self._node_delete(ev, flap=True)

    def _pod_evict(self, ev: ChurnEvent) -> bool:
        from tpusim.api.types import ResourceType

        pod, ok = self.cc.resource_store.get(ResourceType.PODS, ev.target)
        if not ok or not pod.spec.node_name:
            return False  # not placed (or already gone): nothing to evict
        self.cc.resource_store.delete(ResourceType.PODS, pod)
        key = pod.key()
        self.evicted_keys.add(key)
        # mirror commit_preemption's bookkeeping: an evicted pod is no
        # longer placed, so it leaves the success/pre-scheduled buckets
        st = self.cc.status
        st.successful_pods = [p for p in st.successful_pods
                              if p.key() != key]
        st.scheduled_pods = [p for p in st.scheduled_pods if p.key() != key]
        if key in self.fed_keys:
            # a fed pod gets re-fed for another attempt (the controller
            # re-creates it); a seed pod is terminally evicted
            fresh = pod.copy()
            fresh.spec.node_name = ""
            fresh.status.phase = ""
            fresh.status.conditions = []
            fresh.status.reason = ""
            self.cc.pod_queue.push(fresh)
            self.requeued_keys.add(key)
        return True

    # -- retry gating ------------------------------------------------------

    def allow_retry(self, pod) -> bool:
        """May this churn-reactivated pod re-attempt? Bounded by the plan's
        per-pod max_retries; backoff-gated through the injected clock (the
        deterministic analog of MakeDefaultErrorFunc's podBackoff wait)."""
        key = pod.key()
        if self.retries.get(key, 0) >= self.plan.max_retries:
            return False
        backoff = self.cc.pod_backoff
        spins = 0
        while not backoff.try_backoff_and_wait(key):
            self.clock.advance()
            spins += 1
            if spins > 64:  # > max backoff (60s) at 1s ticks: impossible
                self.violations.append(
                    f"backoff for {key} never expired under the injected "
                    f"clock")
                return False
        self.retries[key] = self.retries.get(key, 0) + 1
        return True

    def audit_fabric(self) -> List[str]:
        """Final reconvergence check: every mirror's ``known`` map must
        agree with the authoritative store — key set and, for pods, the
        bound node. A lossy stream is allowed to lag mid-run; it is NOT
        allowed to end diverged. Streams torn by disconnect/overflow heal
        through relist-on-410 during the run; a silently DROPPED frame is
        undetectable from the stream alone, so the audit first runs one
        forced relist per mirror — the client-go periodic-resync analog —
        and then requires exact agreement."""
        if not self._mirrors:
            return []
        violations = []
        self._sync_mirrors()
        for refl in self._mirrors:
            refl.relist()
            refl.sync()
        for refl in self._mirrors:
            rt = refl.resource
            truth = {o.key(): o
                     for o in self.cc.resource_store.list(rt)}
            if set(refl.known) != set(truth):
                missing = sorted(set(truth) - set(refl.known))
                extra = sorted(set(refl.known) - set(truth))
                violations.append(
                    f"fabric mirror diverged on {rt.value}: "
                    f"missing={missing} extra={extra} after "
                    f"{refl.relists} relist(s)")
                continue
            for key, obj in truth.items():
                mirrored = refl.known[key]
                if getattr(obj.spec, "node_name", "") != \
                        getattr(mirrored.spec, "node_name", ""):
                    violations.append(
                        f"fabric mirror stale on {key}: node "
                        f"{getattr(mirrored.spec, 'node_name', '')!r} vs "
                        f"store {getattr(obj.spec, 'node_name', '')!r}")
        return violations

    def record_violation(self, message: str) -> None:
        self.violations.append(message)
        note_fault("invariant_violation", {"message": message})

    def summary(self) -> dict:
        return {
            "boundaries": self.boundary,
            "churn_fired": len(self.fired),
            "churn_skipped": len(self.skipped),
            "evicted": sorted(self.evicted_keys),
            "retries": dict(sorted(self.retries.items())),
            "fabric_injected": (list(self.fabric_injector.injected)
                                if self.fabric_injector else []),
            "fabric_relists": sum(r.relists for r in self._mirrors),
            "device_injected": (list(self.device_injector.injected)
                                if self.device_injector else []),
            "violations": list(self.violations),
        }


def check_invariants(cc, engine: ChaosEngine) -> List[str]:
    """End-state audit of a chaos run. Returns violation strings (empty =
    the system degraded gracefully):

    - **no pod lost** — every fed pod terminates scheduled or
      unschedulable (evicted seed pods are accounted as evicted, and
      evicted fed pods were re-fed so they too must terminate);
    - **no double-bind** — no pod occupies two placements: the success
      list is key-unique and agrees with the store's bound state;
    - **no bind to a deleted node** — checked at bind time by the
      simulator's seam (engine.record_violation) and re-checked here
      against the store's surviving nodes;
    - **fabric reconvergence** — when fabric faults are planned, the
      mirror consumer behind the lossy stream must end in agreement with
      the store (engine.audit_fabric).
    """
    from tpusim.api.types import ResourceType

    violations = list(engine.violations)
    violations.extend(engine.audit_fabric())
    st = cc.status
    scheduled_keys = [p.key() for p in st.successful_pods]
    scheduled_set = set(scheduled_keys)
    failed_set = {p.key() for p in st.failed_pods}

    # no pod lost
    for key in engine.fed_keys:
        if key in scheduled_set:
            continue
        if key in failed_set:
            continue
        if key in engine.evicted_keys and key not in engine.requeued_keys:
            continue
        violations.append(f"pod lost: {key} is neither scheduled, "
                          "unschedulable, nor accounted as evicted")

    # no double-bind
    dupes = {k for k in scheduled_set if scheduled_keys.count(k) > 1}
    for key in sorted(dupes):
        violations.append(f"double-bind: {key} appears "
                          f"{scheduled_keys.count(key)}x in successful_pods")
    for p in st.successful_pods:
        stored, ok = cc.resource_store.get(ResourceType.PODS, p.key())
        if not ok:
            if p.key() not in engine.evicted_keys:
                violations.append(f"bound pod {p.key()} missing from store")
        elif stored.spec.node_name != p.spec.node_name:
            violations.append(
                f"double-bind: {p.key()} bound to {p.spec.node_name} but "
                f"store says {stored.spec.node_name}")

    # no bind to a deleted node (bind-time seam already recorded live
    # violations; this catches placements that survived node deletion
    # without eviction bookkeeping going through the fabric)
    live_nodes = {n.name for n in cc.resource_store.list(ResourceType.NODES)}
    for p in st.successful_pods:
        node = p.spec.node_name
        if node not in live_nodes and node not in engine.deleted_nodes:
            violations.append(f"{p.key()} bound to unknown node {node}")

    # no partial gang bound (tpusim/gang): a pod group either holds at
    # least its min-available members or none at all — chaos that breaks a
    # gang mid-flight must have released every member
    from tpusim.gang.group import PodGroup, gang_name

    members: Dict[str, Dict[str, object]] = {}
    for p in (list(cc.resource_store.list(ResourceType.PODS))
              + st.successful_pods + st.failed_pods):
        name = gang_name(p)
        if name:
            members.setdefault(name, {})[p.key()] = p
    for name, by_key in sorted(members.items()):
        group = PodGroup(name=name, pods=list(by_key.values()))
        bound = sum(1 for p in cc.resource_store.list(ResourceType.PODS)
                    if gang_name(p) == name and p.spec.node_name)
        if 0 < bound < group.min_available:
            violations.append(
                f"partial gang bound: group {name} holds {bound}/"
                f"{len(group.pods)} members (min-available "
                f"{group.min_available})")

    # cache/store coherence: every store-bound pod the cache still tracks
    # must agree on its node (the informer seam never diverged)
    for key, state in cc.cache.pod_states.items():
        stored, ok = cc.resource_store.get(ResourceType.PODS, key)
        if ok and stored.spec.node_name and \
                state.pod.spec.node_name != stored.spec.node_name:
            violations.append(
                f"cache/store divergence for {key}: cache on "
                f"{state.pod.spec.node_name}, store on "
                f"{stored.spec.node_name}")
    return violations


def audit_failover(records) -> List[str]:
    """Journal-level invariant audit across a leader failover (ISSUE 18).

    Input is the [(offset, record)] list of the FULL post-failover WAL —
    leader prefix, recomputed crash tail, and promoted-leader
    continuation all in one journal. Returns violation strings:

    - **no pod lost** — every emit record's scheduled count ``s`` is
      matched by exactly that many bind entries for its cycle, and every
      emit accounts for its whole batch (``n`` == the batch size);
    - **no double-bind** — no pod key is bound to two different nodes
      without an intervening DELETED event for it (a rebind of a live
      pod across the failover boundary would mean the promoted twin
      re-decided a cycle the dead leader had already committed);
    - **bind provenance** — every bind entry's pod key belongs to its
      cycle's batch record.
    """
    violations: List[str] = []
    batch_keys: Dict[int, Set[str]] = {}
    batch_sizes: Dict[int, int] = {}
    binds_by_cycle: Dict[int, List[Tuple[str, str]]] = {}
    bound_to: Dict[str, str] = {}
    for _ofs, rec in records:
        k, c = rec.get("k"), int(rec.get("c", -1))
        if k == "batch":
            keys = set()
            for obj in rec.get("pods", []):
                md = obj.get("metadata", obj)
                ns = md.get("namespace") or "default"
                keys.add(f"{ns}/{md.get('name')}")
            batch_keys[c] = keys
            batch_sizes[c] = len(rec.get("pods", []))
        elif k == "ev" and rec.get("t") == "DELETED" \
                and rec.get("r") == "pod":
            obj = rec.get("o", {})
            md = obj.get("metadata", obj)
            ns = md.get("namespace") or "default"
            bound_to.pop(f"{ns}/{md.get('name')}", None)
        elif k == "bind":
            entries = [(key, node) for key, node in rec.get("b", [])]
            binds_by_cycle.setdefault(c, []).extend(entries)
            for key, node in entries:
                prev = bound_to.get(key)
                if prev is not None and prev != node:
                    violations.append(
                        f"double-bind across failover: {key} bound to "
                        f"{prev} then {node} (cycle {c})")
                bound_to[key] = node
                keys = batch_keys.get(c)
                if keys is not None and key not in keys:
                    violations.append(
                        f"bind without batch: {key} in cycle {c}")
        elif k == "emit":
            n, s = int(rec.get("n", 0)), int(rec.get("s", 0))
            got = len(binds_by_cycle.get(c, []))
            if got != s:
                violations.append(
                    f"pod lost in cycle {c}: emit says {s} scheduled "
                    f"but the journal holds {got} bind entries")
            size = batch_sizes.get(c)
            if size is not None and n != size:
                violations.append(
                    f"pod lost in cycle {c}: emit covers {n} decisions "
                    f"for a batch of {size}")
    return violations

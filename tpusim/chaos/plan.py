"""The declarative fault plan: what breaks, where, and when.

A plan is plain data — JSON on disk, dataclasses in memory — so a chaos
run is a pure function of (workload, snapshot, plan): replaying the same
plan reproduces the same fault sequence byte-for-byte. Three sections,
one per injection layer:

``churn``   scripted cluster events fired through the store at pod-attempt
            boundaries: ``node_delete``, ``node_cordon``, ``node_flap``
            (delete + re-add ``restore_after`` boundaries later), and
            ``pod_evict``. ``process_crash`` rides in this section too but
            fires from the persistence layer, not the attempt loop: the
            process dies right after the targeted WAL record (``target``
            names a record kind, one of CRASH_POINTS) of cycle ``at`` is
            durably written.
``fabric``  watch-stream faults keyed by the global fan-out event index:
            ``drop`` (the frame never reaches the watcher), ``dup`` (the
            frame is delivered twice), ``disconnect`` (the stream closes
            mid-flight with a transport error — the reflector must relist).
``device``  per-dispatch backend faults keyed by dispatch index:
            ``exception`` (the dispatch dies), ``corrupt_invalid``
            (out-of-range/NaN outputs — caught structurally), and
            ``corrupt_silent`` (in-range but wrong placements — caught
            only by host verification), plus the breaker thresholds.

Schema example (the README "Chaos & fault injection" quickstart):

    {
      "seed": 42,
      "max_retries": 3,
      "churn": [
        {"at": 2, "action": "node_delete", "target": "node-1"},
        {"at": 3, "action": "node_cordon", "target": "node-2"},
        {"at": 4, "action": "node_flap", "target": "node-0",
         "restore_after": 2},
        {"at": 5, "action": "pod_evict", "target": "default/web-1"}
      ],
      "fabric": {"drop": [4], "dup": [7], "disconnect": [9]},
      "device": {"faults": {"0": "exception", "1": "corrupt_silent"},
                 "failure_threshold": 2, "cooldown": 2, "verify": "all"}
    }
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

CHURN_ACTIONS = ("node_delete", "node_cordon", "node_flap", "pod_evict",
                 "process_crash")
DEVICE_FAULTS = ("exception", "corrupt_invalid", "corrupt_silent")
DEVICE_VERIFY_MODES = ("all", "probe")
# process_crash targets: the WAL record kind of cycle ``at`` the process
# dies immediately after (stream.persist writes the record, then raises) —
# together they cover every commit boundary a streaming cycle has
CRASH_POINTS = ("events", "batch", "bind", "emit")


class PlanError(ValueError):
    """A malformed fault plan (schema violation)."""


@dataclass(frozen=True)
class ChurnEvent:
    """One scripted cluster event, fired at pod-attempt boundary ``at``."""

    at: int                 # attempt boundary (0 = before the first attempt)
    action: str             # one of CHURN_ACTIONS
    target: str             # node name, or pod key (ns/name) for pod_evict
    restore_after: int = 0  # node_flap: boundaries until the node re-adds

    def validate(self) -> None:
        if self.action not in CHURN_ACTIONS:
            raise PlanError(f"unknown churn action {self.action!r} "
                            f"(expected one of {CHURN_ACTIONS})")
        if self.at < 0:
            raise PlanError(f"churn event {self.target!r}: negative boundary")
        if self.action == "node_flap" and self.restore_after < 1:
            raise PlanError(f"node_flap {self.target!r}: restore_after "
                            "must be >= 1")
        if self.action == "process_crash" and self.target not in CRASH_POINTS:
            raise PlanError(f"process_crash target must be a WAL record "
                            f"kind {CRASH_POINTS}, got {self.target!r}")


@dataclass
class FabricFaultPlan:
    """Watch-fabric faults by global fan-out event index (the order frames
    leave FakeRESTClient.emit_object_watch_event, which is deterministic in
    the single-threaded simulator)."""

    drop: List[int] = field(default_factory=list)
    dup: List[int] = field(default_factory=list)
    disconnect: List[int] = field(default_factory=list)

    def validate(self) -> None:
        for name in ("drop", "dup", "disconnect"):
            idxs = getattr(self, name)
            if any(i < 0 for i in idxs):
                raise PlanError(f"fabric.{name}: negative event index")
        overlap = set(self.drop) & set(self.dup)
        if overlap:
            raise PlanError(f"fabric: event(s) {sorted(overlap)} are both "
                            "dropped and duplicated")

    def empty(self) -> bool:
        return not (self.drop or self.dup or self.disconnect)


@dataclass
class DeviceFaultPlan:
    """Device-backend faults by dispatch index, plus breaker tuning."""

    faults: Dict[int, str] = field(default_factory=dict)
    failure_threshold: int = 3   # consecutive faults before the breaker opens
    cooldown: int = 2            # denied dispatches before half-open re-probe
    verify: str = "all"          # "all": host-verify every device batch under
                                 # chaos; "probe": only half-open probes

    def validate(self) -> None:
        for idx, kind in self.faults.items():
            if idx < 0:
                raise PlanError("device.faults: negative dispatch index")
            if kind not in DEVICE_FAULTS:
                raise PlanError(f"unknown device fault {kind!r} "
                                f"(expected one of {DEVICE_FAULTS})")
        if self.failure_threshold < 1:
            raise PlanError("device.failure_threshold must be >= 1")
        if self.cooldown < 1:
            raise PlanError("device.cooldown must be >= 1")
        if self.verify not in DEVICE_VERIFY_MODES:
            raise PlanError(f"device.verify must be one of "
                            f"{DEVICE_VERIFY_MODES}, got {self.verify!r}")

    def empty(self) -> bool:
        return not self.faults


@dataclass
class FaultPlan:
    """The full declarative plan; every section optional."""

    seed: int = 0
    max_retries: int = 3        # per-pod re-attempts after churn requeues
    churn: List[ChurnEvent] = field(default_factory=list)
    fabric: FabricFaultPlan = field(default_factory=FabricFaultPlan)
    device: DeviceFaultPlan = field(default_factory=DeviceFaultPlan)

    def validate(self) -> "FaultPlan":
        if self.max_retries < 0:
            raise PlanError("max_retries must be >= 0")
        for ev in self.churn:
            ev.validate()
        self.fabric.validate()
        self.device.validate()
        return self

    def host_sections_empty(self) -> bool:
        """True when only device faults and/or process crashes are planned
        (the jax batch path has no per-attempt boundary, so node/pod churn
        and fabric faults are host-orchestrator sections; a process_crash
        is fired by the persistence layer, not the attempt loop)."""
        return (self.fabric.empty()
                and all(ev.action == "process_crash" for ev in self.churn))

    def crash_events(self) -> List[ChurnEvent]:
        """The plan's scripted process deaths, in firing order."""
        return sorted((ev for ev in self.churn
                       if ev.action == "process_crash"),
                      key=lambda ev: (ev.at, ev.target))

    # -- (de)serialization -------------------------------------------------

    def to_obj(self) -> dict:
        obj: dict = {"seed": self.seed, "max_retries": self.max_retries}
        if self.churn:
            obj["churn"] = [
                {k: v for k, v in (("at", ev.at), ("action", ev.action),
                                   ("target", ev.target),
                                   ("restore_after", ev.restore_after))
                 if not (k == "restore_after" and v == 0)}
                for ev in self.churn]
        if not self.fabric.empty():
            obj["fabric"] = {k: v for k, v in
                             (("drop", self.fabric.drop),
                              ("dup", self.fabric.dup),
                              ("disconnect", self.fabric.disconnect)) if v}
        if not self.device.empty():
            obj["device"] = {
                "faults": {str(i): kind
                           for i, kind in sorted(self.device.faults.items())},
                "failure_threshold": self.device.failure_threshold,
                "cooldown": self.device.cooldown,
                "verify": self.device.verify,
            }
        return obj

    def to_json(self) -> str:
        return json.dumps(self.to_obj(), sort_keys=True, indent=2) + "\n"

    @classmethod
    def from_obj(cls, obj: dict) -> "FaultPlan":
        if not isinstance(obj, dict):
            raise PlanError(f"plan must be a JSON object, got "
                            f"{type(obj).__name__}")
        unknown = set(obj) - {"seed", "max_retries", "churn", "fabric",
                              "device"}
        if unknown:
            raise PlanError(f"unknown plan key(s): {sorted(unknown)}")
        churn = []
        for i, entry in enumerate(obj.get("churn") or []):
            if not isinstance(entry, dict):
                raise PlanError(f"churn[{i}] must be an object")
            try:
                churn.append(ChurnEvent(
                    at=int(entry["at"]), action=str(entry["action"]),
                    target=str(entry["target"]),
                    restore_after=int(entry.get("restore_after", 0))))
            except KeyError as exc:
                raise PlanError(f"churn[{i}]: missing {exc}") from exc
        fab = obj.get("fabric") or {}
        if not isinstance(fab, dict):
            raise PlanError("fabric must be an object")
        fabric = FabricFaultPlan(
            drop=[int(i) for i in fab.get("drop") or []],
            dup=[int(i) for i in fab.get("dup") or []],
            disconnect=[int(i) for i in fab.get("disconnect") or []])
        dev = obj.get("device") or {}
        if not isinstance(dev, dict):
            raise PlanError("device must be an object")
        device = DeviceFaultPlan(
            faults={int(i): str(kind)
                    for i, kind in (dev.get("faults") or {}).items()},
            failure_threshold=int(dev.get("failure_threshold", 3)),
            cooldown=int(dev.get("cooldown", 2)),
            verify=str(dev.get("verify", "all")))
        return cls(seed=int(obj.get("seed", 0)),
                   max_retries=int(obj.get("max_retries", 3)),
                   churn=churn, fabric=fabric, device=device).validate()


def load_plan(path: str) -> FaultPlan:
    """Parse a fault-plan JSON file (raises PlanError/OSError)."""
    with open(path, "r", encoding="utf-8") as f:
        try:
            obj = json.load(f)
        except json.JSONDecodeError as exc:
            raise PlanError(f"{path}: not JSON: {exc}") from exc
    return FaultPlan.from_obj(obj)


def random_crash_plan(seed: int, cycles: int,
                      points=CRASH_POINTS) -> FaultPlan:
    """One seeded process_crash at a random (cycle, WAL-record) boundary —
    the crash-recovery fuzz unit. Deterministic in ``seed``; the cycle is
    drawn from [0, cycles) and the record kind from ``points``, so a seed
    sweep covers every commit boundary class the streaming cycle has."""
    if cycles < 1:
        raise PlanError("random_crash_plan needs cycles >= 1")
    rng = random.Random(seed)
    ev = ChurnEvent(at=rng.randrange(cycles), action="process_crash",
                    target=rng.choice(tuple(points)))
    return FaultPlan(seed=seed, churn=[ev]).validate()


def kill_leader_campaign(seed: int, cycles: int,
                         points=CRASH_POINTS) -> List[FaultPlan]:
    """A kill-the-leader campaign (ISSUE 18): one FaultPlan per WAL
    record boundary class, each crashing the LEADER of a replicated pair
    at a seeded mid-run cycle. The failover matrix runs every plan
    against the same workload and asserts that a follower promotes with
    a byte-identical placement-hash chain head at all four boundaries.

    Crash cycles are drawn from the middle half of the run ([cycles/4,
    3*cycles/4)) so every campaign leaves both a replicated prefix to
    promote FROM and a post-failover tail to keep scheduling INTO —
    a crash at cycle 0 or the final cycle would test recovery, not
    continuity. Deterministic in ``seed``."""
    if cycles < 4:
        raise PlanError("kill_leader_campaign needs cycles >= 4")
    rng = random.Random(seed)
    lo, hi = cycles // 4, max(cycles // 4 + 1, (3 * cycles) // 4)
    return [FaultPlan(seed=seed, churn=[
        ChurnEvent(at=rng.randrange(lo, hi), action="process_crash",
                   target=point)]).validate()
        for point in points]


def random_plan(seed: int, node_names: List[str], pod_keys: List[str],
                attempts: int, device_dispatches: int = 0,
                max_retries: int = 3,
                keep_nodes: int = 1) -> FaultPlan:
    """Generate a seeded adversarial plan against a concrete workload.

    Deterministic: ``random.Random(seed)`` drives every choice, so the
    fault-fuzz matrix replays byte-identically. ``keep_nodes`` nodes are
    never deleted/cordoned (a cluster with zero schedulable nodes proves
    nothing beyond the all-unschedulable arm, which gets its own fixed
    case in the test matrix). ``device_dispatches`` > 0 additionally
    scripts device faults over that many dispatch indices.
    """
    rng = random.Random(seed)
    attempts = max(attempts, 1)
    churn: List[ChurnEvent] = []
    killable = list(node_names[keep_nodes:])
    rng.shuffle(killable)
    n_node_events = min(len(killable), rng.randint(0, 2 + len(killable) // 2))
    for name in killable[:n_node_events]:
        action = rng.choice(("node_delete", "node_cordon", "node_flap"))
        churn.append(ChurnEvent(
            at=rng.randrange(attempts), action=action, target=name,
            restore_after=rng.randint(1, 3) if action == "node_flap" else 0))
    evictable = list(pod_keys)
    rng.shuffle(evictable)
    for key in evictable[:rng.randint(0, min(2, len(evictable)))]:
        churn.append(ChurnEvent(at=rng.randrange(attempts),
                                action="pod_evict", target=key))
    churn.sort(key=lambda ev: (ev.at, ev.action, ev.target))

    # fabric faults over a conservative estimate of the fan-out stream:
    # every attempt emits at least an ADDED (feed) frame; churn adds more
    n_events = attempts * 2 + len(churn) + len(node_names)
    idxs = rng.sample(range(n_events), min(n_events, rng.randint(0, 5)))
    fabric = FabricFaultPlan()
    for i in sorted(idxs):
        bucket = rng.choice(("drop", "dup", "disconnect"))
        getattr(fabric, bucket).append(i)

    device = DeviceFaultPlan()
    if device_dispatches > 0:
        threshold = rng.randint(1, 3)
        n_faults = rng.randint(threshold, min(device_dispatches,
                                              threshold + 2))
        for i in rng.sample(range(device_dispatches),
                            min(n_faults, device_dispatches)):
            device.faults[i] = rng.choice(DEVICE_FAULTS)
        device.failure_threshold = threshold
        device.cooldown = rng.randint(1, 2)
    return FaultPlan(seed=seed, max_retries=max_retries, churn=churn,
                     fabric=fabric, device=device).validate()

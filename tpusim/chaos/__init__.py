"""Deterministic chaos engine: seeded fault injection across the watch
fabric, the scheduler loop, and the device backend.

The simulator's reference half only ever exercises the happy path — a
frozen snapshot, a cooperative fake apiserver, a scheduler that never
sees a node vanish mid-attempt. This package adds the failure half:

- ``plan``       — the declarative :class:`FaultPlan` (JSON or
                   programmatic) plus the seeded :func:`random_plan`
                   generator used by the fault-fuzz differential tests.
- ``engine``     — :class:`ChaosEngine`: fires scripted cluster churn
                   (node delete/cordon/flap, pod eviction) through the
                   store/watch fabric at pod-attempt boundaries, carries
                   the fabric and device injectors, and audits end-state
                   invariants (no pod lost, no double-bind, no bind to a
                   deleted node).
- ``breaker``    — :class:`CircuitBreaker`: the attempt-counted
                   closed → open → half-open → closed state machine the
                   jax backend wraps around device dispatch.

Everything is deterministic under a fixed seed: the plan is data, the
engine's clock is injected (no wall-clock reads), and the breaker counts
attempts instead of seconds, so a chaos replay is byte-stable.
"""

from tpusim.chaos.breaker import BreakerState, CircuitBreaker
from tpusim.chaos.engine import (
    ChaosClock,
    ChaosEngine,
    DeviceFault,
    DeviceInjector,
    DeviceOutputError,
    FabricInjector,
    InjectedDeviceError,
    check_invariants,
)
from tpusim.chaos.plan import (
    ChurnEvent,
    DeviceFaultPlan,
    FabricFaultPlan,
    FaultPlan,
    load_plan,
    random_plan,
)

__all__ = [
    "BreakerState",
    "ChaosClock",
    "ChaosEngine",
    "ChurnEvent",
    "CircuitBreaker",
    "DeviceFault",
    "DeviceFaultPlan",
    "DeviceInjector",
    "DeviceOutputError",
    "FabricFaultPlan",
    "FabricInjector",
    "FaultPlan",
    "InjectedDeviceError",
    "check_invariants",
    "load_plan",
    "random_plan",
]

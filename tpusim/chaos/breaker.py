"""Circuit breaker: closed → open on repeated faults → half-open re-probe
→ closed.

The jax backend wraps device dispatch in one of these so a flaky device
(injected chaos faults or a real wedged tunnel) degrades to the host
pipeline and RECOVERS, instead of either crashing the run or staying
disabled for the rest of the process (what the pre-breaker `_FAST_AUTO`
three-strikes logic did for the Pallas fast path).

Deterministic by construction: state advances on *attempt counts*, never
wall-clock — ``cooldown`` is the number of denied dispatches before a
half-open probe, so a seeded chaos replay walks the identical transition
sequence every run. Every transition lands in the
``tpusim_breaker_transitions_total`` counter family and as a recorder
instant (``breaker:<transition>``) via the ``obs.recorder.note_breaker``
bridge, and the live state is mirrored into the ``tpusim_breaker_state``
gauge (0 closed, 0.5 half-open, 1 open).
"""

from __future__ import annotations

import enum
from typing import Optional


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


_STATE_GAUGE = {BreakerState.CLOSED: 0.0, BreakerState.HALF_OPEN: 0.5,
                BreakerState.OPEN: 1.0}


class CircuitBreaker:
    """Attempt-counted three-state breaker.

    - CLOSED: traffic flows; ``failure_threshold`` CONSECUTIVE failures
      trip it open (any success resets the streak).
    - OPEN: ``allow()`` denies; after ``cooldown`` denials the breaker
      moves to HALF_OPEN.
    - HALF_OPEN: exactly one probe is allowed through; its success closes
      the breaker, its failure reopens (and restarts the cooldown).
    """

    def __init__(self, name: str = "device", failure_threshold: int = 3,
                 cooldown: int = 2):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if cooldown < 1:
            raise ValueError("cooldown must be >= 1")
        self.name = name
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.denied_since_open = 0
        self.transitions: list = []  # (transition, detail) audit trail

    # -- state machine -----------------------------------------------------

    def _transition(self, state: BreakerState, transition: str,
                    detail: Optional[str] = None) -> None:
        self.state = state
        self.transitions.append((transition, detail or ""))
        from tpusim.obs.recorder import note_breaker

        note_breaker(self.name, transition, _STATE_GAUGE[state], detail)

    def allow(self) -> bool:
        """May the next dispatch go to the device? A denial while OPEN
        counts toward the cooldown; once it elapses the breaker half-opens
        and the NEXT call is the probe."""
        if self.state is BreakerState.CLOSED:
            return True
        if self.state is BreakerState.HALF_OPEN:
            return True  # the probe
        self.denied_since_open += 1
        if self.denied_since_open >= self.cooldown:
            self._transition(BreakerState.HALF_OPEN, "half_open",
                            f"after {self.denied_since_open} denied")
        return False

    @property
    def probing(self) -> bool:
        """True when the next allowed dispatch is the half-open probe (the
        caller must verify its output before trusting it)."""
        return self.state is BreakerState.HALF_OPEN

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state is BreakerState.HALF_OPEN:
            self._transition(BreakerState.CLOSED, "close", "probe passed")

    def record_failure(self, reason: str = "") -> None:
        if self.state is BreakerState.HALF_OPEN:
            self.denied_since_open = 0
            self._transition(BreakerState.OPEN, "reopen",
                            reason or "probe failed")
            return
        self.consecutive_failures += 1
        if (self.state is BreakerState.CLOSED
                and self.consecutive_failures >= self.failure_threshold):
            self.denied_since_open = 0
            self._transition(
                BreakerState.OPEN, "open",
                reason or f"{self.consecutive_failures} consecutive faults")

    def reset(self) -> None:
        """Back to pristine CLOSED (test isolation; not a transition)."""
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.denied_since_open = 0
        self.transitions = []

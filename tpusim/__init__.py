"""tpusim — a TPU-native Kubernetes scheduling simulator.

Rebuilds the capabilities of xiaoxubeii/kubernetes-schedule-simulator (an offline
cluster-capacity / schedule simulator wrapping the vendored kube-scheduler) as a
batched bin-packing engine on JAX/XLA, with a pure-Python reference backend for
placement-parity testing.

Layout (mirrors SURVEY.md §2's component inventory):
  api/        domain model + IO  (reference: pkg/api/api.go, cmd/app/options/options.go)
  engine/     scheduling engine, Go-parity semantics
              (reference: vendor/k8s.io/kubernetes/pkg/scheduler/*)
  jaxe/       the JAX/TPU backend: columnar state, vmapped kernels, scan bind loop
  framework/  store / events / strategy / report
              (reference: pkg/framework/*)
  simulator   ClusterCapacity orchestrator (reference: pkg/scheduler/simulator.go)
  cli         command-line entry (reference: cmd/app/server.go)
"""

__version__ = "0.1.0"

"""AUTO verify-then-trust seam for the batched gang packing kernel.

Mirrors the victim-selection seam in jaxe/backend.py (`_VICTIM_AUTO`):
TPUSIM_GANG_KERNEL=0 forces the host oracle, =1 forces the device kernel
without verification (benchmark/debug), unset = AUTO — the first gang solved
per (members, nodes) pow2-bucketed signature runs BOTH sides and compares
choices; a match pins the signature (later gangs of that shape skip the
host compute), any disagreement disables the kernel process-wide and the
host result is used, so AUTO can never change behavior.
"""

from __future__ import annotations

import math
import os
from typing import List

import numpy as np

from tpusim.gang import oracle as _oracle
from tpusim.obs.recorder import note_auto_transition

# process-wide trust state; reset by jaxe.backend.reset_fast_auto() for test
# isolation alongside _FAST_AUTO/_VICTIM_AUTO
_GANG_AUTO = {"disabled": False, "verified_sigs": set()}


def gang_kernel_enabled() -> tuple:
    """(enabled, auto_mode) for the batched gang select kernel."""
    env = os.environ.get("TPUSIM_GANG_KERNEL")
    if env == "0":
        return False, False
    if _GANG_AUTO["disabled"]:
        return False, False
    if env == "1":
        return True, False
    return True, True


def _sig(m: int, n: int) -> str:
    bucket = lambda v: 1 << max(0, math.ceil(math.log2(max(1, v))))
    return f"gang:{bucket(m)}x{bucket(n)}"


def gang_choices(feasible: np.ndarray, score: np.ndarray,
                 req_cpu, req_mem, req_gpu, req_eph, zero_request,
                 alloc_cpu, alloc_mem, alloc_gpu, alloc_eph, allowed_pods,
                 used_cpu, used_mem, used_gpu, used_eph, pod_count,
                 zone_dom: np.ndarray, rack_dom: np.ndarray,
                 n_zone: int, n_rack: int) -> List[int]:
    """Solve the joint packing for one gang, routing host/device per the
    AUTO seam. All inputs are host numpy; the device path ships them through
    jit and the result is compared (or trusted) per signature."""
    enabled, auto = gang_kernel_enabled()
    host: List[int] = []

    def run_host() -> List[int]:
        return _oracle.select_oracle(
            feasible, score, req_cpu, req_mem, req_gpu, req_eph,
            zero_request, alloc_cpu, alloc_mem, alloc_gpu, alloc_eph,
            allowed_pods, used_cpu, used_mem, used_gpu, used_eph,
            pod_count, zone_dom, rack_dom, n_zone, n_rack)

    if not enabled:
        return run_host()

    import jax.numpy as jnp
    from tpusim.jaxe.kernels import GangIn, gang_select

    gi = GangIn(
        alloc_cpu=jnp.asarray(alloc_cpu, dtype=jnp.int64),
        alloc_mem=jnp.asarray(alloc_mem, dtype=jnp.int64),
        alloc_gpu=jnp.asarray(alloc_gpu, dtype=jnp.int64),
        alloc_eph=jnp.asarray(alloc_eph, dtype=jnp.int64),
        allowed_pods=jnp.asarray(allowed_pods, dtype=jnp.int64),
        used_cpu=jnp.asarray(used_cpu, dtype=jnp.int64),
        used_mem=jnp.asarray(used_mem, dtype=jnp.int64),
        used_gpu=jnp.asarray(used_gpu, dtype=jnp.int64),
        used_eph=jnp.asarray(used_eph, dtype=jnp.int64),
        pod_count=jnp.asarray(pod_count, dtype=jnp.int64),
        zone_dom=jnp.asarray(zone_dom, dtype=jnp.int32),
        rack_dom=jnp.asarray(rack_dom, dtype=jnp.int32))
    device = [int(c) for c in np.asarray(gang_select(
        jnp.asarray(feasible, dtype=bool),
        jnp.asarray(score, dtype=jnp.int64),
        jnp.asarray(req_cpu, dtype=jnp.int64),
        jnp.asarray(req_mem, dtype=jnp.int64),
        jnp.asarray(req_gpu, dtype=jnp.int64),
        jnp.asarray(req_eph, dtype=jnp.int64),
        jnp.asarray(zero_request, dtype=bool),
        gi, n_zone=n_zone, n_rack=n_rack))]

    if not auto:
        return device
    sig = _sig(*feasible.shape)
    if sig in _GANG_AUTO["verified_sigs"]:
        note_auto_transition("trust", sig)
        return device
    host = run_host()
    if host == device:
        _GANG_AUTO["verified_sigs"].add(sig)
        note_auto_transition("verify_pass", sig)
        return device
    _GANG_AUTO["disabled"] = True
    note_auto_transition("verify_fail", sig)
    return host

"""Gang admission driver: host-oracle group pass over the fused scan's lanes.

`schedule_with_gangs` is the one entry point every route (batch simulator,
stream runtime, verify oracle) calls when a feed carries gang annotations.
It splits the feed into ungrouped runs — scheduled through the UNCHANGED
per-pod path, so gang-free prefixes place identically to today — and
complete gangs, each admitted all-or-nothing by `admit_gang`:

  1. compile the member batch against the live IncrementalCluster and run
     the fused scan's feasibility/score lanes for every member against the
     SAME snapshot (`gang_lanes`: a vmap over the per-pod evaluate stage);
  2. solve joint placement (`gang_choices`: rank-aware greedy packing that
     pulls members toward zone/rack domains already holding mates, with an
     arithmetic capacity re-check as members stack — the device kernel runs
     behind the AUTO verify-then-trust seam against the numpy oracle);
  3. if at least `min-available` members placed, commit every bind
     atomically through the store fabric (journal-marked: a partial apply
     failure rolls the journal back); otherwise reject the WHOLE gang with
     one shared FitError and zero binds.

Gangs whose members use features the compiled state cannot carry fall back
to the backend's sequential path for the trial (intra-batch binds visible,
reference semantics), then the same all-or-nothing commit-or-reject gate.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Tuple

import numpy as np

from tpusim.api.types import Pod
from tpusim.backends import Placement, bind_pod, mark_unschedulable
from tpusim.framework import metrics as _metrics
from tpusim.framework.store import MODIFIED
from tpusim.gang.group import PodGroup, split_feed
from tpusim.gang.kernel import gang_choices
from tpusim.gang.oracle import packing_domains
from tpusim.obs import provenance
from tpusim.obs import recorder as flight

log = logging.getLogger("tpusim.gang")


def gang_fit_message(group: PodGroup, num_nodes: int, placed: int) -> str:
    """The single FitError message shared by every member of a rejected
    gang: the group identity and the shortfall, not a per-member reason
    histogram (the decision is joint, so the attribution is too)."""
    return (f"0/{num_nodes} nodes are available: pod group "
            f"\"{group.name}\" requires {group.min_available}/"
            f"{len(group.pods)} members, only {placed} fit jointly.")


def _reject(group: PodGroup, num_nodes: int, placed: int,
            reason: str) -> List[Placement]:
    msg = gang_fit_message(group, num_nodes, placed)
    m = _metrics.register()
    m.gang_rejected.inc(reason)
    flight.note_gang("reject", {"group": group.name, "reason": reason,
                                "placed": placed})
    return [Placement(pod=mark_unschedulable(p, msg),
                      reason="Unschedulable", message=msg)
            for p in group.pods]


def admit_gang(backend, inc, group: PodGroup) -> List[Placement]:
    """All-or-nothing admission of one gang against the live incremental
    cluster. On admit the binds are applied to `inc` (journal-marked);
    on reject nothing is applied."""
    m = _metrics.register()
    m.gang_size.observe(len(group.pods))
    members = group.pods
    num_nodes = len(inc.nodes)
    if num_nodes == 0:
        return _reject(group, 0, 0, "no_nodes")

    with flight.span("gang:admit") as sp:
        if sp:
            sp.set("group", group.name)
            sp.set("members", len(members))
        compiled, cols = inc.compile(members)
        if compiled.unsupported:
            choices, node_names = _sequential_trial(
                backend, inc, members, compiled, cols)
        else:
            choices, node_names = _joint_solve(
                backend, inc, members, compiled, cols)

    placed = sum(1 for c in choices if c >= 0)
    if placed < group.min_available:
        return _reject(group, num_nodes, placed, "min_available")

    # commit: every placed member binds atomically through the store
    # fabric; a failure mid-loop rolls the journal back so no partial
    # gang survives in the event stream
    mark = inc.journal_mark()
    placements: List[Placement] = []
    try:
        for pod, c in zip(members, choices):
            if c >= 0:
                bound = bind_pod(pod, node_names[c])
                inc.apply(MODIFIED, bound)
                placements.append(Placement(pod=bound,
                                            node_name=node_names[c]))
            else:
                # admitted at min-available: the overflow members failed
                # individually, not the gang
                msg = (f"pod group \"{group.name}\" admitted at "
                       f"{placed}/{len(members)}; this member did not fit.")
                placements.append(Placement(
                    pod=mark_unschedulable(pod, msg),
                    reason="Unschedulable", message=msg))
    except Exception:
        inc.journal_rollback(mark)
        m.gang_partial_rollback.inc()
        flight.note_gang("rollback", {"group": group.name})
        raise
    inc.journal_release()
    m.gang_admitted.inc()
    flight.note_gang("admit", {"group": group.name, "placed": placed,
                               "members": len(members)})
    return placements


def _joint_solve(backend, inc, members: List[Pod], compiled, cols
                 ) -> Tuple[List[int], List[str]]:
    """Member lanes + joint packing. Returns (choices, node name order)."""
    from tpusim.jaxe import ensure_x64
    from tpusim.jaxe.backend import _MOST_REQUESTED_PROVIDERS
    from tpusim.jaxe.kernels import (
        carry_init,
        carry_init_host,
        config_for,
        gang_lanes,
        pod_columns_to_device,
        pod_columns_to_host,
        statics_to_device,
        statics_to_host,
    )
    from tpusim.jaxe.state import NUM_FIXED_BITS

    ensure_x64()
    config = config_for(
        [compiled],
        most_requested=getattr(backend, "provider",
                               None) in _MOST_REQUESTED_PROVIDERS,
        num_reason_bits=NUM_FIXED_BITS + len(compiled.scalar_names),
        hard_weight=getattr(backend,
                            "hard_pod_affinity_symmetric_weight", 10))
    statics = statics_to_device(compiled)
    carry = carry_init(compiled)
    xs = pod_columns_to_device(cols)
    n_nodes = len(compiled.statics.names)
    lanes = None
    from tpusim.jaxe.backend import _SHARD_AUTO, _shard_count

    n_shards = _shard_count()
    if n_shards > 1 and not _SHARD_AUTO["disabled"]:
        import jax

        if len(jax.devices()) >= n_shards:
            # cross-shard gang lanes (ISSUE 16 sub-problem b): per-member
            # filter/score runs per node shard with collective reductions,
            # the stitched output re-gathers the full (member, node) matrix
            # — padded columns come back all-infeasible, so slicing to the
            # real node count feeds gang_choices byte-identical inputs
            from dataclasses import replace as _dc_replace

            from tpusim.jaxe.kernels import gang_lanes_sharded
            from tpusim.jaxe.sharding import make_mesh, shard_for_mesh

            mesh = make_mesh(n_shards, snap=1)
            st, ca, xs_r = shard_for_mesh(mesh, statics, carry, xs)
            with flight.span("shard:gang_lanes", "device") as sp:
                lanes = gang_lanes_sharded(
                    _dc_replace(config, shard_axis="node"), mesh, ca, st,
                    xs_r)
                if sp:
                    sp.set("shards", n_shards)
                    sp.set("members", len(members))
    if lanes is None:
        lanes = gang_lanes(config, carry, statics, xs)
    feasible = np.asarray(lanes[0])[:, :n_nodes]
    score = np.asarray(lanes[1])[:, :n_nodes]

    names = list(compiled.statics.names)
    by_name = {n.metadata.name: n for n in inc.nodes}
    zone_dom, rack_dom, n_zone, n_rack = packing_domains(
        [by_name[name] for name in names])

    hs = statics_to_host(compiled)
    hc = carry_init_host(compiled)
    hx = pod_columns_to_host(cols)
    choices = gang_choices(
        feasible, score,
        np.asarray(hx.req_cpu), np.asarray(hx.req_mem),
        np.asarray(hx.req_gpu), np.asarray(hx.req_eph),
        np.asarray(hx.zero_request),
        np.asarray(hs.alloc_cpu), np.asarray(hs.alloc_mem),
        np.asarray(hs.alloc_gpu), np.asarray(hs.alloc_eph),
        np.asarray(hs.allowed_pods),
        np.asarray(hc.used_cpu), np.asarray(hc.used_mem),
        np.asarray(hc.used_gpu), np.asarray(hc.used_eph),
        np.asarray(hc.pod_count),
        zone_dom, rack_dom, n_zone, n_rack)
    return choices, names


def _sequential_trial(backend, inc, members: List[Pod], compiled, cols
                      ) -> Tuple[List[int], List[str]]:
    """Fallback for gangs carrying features the compiled state cannot hold:
    a sequential trial through the backend (which itself falls back to
    reference semantics for the unsupported features; intra-batch binds are
    visible pod-to-pod on both engines). Nothing is committed here — the
    caller applies the all-or-nothing gate over the resulting choices."""
    log.warning("gang trial via sequential fallback for: %s",
                "; ".join(sorted(set(compiled.unsupported))[:5]))
    names = list(compiled.statics.names)
    index = {name: i for i, name in enumerate(names)}
    trial = backend.schedule(members, inc.to_snapshot(),
                             precompiled=(compiled, cols))
    return [index.get(pl.node_name, -1) if pl.scheduled else -1
            for pl in trial], names


def schedule_with_gangs(backend, inc, pods: List[Pod],
                        source: str = "gang") -> List[Placement]:
    """Schedule a feed that (may) carry gang annotations: ungrouped runs go
    through the backend's unchanged per-pod path; each gang is admitted
    all-or-nothing by `admit_gang`. Binds are applied to `inc` as decisions
    land, so later segments see earlier placements. Placements come back in
    the original feed order."""
    by_key: Dict[Tuple[str, str], Placement] = {}
    gang_placements: List[Placement] = []
    for seg in split_feed(pods):
        if seg.pods is not None:
            snapshot = inc.to_snapshot()
            precompiled = inc.compile(seg.pods) if inc.nodes else None
            pls = backend.schedule(seg.pods, snapshot,
                                   precompiled=precompiled)
            for pl in pls:
                if pl.scheduled:
                    inc.apply(MODIFIED, pl.pod)
        else:
            pls = admit_gang(backend, inc, seg.group)
            gang_placements.extend(pls)
        for pl in pls:
            key = (pl.pod.metadata.namespace, pl.pod.metadata.name)
            by_key[key] = pl
    if gang_placements:
        provenance.capture(gang_placements, source)
    return [by_key[(p.metadata.namespace, p.metadata.name)] for p in pods]

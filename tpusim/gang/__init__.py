"""Gang admission: all-or-nothing pod-group scheduling (ISSUE 15).

Pods carrying the ``pod-group.tpusim.io/name`` annotation are admitted as a
group: either at least ``min-available`` members place together against one
consistent snapshot, or the whole gang is rejected with a single shared
FitError and zero binds. Placement is rank-aware — members pack toward
zone/rack domains already holding their mates — solved jointly on host from
the fused scan's per-member feasibility/score lanes, with a batched device
kernel promoted behind the usual AUTO verify-then-trust seam.

Explicitly NOT wavefront speculation (DEVIATIONS.md #8): the member lanes
are evaluated against one frozen snapshot and the joint solve re-checks
capacity arithmetically as members stack, so the decision is a consistent
group admission, not optimistic multi-pod placement against stale state.
"""

from tpusim.gang.group import (
    GANG_MIN_AVAILABLE_ANNOTATION,
    GANG_NAME_ANNOTATION,
    PodGroup,
    gang_min_available,
    gang_name,
    has_gangs,
    split_feed,
)

__all__ = [
    "GANG_NAME_ANNOTATION",
    "GANG_MIN_AVAILABLE_ANNOTATION",
    "PodGroup",
    "gang_name",
    "gang_min_available",
    "has_gangs",
    "split_feed",
]

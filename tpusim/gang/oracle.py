"""Host oracle for the gang packing solve.

A numpy mirror of ``jaxe.kernels.gang_select``: the same member loop, the
same int64 rank key (zone mates << 52, rack mates << 32, clipped score), the
same capacity-arithmetic re-check as members stack onto a node. Both sides
consume identical domain-id arrays (computed here, host-side, from node
labels), so oracle-vs-kernel parity is bit-exact by construction — the AUTO
seam in tpusim/gang/kernel.py compares choices, not scores-within-epsilon.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from tpusim.api.types import Node
from tpusim.engine.priorities import get_zone_key
from tpusim.jaxe.packing import encode_gang_rank

# Rack topology labels, checked in order. The upstream scheduler has no
# canonical rack label; we accept the common community spelling first and a
# tpusim-local fallback (documented in DEVIATIONS.md, gang entry).
RACK_LABELS = ("topology.kubernetes.io/rack", "tpusim.io/rack")


def _rack_key(node: Node) -> str:
    labels = node.metadata.labels
    for label in RACK_LABELS:
        value = labels.get(label, "")
        if value:
            return value
    return ""


def packing_domains(nodes: Sequence[Node]) -> Tuple[np.ndarray, np.ndarray,
                                                    int, int]:
    """(zone_dom[N], rack_dom[N], n_zone, n_rack): 1-based interned domain
    ids per node, 0 = no domain. Computed host-side from node labels (the
    engine's GroupTables only populate zone/topo domains when services or
    inter-pod affinity are in play); both the oracle and the device kernel
    receive these exact arrays."""
    zone_ids: dict = {}
    rack_ids: dict = {}
    zone_dom = np.zeros(len(nodes), dtype=np.int32)
    rack_dom = np.zeros(len(nodes), dtype=np.int32)
    for i, node in enumerate(nodes):
        zone = get_zone_key(node)
        if zone:
            zone_dom[i] = zone_ids.setdefault(zone, len(zone_ids) + 1)
        rack = _rack_key(node)
        if rack:
            rack_dom[i] = rack_ids.setdefault(rack, len(rack_ids) + 1)
    return zone_dom, rack_dom, len(zone_ids) + 1, len(rack_ids) + 1


def select_oracle(feasible: np.ndarray, score: np.ndarray,
                  req_cpu: np.ndarray, req_mem: np.ndarray,
                  req_gpu: np.ndarray, req_eph: np.ndarray,
                  zero_request: np.ndarray,
                  alloc_cpu: np.ndarray, alloc_mem: np.ndarray,
                  alloc_gpu: np.ndarray, alloc_eph: np.ndarray,
                  allowed_pods: np.ndarray,
                  used_cpu: np.ndarray, used_mem: np.ndarray,
                  used_gpu: np.ndarray, used_eph: np.ndarray,
                  pod_count: np.ndarray,
                  zone_dom: np.ndarray, rack_dom: np.ndarray,
                  n_zone: int, n_rack: int) -> List[int]:
    """The packing loop, in numpy. Returns per-member node index or -1."""
    m, n = feasible.shape
    gang_cpu = np.zeros(n, dtype=np.int64)
    gang_mem = np.zeros(n, dtype=np.int64)
    gang_gpu = np.zeros(n, dtype=np.int64)
    gang_eph = np.zeros(n, dtype=np.int64)
    gang_pods = np.zeros(n, dtype=np.int64)
    zone_cnt = np.zeros(n_zone, dtype=np.int64)
    rack_cnt = np.zeros(n_rack, dtype=np.int64)
    choices: List[int] = []
    for i in range(m):
        fits = (pod_count + gang_pods + 1) <= allowed_pods
        if not zero_request[i]:
            fits &= alloc_cpu >= used_cpu + gang_cpu + int(req_cpu[i])
            fits &= alloc_mem >= used_mem + gang_mem + int(req_mem[i])
            fits &= alloc_gpu >= used_gpu + gang_gpu + int(req_gpu[i])
            fits &= alloc_eph >= used_eph + gang_eph + int(req_eph[i])
        ok = feasible[i] & fits
        zone_bonus = np.where(zone_dom > 0, zone_cnt[zone_dom], 0)
        rack_bonus = np.where(rack_dom > 0, rack_cnt[rack_dom], 0)
        # the SAME encode the device kernel runs (jaxe/packing.py)
        rank = encode_gang_rank(zone_bonus, rack_bonus,
                                score[i].astype(np.int64), ok)
        choice = int(np.argmax(rank))
        if rank[choice] < 0:
            choices.append(-1)
            continue
        gang_cpu[choice] += int(req_cpu[i])
        gang_mem[choice] += int(req_mem[i])
        gang_gpu[choice] += int(req_gpu[i])
        gang_eph[choice] += int(req_eph[i])
        gang_pods[choice] += 1
        zone_cnt[zone_dom[choice]] += 1
        rack_cnt[rack_dom[choice]] += 1
        choices.append(choice)
    return choices

"""Pod-group annotations and feed planning.

The annotation schema (DEVIATIONS.md, gang entry) follows the
kube-batch/coscheduling lineage: a group name plus an optional
``min-available`` floor, carried as pod annotations so podspecs, the load
generators, and watch events all transport gangs with zero new types.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from tpusim.api.types import Pod

GANG_NAME_ANNOTATION = "pod-group.tpusim.io/name"
GANG_MIN_AVAILABLE_ANNOTATION = "pod-group.tpusim.io/min-available"


def gang_name(pod: Pod) -> str:
    """The pod's group name, or "" for an ungrouped pod."""
    annotations = pod.metadata.annotations
    if not annotations:
        return ""
    return str(annotations.get(GANG_NAME_ANNOTATION, "") or "")


def gang_min_available(pod: Pod) -> int:
    """The pod's declared min-available floor; 0 = "all members"."""
    annotations = pod.metadata.annotations
    if not annotations:
        return 0
    raw = annotations.get(GANG_MIN_AVAILABLE_ANNOTATION, "")
    try:
        return max(0, int(raw))
    except (TypeError, ValueError):
        return 0


def mark_gang(pod: Pod, name: str, min_available: int = 0) -> Pod:
    """Stamp the group annotations onto `pod` (in place) and return it."""
    pod.metadata.annotations[GANG_NAME_ANNOTATION] = name
    if min_available:
        pod.metadata.annotations[GANG_MIN_AVAILABLE_ANNOTATION] = \
            str(min_available)
    return pod


def has_gangs(pods: Sequence[Pod]) -> bool:
    """True when any pod in the batch carries a group annotation. The ONLY
    routing trigger for the gang paths: gang-free feeds take the exact
    pre-existing code, so their placement hashes are byte-identical by
    construction."""
    return any(gang_name(p) for p in pods)


@dataclass
class PodGroup:
    """One gang, in feed order."""

    name: str
    pods: List[Pod] = field(default_factory=list)

    @property
    def min_available(self) -> int:
        """The group's admission floor: the max declared min-available
        across members (they should agree), defaulting to the full group
        size — plain gangs are strictly all-or-nothing."""
        declared = max((gang_min_available(p) for p in self.pods), default=0)
        if declared <= 0:
            return len(self.pods)
        return min(declared, len(self.pods))


@dataclass
class FeedSegment:
    """A contiguous run of the feed: either ungrouped pods (scheduled through
    the unchanged per-pod path) or one complete gang."""

    pods: Optional[List[Pod]] = None
    group: Optional[PodGroup] = None


def split_feed(pods: Sequence[Pod]) -> List[FeedSegment]:
    """Partition a feed into ordered segments: maximal runs of ungrouped pods
    and complete gangs. A gang's decision point is its FIRST member's feed
    position; members arriving later in the feed are pulled forward into the
    group (the queue analog gathers them from the pending queue)."""
    segments: List[FeedSegment] = []
    groups: dict = {}
    run: List[Pod] = []
    for pod in pods:
        name = gang_name(pod)
        if not name:
            run.append(pod)
            continue
        group = groups.get(name)
        if group is None:
            if run:
                segments.append(FeedSegment(pods=run))
                run = []
            group = PodGroup(name=name)
            groups[name] = group
            segments.append(FeedSegment(group=group))
        group.pods.append(pod)
    if run:
        segments.append(FeedSegment(pods=run))
    return segments

"""Streaming scheduler runtime (ISSUE 7): device-resident cluster state,
O(delta) scatter updates, classified restage fallbacks."""

from tpusim.stream.loadgen import ChurnLoadGen
from tpusim.stream.runtime import (
    MIN_BUCKET,
    DeviceResidentCluster,
    StreamSession,
    bucket_size,
)

__all__ = [
    "MIN_BUCKET",
    "ChurnLoadGen",
    "DeviceResidentCluster",
    "StreamSession",
    "bucket_size",
]

"""Streaming scheduler runtime (ISSUE 7): device-resident cluster state,
O(delta) scatter updates, classified restage fallbacks; crash recovery
via WAL + checkpoints (ISSUE 12); live what-if overlays + multi-tenant
residency (ISSUE 19)."""

from tpusim.stream.loadgen import ChurnLoadGen
from tpusim.stream.persist import (
    CRASH_POINTS,
    PersistError,
    RecoveryReport,
    StreamPersistence,
    chain_fold,
    read_wal,
    recover_stream_session,
    tail_wal,
)
from tpusim.stream.replicate import (
    FailoverController,
    FollowerTwin,
    PromotionRefused,
    PromotionReport,
    ReplicationError,
    WalShipper,
)
from tpusim.stream.runtime import (
    MIN_BUCKET,
    DeviceResidentCluster,
    StreamSession,
    bucket_size,
)
from tpusim.stream.tenancy import ResidencyBudget, TenantTwin

__all__ = [
    "CRASH_POINTS",
    "MIN_BUCKET",
    "ChurnLoadGen",
    "DeviceResidentCluster",
    "FailoverController",
    "FollowerTwin",
    "PersistError",
    "PromotionRefused",
    "PromotionReport",
    "RecoveryReport",
    "ReplicationError",
    "ResidencyBudget",
    "StreamPersistence",
    "StreamSession",
    "TenantTwin",
    "WalShipper",
    "bucket_size",
    "chain_fold",
    "read_wal",
    "recover_stream_session",
    "tail_wal",
]

"""Streaming scheduler runtime: device-resident cluster state, O(delta)
scatter commits (ISSUE 7).

Every other execution path in this repo re-stages the full compiled cluster
(statics + dynamic carry) onto device per scheduling attempt, so churn-heavy
steady state pays O(cluster) host→HBM traffic per cycle — BASELINE.md's
r02→r05 warm-CPU slide (11,410 → 6,232 pods/s on an unchanged placement
hash) is that staging contention. The reference simulator never re-lists the
world per decision: its reflector→informer fabric mutates a persistent cache
in place. This module is the device-side analog:

  DeviceResidentCluster — the compiled arrays stay in HBM across decisions;
      watch-fabric deltas land as donated scatter updates
      (kernels.apply_delta_donated) gathered from the IncrementalCluster's
      journal, so a warm cycle's update cost is O(touched rows), not
      O(nodes).
  StreamSession — drives ingest → scatter-commit → schedule → fold-back.
      Binds from the fused scan update the resident carry directly (the
      scan's final carry IS the post-bind state — zero host round-trip);
      host fold-back journal entries are therefore discarded, not
      re-committed. Structural events the scatter path can't express (node
      churn, group-table invalidation, signature-memo eviction, scalar
      universe growth, watch 410-relists) fall back to a full restage,
      classified in tpusim_stream_restage_total{reason}.

Exactness contract (tested by the churn-parity fuzz): stream-path placements
are byte-identical (placement_hash) to scheduling every batch through the
full-restage path (JaxBackend.schedule on a fresh compile) over any event
sequence. The parity argument: the host IncrementalCluster stays the source
of truth; commits scatter-`set` AUTHORITATIVE host values (idempotent,
self-healing), the commit re-arms the per-batch lanes (sa_lock/rr) exactly
like carry_init_host, and every field without a scatter path (presence_dom,
used_vols, statics columns) only changes under events that force a restage.

Chaos composition mirrors jaxe.backend.JaxBackend.schedule: host faults
(node flap, pod evict, watch drop) arrive as ordinary deltas; device faults
flow through the same circuit breaker + injector seam, and any chaos
intervention (fault, corruption, verify divergence, open breaker)
invalidates residency so the next cycle re-arms from host truth.
"""

from __future__ import annotations

import logging
from time import perf_counter
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Pod, ResourceType
from tpusim.backends import (
    Placement,
    ReferenceBackend,
    mark_unschedulable,
    placement_hash,
)
from tpusim.engine.providers import DEFAULT_PROVIDER
from tpusim.framework.events import WatchExpiredError
from tpusim.framework.metrics import register, since_in_microseconds
from tpusim.framework.reflector import Reflector
from tpusim.framework.store import MODIFIED
from tpusim.jaxe import backend as _backend
from tpusim.jaxe import ensure_responsive_platform, ensure_x64
from tpusim.jaxe.delta import IncrementalCluster
from tpusim.jaxe.kernels import (
    DeltaRows,
    apply_delta_donated,
    carry_init_host,
    config_for,
    pad_infeasible_rows,
    pod_columns_to_host,
    schedule_scan_donated,
    statics_to_host,
)
from tpusim.jaxe.sharding import stage_tree
from tpusim.jaxe.state import NUM_FIXED_BITS, reason_strings
from tpusim.obs import recorder as flight

log = logging.getLogger(__name__)

# Scatter-commit and pod-batch axes are padded up to pow2 buckets (floor 8):
# the warm steady state cycles through a handful of compiled programs instead
# of one per delta count — the zero-retrace contract kernels.py documents.
MIN_BUCKET = 8


def bucket_size(n: int) -> int:
    """Smallest pow2 >= n, floored at MIN_BUCKET."""
    return max(MIN_BUCKET, 1 << max(0, n - 1).bit_length())


def _pad_index(idx: np.ndarray, size: int) -> np.ndarray:
    """Pad an index vector to `size` by repeating its first entry (index 0
    when empty): duplicates are safe under the commit's `set` semantics
    because every duplicate carries the same authoritative value."""
    if len(idx) >= size:
        return idx
    fill = idx[0] if len(idx) else np.int32(0)
    return np.concatenate([idx, np.full(size - len(idx), fill, np.int32)])


class DeviceResidentCluster:
    """The device half of the stream runtime: compiled statics + carry held
    in HBM across decisions, plus the host-side metadata needed to prove a
    new batch can reuse them (resident signature-row interning, group batch
    keys, node/scalar shape)."""

    def __init__(self):
        self.compiled = None          # host CompiledCluster of the restage
        self.config = None            # EngineConfig (jit-static)
        self.statics = None           # device Statics
        self.carry = None             # device Carry — THE resident state
        self.sig_rows: Optional[Dict[str, Dict[object, int]]] = None
        self.n_nodes = 0
        self.scalar_width = 0
        self.evictions_mark = 0       # inc.sig_evictions at adopt time
        self.commits = 0              # scatter commits since construction
        self.restages = 0

    @property
    def valid(self) -> bool:
        return self.carry is not None

    def invalidate(self) -> None:
        self.compiled = self.config = self.statics = self.carry = None
        self.sig_rows = None

    def adopt(self, inc: IncrementalCluster, compiled, config, statics,
              carry) -> None:
        """Install a freshly restaged state as resident."""
        self.compiled = compiled
        self.config = config
        self.statics = statics
        self.carry = carry
        # resident signature-row order per kind: later batches' batch-local
        # ids are remapped through these dicts onto the resident table rows
        self.sig_rows = {kind: {key: row for row, key in enumerate(keys)}
                         for kind, keys in inc.last_batch_key_lists.items()}
        self.n_nodes = len(compiled.statics.names)
        self.scalar_width = len(compiled.scalar_names)
        self.evictions_mark = inc.sig_evictions
        self.restages += 1

    def residency_miss(self, inc: IncrementalCluster) -> Optional[str]:
        """A structural reason the resident arrays cannot serve the next
        cycle, or None. Ordering matters for the classifier: node events
        also dirty the group tables, so the node-set check runs first."""
        if self.carry is None:
            return "cold_start"
        if len(inc.nodes) != self.n_nodes:
            return "node_set"
        if inc._groups_dirty:
            return "groups_dirty"
        if len(inc._scalar_names) != self.scalar_width:
            return "scalar_set"
        return None

    def remap_signatures(self, inc: IncrementalCluster, cols,
                         key_lists: Dict[str, List]) -> Optional[str]:
        """Rewrite the batch's batch-local signature ids into resident table
        row ids in place. Returns None on success, or the restage reason for
        a signature the resident tables have no row for ("sig_evict" when
        the memo has evicted rows since the restage — the miss may be cache
        pressure, not novelty)."""
        luts = {}
        for kind, keys in key_lists.items():
            resident = self.sig_rows[kind]
            try:
                luts[kind] = np.fromiter((resident[k] for k in keys),
                                         dtype=np.int32, count=len(keys))
            except KeyError:
                return ("sig_evict"
                        if inc.sig_evictions > self.evictions_mark
                        else "new_signature")
        for kind, lut in luts.items():
            col = getattr(cols, kind)
            col[:] = lut[col]
        return None

    def commit(self, inc: IncrementalCluster) -> None:
        """Drain the IncrementalCluster's delta journal and scatter-commit
        the AUTHORITATIVE post-event values of every touched node row /
        presence cell into the resident carry (donated: the HBM buffers are
        patched in place). Always dispatches — even with an empty journal —
        because the commit also re-arms the per-batch lanes (sa_lock/rr) to
        carry_init_host's values, keeping stream and restage cycles
        byte-identical."""
        nodes, cells = inc.drain_journal()
        dyn = inc._ensure_dyn()
        idx = np.fromiter(sorted(nodes), dtype=np.int32, count=len(nodes))
        idx = _pad_index(idx, bucket_size(max(len(idx), 1)))
        rows = DeltaRows(
            used_cpu=dyn.used_cpu[idx], used_mem=dyn.used_mem[idx],
            used_gpu=dyn.used_gpu[idx], used_eph=dyn.used_eph[idx],
            used_scalar=dyn.used_scalar[idx],
            nonzero_cpu=dyn.nonzero_cpu[idx],
            nonzero_mem=dyn.nonzero_mem[idx],
            pod_count=dyn.pod_count[idx])
        cell_list = sorted(cells)
        gid = np.fromiter((g for g, _ in cell_list), dtype=np.int32,
                          count=len(cell_list))
        nid = np.fromiter((n for _, n in cell_list), dtype=np.int32,
                          count=len(cell_list))
        size = bucket_size(max(len(gid), 1))
        gid, nid = _pad_index(gid, size), _pad_index(nid, size)
        if inc._presence is not None:
            val = inc._presence[gid, nid].astype(np.int32)
        else:
            # trivial [1, N] dummy presence: the padded (0, 0) cells are
            # untouched zeros on both sides
            val = np.zeros(size, np.int32)
        sp = flight.span("stream_commit", "device")
        self.carry = apply_delta_donated(self.carry, idx, rows, gid, nid, val)
        if sp:
            sp.set("rows", int(len(nodes)))
            sp.set("cells", int(len(cells)))
            sp.end()
        self.commits += 1


class StreamSession:
    """Drives the streaming loop: ingest watch deltas → scatter-commit →
    schedule on the resident state → fold placements back.

    v1 scope: providers only (no compiled policy — policy'd workloads keep
    the per-batch JaxBackend path). Unsupported feature combinations route
    whole batches through the reference backend, classified like every
    other fallback.
    """

    def __init__(self, snapshot: Optional[ClusterSnapshot] = None, *,
                 incremental: Optional[IncrementalCluster] = None,
                 provider: str = DEFAULT_PROVIDER,
                 hard_pod_affinity_symmetric_weight: int = 10,
                 always_restage: bool = False):
        """always_restage: disable the O(delta) fast path — every cycle pays
        the full compile + device staging. The bench's restage-vs-stream
        comparison arm; placements are identical either way."""
        if provider not in _backend._KNOWN_PROVIDERS:
            raise KeyError(f"plugin {provider!r} has not been registered")
        ensure_x64()
        ensure_responsive_platform()
        self.inc = (incremental if incremental is not None
                    else IncrementalCluster(snapshot))
        self.provider = provider
        self.hard_weight = hard_pod_affinity_symmetric_weight
        self.always_restage = always_restage
        self.device = DeviceResidentCluster()
        self.cycles = 0
        self.restage_counts: Dict[str, int] = {}
        self.path_counts: Dict[str, int] = {}
        self._forced: Optional[str] = None
        self._reflectors: List[Reflector] = []

    # -- ingest -----------------------------------------------------------

    def apply(self, event_type: str, obj) -> None:
        self.inc.apply(event_type, obj)

    def apply_events(self, events) -> None:
        self.inc.apply_events(events)

    def ingest(self, watch_buffer) -> int:
        """Drain a WatchBuffer into the host picture. A torn stream (410
        Gone analog) forces a restage on the next cycle and re-raises so
        the caller can relist (or use watch()/sync(), which do)."""
        try:
            return self.inc.ingest(watch_buffer)
        except WatchExpiredError:
            self.force_restage("watch_expired")
            raise

    def watch(self, client, resource: ResourceType, **kwargs) -> Reflector:
        """Attach a Reflector stream feeding this session; its 410-Gone
        recovery relists force a device restage (the synthetic diff may not
        be O(delta)-expressible)."""
        r = Reflector(client, resource, handler=self.inc.apply,
                      on_relist=lambda _n: self.force_restage("watch_expired"),
                      **kwargs)
        self._reflectors.append(r)
        return r

    def sync(self) -> int:
        """Drain every attached Reflector; returns events applied."""
        return sum(r.sync() for r in self._reflectors)

    def force_restage(self, reason: str) -> None:
        """Invalidate residency before the next cycle (first reason wins)."""
        if self._forced is None:
            self._forced = reason

    # -- the cycle --------------------------------------------------------

    def schedule(self, pods: List[Pod]) -> List[Placement]:
        """One decision cycle: route the batch through the resident fast
        path when residency holds, else a classified restage; fold scheduled
        placements back into the host picture (and, on the fast path, rely
        on the scan having already bound them on device)."""
        if not pods:
            return []
        self.cycles += 1
        inc = self.inc
        if not inc.nodes:
            msg = "no nodes available to schedule pods"
            return [Placement(pod=mark_unschedulable(p, msg),
                              reason="Unschedulable", message=msg)
                    for p in pods]
        t0 = perf_counter()
        reason = self._forced
        self._forced = None
        if reason is None and self.always_restage:
            reason = "forced_restage"
        if reason is None:
            reason = self.device.residency_miss(inc)
        cols = None
        if reason is None:
            cols, key_lists = inc._batch_columns(pods)
            if len(inc._scalar_names) != self.device.scalar_width:
                # the batch itself widened the scalar universe
                reason = "scalar_set"
            else:
                reason = self.device.remap_signatures(inc, cols, key_lists)
            if reason is None and not inc.assign_group_ids(cols, pods):
                reason = "group_shape"
            if reason is None and self.device.config.has_interpod \
                    and inc._journal_presence:
                # presence_dom has no scatter path: external presence churn
                # under inter-pod affinity must rebuild it host-side
                reason = "interpod_delta"
        if reason is None:
            placements = self._stream_cycle(pods, cols)
        else:
            placements = self._restage_cycle(pods, reason)
        for pl in placements:
            if pl.node_name:
                inc.apply(MODIFIED, pl.pod)
        if self.device.valid:
            # the scan already applied these binds to the resident carry
            # with identical integer arithmetic — replaying the fold-back
            # journal next cycle would be a byte-for-byte no-op
            inc.drain_journal()
        register().e2e_scheduling_latency.observe(since_in_microseconds(t0))
        return placements

    # -- paths ------------------------------------------------------------

    def _stream_cycle(self, pods: List[Pod], cols) -> List[Placement]:
        dev = self.device

        def dispatch():
            dev.commit(self.inc)
            p = len(pods)
            xs_host = pad_infeasible_rows(pod_columns_to_host(cols),
                                          bucket_size(p) - p)
            carry, placements, intervened = self._dispatch(
                dev.config, dev.carry, dev.statics, stage_tree(xs_host),
                pods, dev.compiled)
            # the donated input buffer is gone either way; the scan's final
            # carry IS the post-bind resident state
            dev.carry = carry
            return placements, intervened

        return self._run_guarded(pods, "stream_scan", dispatch)

    def _restage_cycle(self, pods: List[Pod], reason: str) -> List[Placement]:
        inc = self.inc
        dev = self.device
        dev.invalidate()
        inc.drain_journal()  # structural restage: indices may have shifted
        t0 = perf_counter()
        with flight.span("compile_cluster") as csp:
            compiled, cols = inc.compile(pods)
            if csp:
                csp.set("pods", len(pods))
                csp.set("nodes", len(inc.nodes))
        register().backend_compile_latency.observe(since_in_microseconds(t0))
        if compiled.unsupported:
            detail = "; ".join(sorted(set(compiled.unsupported))[:5])
            log.warning("stream runtime falling back to reference for: %s",
                        detail)
            return self._host_cycle(pods, "reference_fallback")
        config = config_for(
            [compiled],
            most_requested=self.provider in _backend._MOST_REQUESTED_PROVIDERS,
            num_reason_bits=NUM_FIXED_BITS + len(compiled.scalar_names),
            hard_weight=self.hard_weight)
        statics = stage_tree(statics_to_host(compiled))
        carry0 = stage_tree(carry_init_host(compiled))
        p = len(pods)
        xs_host = pad_infeasible_rows(pod_columns_to_host(cols),
                                      bucket_size(p) - p)
        xs = stage_tree(xs_host)

        def dispatch():
            carry, placements, intervened = self._dispatch(
                config, carry0, statics, xs, pods, compiled)
            if not intervened:
                dev.adopt(inc, compiled, config, statics, carry)
            return placements, intervened

        return self._run_guarded(pods, "restage_scan", dispatch, reason)

    def _run_guarded(self, pods: List[Pod], path: str,
                     dispatch: Callable[[], Tuple[List[Placement], bool]],
                     restage_reason: Optional[str] = None) -> List[Placement]:
        """The chaos seam, mirroring JaxBackend.schedule: breaker-denied or
        faulted dispatches route to the host pipeline, probes and
        verify="all" dispatches are host-verified before placements are
        emitted, and ANY intervention invalidates residency (the next cycle
        re-arms from host truth).

        Classification is deferred to here so each off-stream cycle carries
        exactly ONE label — its final disposition: a restage cycle that the
        breaker denies counts as breaker_open, not as its structural reason
        plus breaker_open."""
        breaker = _backend._CHAOS["breaker"]
        if breaker is None:
            placements, intervened = dispatch()
            if restage_reason is not None:
                self._classify(restage_reason)
            if intervened:
                self.device.invalidate()
            self._note_path(path, len(pods))
            return placements
        from tpusim.chaos.engine import DeviceFault

        if not breaker.allow():
            flight.note_route("breaker_fallback", len(pods))
            return self._host_cycle(pods, "breaker_open")
        probing = breaker.probing
        try:
            placements, intervened = dispatch()
        except DeviceFault as exc:
            breaker.record_failure(f"{type(exc).__name__}: {exc}")
            flight.note_route("breaker_fallback", len(pods))
            return self._host_cycle(pods, "device_fault")
        if probing or _backend._CHAOS["verify"] == "all":
            expected = self._reference(pods)
            if placement_hash(placements) != placement_hash(expected):
                breaker.record_failure("device/host placement divergence")
                flight.note_route("breaker_fallback", len(pods))
                self.device.invalidate()
                self._classify("verify_divergence")
                self._note_path("host", len(pods))
                return expected
        breaker.record_success()
        if restage_reason is not None:
            self._classify(restage_reason)
        if intervened:
            self.device.invalidate()
        self._note_path(path, len(pods))
        return placements

    def _dispatch(self, config, carry, statics, xs, pods: List[Pod],
                  compiled) -> Tuple[object, List[Placement], bool]:
        """Run the donated scan under the chaos injector seam. Returns
        (final_carry, placements, intervened) — `intervened` flags a
        scripted corruption this dispatch (the emitted results may not
        match the device's true decisions, so residency must drop)."""
        metrics = register()
        injector = _backend._CHAOS["injector"]
        corrupt_kind = None
        if injector is not None:
            corrupt_kind = injector.begin_dispatch()  # may raise DeviceFault
        t0 = perf_counter()
        dsp = flight.span("device_dispatch", "device")
        with flight.profiled("tpusim:stream_scan"):
            final_carry, choices, counts, _adv = schedule_scan_donated(
                config, carry, statics, xs)
        p = len(pods)
        choices = np.asarray(choices)[:p]
        counts = np.asarray(counts)[:p]
        if injector is not None:
            if corrupt_kind is not None:
                from tpusim.chaos.engine import DeviceInjector

                choices, counts = DeviceInjector.corrupt(corrupt_kind,
                                                         choices, counts)
            from tpusim.chaos.engine import DeviceOutputError

            n_nodes = len(compiled.statics.names)
            if choices.size and (int(choices.max()) >= n_nodes
                                 or int(choices.min()) < -1):
                raise DeviceOutputError(
                    f"device choice out of range [-1, {n_nodes})")
            if np.isnan(np.asarray(counts, dtype=np.float64)).any():
                raise DeviceOutputError("NaN in device reason counts")
        if dsp:
            dsp.set("pods", p)
            dsp.end()
        metrics.backend_dispatch_latency.observe(since_in_microseconds(t0))
        metrics.scheduling_algorithm_latency.observe(
            since_in_microseconds(t0))
        strings = reason_strings(compiled.scalar_names)
        with flight.span("decode_placements"):
            placements, _ = _backend.decode_placements(
                pods, choices, counts, compiled.statics.names, strings)
        return final_carry, placements, corrupt_kind is not None

    def _host_cycle(self, pods: List[Pod], reason: str) -> List[Placement]:
        """Reference-backend cycle (chaos fallback or unsupported features):
        residency drops — the device never saw these binds."""
        self._classify(reason)
        self.device.invalidate()
        placements = self._reference(pods)
        self._note_path("host", len(pods))
        return placements

    def _reference(self, pods: List[Pod]) -> List[Placement]:
        return ReferenceBackend(
            provider=self.provider,
            hard_pod_affinity_symmetric_weight=self.hard_weight,
        ).schedule(pods, self.inc.to_snapshot())

    # -- accounting -------------------------------------------------------

    def _classify(self, reason: str, detail: Optional[str] = None) -> None:
        self.restage_counts[reason] = self.restage_counts.get(reason, 0) + 1
        flight.note_stream_restage(reason, detail)

    def _note_path(self, path: str, pods: int) -> None:
        self.path_counts[path] = self.path_counts.get(path, 0) + 1
        flight.note_stream_cycle(path, pods)

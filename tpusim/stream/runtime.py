"""Streaming scheduler runtime: device-resident cluster state, O(delta)
scatter commits (ISSUE 7), compiled-policy residency and pipelined cycle
execution (ISSUE 9).

Every other execution path in this repo re-stages the full compiled cluster
(statics + dynamic carry) onto device per scheduling attempt, so churn-heavy
steady state pays O(cluster) host→HBM traffic per cycle — BASELINE.md's
r02→r05 warm-CPU slide (11,410 → 6,232 pods/s on an unchanged placement
hash) is that staging contention. The reference simulator never re-lists the
world per decision: its reflector→informer fabric mutates a persistent cache
in place. This module is the device-side analog:

  DeviceResidentCluster — the compiled arrays stay in HBM across decisions;
      watch-fabric deltas land as donated scatter updates
      (kernels.apply_delta_donated) gathered from the IncrementalCluster's
      journal, so a warm cycle's update cost is O(touched rows), not
      O(nodes).
  StreamSession — drives ingest → scatter-commit → schedule → fold-back.
      Binds from the fused scan update the resident carry directly (the
      scan's final carry IS the post-bind state — zero host round-trip);
      host fold-back journal entries are therefore discarded, not
      re-committed. Structural events the scatter path can't express (node
      churn, group-table invalidation, signature-memo eviction, scalar
      universe growth, watch 410-relists) fall back to a full restage,
      classified in tpusim_stream_restage_total{reason}.

Exactness contract (tested by the churn-parity fuzz): stream-path placements
are byte-identical (placement_hash) to scheduling every batch through the
full-restage path (JaxBackend.schedule on a fresh compile) over any event
sequence. The parity argument: the host IncrementalCluster stays the source
of truth; commits scatter-`set` AUTHORITATIVE host values (idempotent,
self-healing), the commit re-arms the per-batch lanes (sa_lock/rr) exactly
like carry_init_host — including the policy ServiceAffinity segment locks,
recomputed per commit from the live pod set the way a restage would — and
every field without a scatter path (presence_dom, used_vols, group tables)
only changes under events that force a restage. Statics columns gained a
scatter path in v2: label/taint-only node churn lands as
kernels.apply_statics_delta_donated over the churned columns (signature
rows re-gathered from the host memo, policy rows recomputed against the
RESIDENT interning), so a fixed plan signature rides out arbitrary
label/taint churn with zero restages; only a genuine plan change restages,
classified as policy_plan_change.

Pipelined execution (schedule_pipelined/poll_placed/flush) keeps the same
contract: dispatch cycle N's device program without blocking, decode cycle
N-1's placements while N runs, and fold N-1's binds back BEFORE the driver
draws N's events — the host picture evolves in exactly the synchronous
order, so emitted placements and placement_hash are byte-identical to the
synchronous path. Any off-stream condition (chaos installed, restage
reason, no nodes) drains the in-flight cycle and runs synchronously.

Chaos composition mirrors jaxe.backend.JaxBackend.schedule: host faults
(node flap, pod evict, watch drop) arrive as ordinary deltas; device faults
flow through the same circuit breaker + injector seam, and any chaos
intervention (fault, corruption, verify divergence, open breaker)
invalidates residency so the next cycle re-arms from host truth.
"""

from __future__ import annotations

import logging
from contextlib import nullcontext
from time import perf_counter
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Pod, ResourceType
from tpusim.backends import (
    Placement,
    ReferenceBackend,
    bind_pod,
    mark_unschedulable,
    placement_hash,
)
from tpusim.engine.providers import DEFAULT_PROVIDER
from tpusim.framework.events import WatchExpiredError
from tpusim.framework.metrics import register, since_in_microseconds
from tpusim.framework.reflector import Reflector
from tpusim.framework.store import MODIFIED
from tpusim.gang.driver import schedule_with_gangs
from tpusim.gang.group import has_gangs
from tpusim.jaxe import backend as _backend
from tpusim.jaxe import ensure_responsive_platform, ensure_x64
from tpusim.jaxe.delta import _SIG_KINDS, IncrementalCluster
from tpusim.jaxe.kernels import (
    DeltaRows,
    StaticsDelta,
    apply_delta_donated,
    apply_statics_delta_donated,
    carry_init_host,
    config_for,
    overlay_restore_donated,
    pad_infeasible_rows,
    pod_columns_to_host,
    schedule_scan_donated,
    statics_to_host,
)
from tpusim.jaxe.policyc import (
    build_policy_residency,
    build_policy_tables,
    compile_policy,
    policy_delta_columns,
    policy_plan_key,
    remap_policy_columns,
    sa_lock_init_rows,
)
from tpusim.jaxe.sharding import stage_tree
from tpusim.jaxe.state import NUM_FIXED_BITS, reason_strings
from tpusim.obs import analytics
from tpusim.obs import provenance
from tpusim.obs import recorder as flight
from tpusim.obs import slo
from tpusim.obs import tracectx

log = logging.getLogger(__name__)

# Scatter-commit and pod-batch axes are padded up to pow2 buckets (floor 8):
# the warm steady state cycles through a handful of compiled programs instead
# of one per delta count — the zero-retrace contract kernels.py documents.
MIN_BUCKET = 8


def bucket_size(n: int) -> int:
    """Smallest pow2 >= n, floored at MIN_BUCKET."""
    return max(MIN_BUCKET, 1 << max(0, n - 1).bit_length())


def _pad_index(idx: np.ndarray, size: int) -> np.ndarray:
    """Pad an index vector to `size` by repeating its first entry (index 0
    when empty): duplicates are safe under the commit's `set` semantics
    because every duplicate carries the same authoritative value."""
    if len(idx) >= size:
        return idx
    fill = idx[0] if len(idx) else np.int32(0)
    return np.concatenate([idx, np.full(size - len(idx), fill, np.int32)])


class DeviceResidentCluster:
    """The device half of the stream runtime: compiled statics + carry held
    in HBM across decisions, plus the host-side metadata needed to prove a
    new batch can reuse them (resident signature-row interning, group batch
    keys, node/scalar shape)."""

    def __init__(self):
        self.compiled = None          # host CompiledCluster of the restage
        self.config = None            # EngineConfig (jit-static)
        self.statics = None           # device Statics
        self.carry = None             # device Carry — THE resident state
        self.sig_rows: Optional[Dict[str, Dict[object, int]]] = None
        self.plan_key = None          # policyc.policy_plan_key of the restage
        self.ptabs = None             # host PolicyTables of the restage
        self.pol_res = None           # policyc.PolicyResidency interning
        self.n_nodes = 0
        self.scalar_width = 0
        self.evictions_mark = 0       # inc.sig_evictions at adopt time
        self.commits = 0              # scatter commits since construction
        self.restages = 0

    @property
    def valid(self) -> bool:
        return self.carry is not None

    def invalidate(self) -> None:
        self.compiled = self.config = self.statics = self.carry = None
        self.sig_rows = None
        self.plan_key = self.ptabs = self.pol_res = None

    def adopt(self, inc: IncrementalCluster, compiled, config, statics,
              carry, plan_key=None, ptabs=None, pol_res=None) -> None:
        """Install a freshly restaged state as resident."""
        self.compiled = compiled
        self.config = config
        self.statics = statics
        self.carry = carry
        # resident signature-row order per kind: later batches' batch-local
        # ids are remapped through these dicts onto the resident table rows
        self.sig_rows = {kind: {key: row for row, key in enumerate(keys)}
                         for kind, keys in inc.last_batch_key_lists.items()}
        self.plan_key = plan_key
        self.ptabs = ptabs
        self.pol_res = pol_res
        self.n_nodes = len(compiled.statics.names)
        self.scalar_width = len(compiled.scalar_names)
        self.evictions_mark = inc.sig_evictions
        self.restages += 1

    def residency_miss(self, inc: IncrementalCluster,
                       plan_key=None) -> Optional[str]:
        """A structural reason the resident arrays cannot serve the next
        cycle, or None. Ordering matters for the classifier: node events
        also dirty the group tables, so the node-set check runs first; a
        plan-signature change outranks everything but a cold start (the
        resident policy tables serve the OLD plan, whatever else holds)."""
        if self.carry is None:
            return "cold_start"
        if plan_key != self.plan_key:
            return "policy_plan_change"
        if len(inc.nodes) != self.n_nodes:
            return "node_set"
        if inc._groups_dirty:
            return "groups_dirty"
        if len(inc._scalar_names) != self.scalar_width:
            return "scalar_set"
        return None

    def remap_signatures(self, inc: IncrementalCluster, cols,
                         key_lists: Dict[str, List]) -> Optional[str]:
        """Rewrite the batch's batch-local signature ids into resident table
        row ids in place. Returns None on success, or the restage reason for
        a signature the resident tables have no row for ("sig_evict" when
        the memo has evicted rows since the restage — the miss may be cache
        pressure, not novelty)."""
        luts = {}
        for kind, keys in key_lists.items():
            resident = self.sig_rows[kind]
            try:
                luts[kind] = np.fromiter((resident[k] for k in keys),
                                         dtype=np.int32, count=len(keys))
            except KeyError:
                return ("sig_evict"
                        if inc.sig_evictions > self.evictions_mark
                        else "new_signature")
        for kind, lut in luts.items():
            col = getattr(cols, kind)
            col[:] = lut[col]
        return None

    def commit(self, inc: IncrementalCluster, sa_lock_init) -> None:
        """Drain the IncrementalCluster's delta journal and scatter-commit
        the AUTHORITATIVE post-event values of every touched node row /
        presence cell into the resident carry (donated: the HBM buffers are
        patched in place). Always dispatches — even with an empty journal —
        because the commit also re-arms the per-batch lanes (sa_lock/rr) to
        the values a fresh restage would stage (`sa_lock_init`: all-unlocked
        for providers, the live first-matching-pod pins for ServiceAffinity
        policies), keeping stream and restage cycles byte-identical."""
        nodes, cells = inc.drain_journal()
        dyn = inc._ensure_dyn()
        idx = np.fromiter(sorted(nodes), dtype=np.int32, count=len(nodes))
        idx = _pad_index(idx, bucket_size(max(len(idx), 1)))
        rows = DeltaRows(
            used_cpu=dyn.used_cpu[idx], used_mem=dyn.used_mem[idx],
            used_gpu=dyn.used_gpu[idx], used_eph=dyn.used_eph[idx],
            used_scalar=dyn.used_scalar[idx],
            nonzero_cpu=dyn.nonzero_cpu[idx],
            nonzero_mem=dyn.nonzero_mem[idx],
            pod_count=dyn.pod_count[idx])
        cell_list = sorted(cells)
        gid = np.fromiter((g for g, _ in cell_list), dtype=np.int32,
                          count=len(cell_list))
        nid = np.fromiter((n for _, n in cell_list), dtype=np.int32,
                          count=len(cell_list))
        size = bucket_size(max(len(gid), 1))
        gid, nid = _pad_index(gid, size), _pad_index(nid, size)
        if inc._presence is not None:
            val = inc._presence[gid, nid].astype(np.int32)
        else:
            # trivial [1, N] dummy presence: the padded (0, 0) cells are
            # untouched zeros on both sides
            val = np.zeros(size, np.int32)
        sp = flight.span("stream_commit", "device")
        self.carry = apply_delta_donated(self.carry, idx, rows, gid, nid, val,
                                         sa_lock_init)
        if sp:
            sp.set("rows", int(len(nodes)))
            sp.set("cells", int(len(cells)))
            sp.end()
        self.commits += 1


class _PendingCycle:
    """One in-flight (or sync-buffered) pipelined cycle: the donated scan's
    un-forced device outputs plus everything the deferred decode needs."""

    __slots__ = ("pods", "choices", "counts", "compiled", "t0",
                 "dispatched_at", "folded", "bound", "placements",
                 "wal_cycle", "trace")

    def __init__(self, pods, choices=None, counts=None, compiled=None,
                 t0=0.0, dispatched_at=0.0, placements=None,
                 wal_cycle=None):
        self.pods = pods
        self.choices = choices
        self.counts = counts
        self.compiled = compiled
        self.t0 = t0
        self.dispatched_at = dispatched_at
        self.folded = placements is not None
        self.bound: List[Placement] = []
        self.placements = placements
        # WAL cycle id when a persistence layer is attached; None for a
        # sync-buffered cycle (schedule() already journaled its commit)
        self.wal_cycle = wal_cycle
        # the dispatching cycle's trace context: the deferred decode and
        # fold-time bind journaling run under THIS cycle's trace, not the
        # overlapping cycle's (ISSUE 20)
        self.trace = tracectx.current()


class StreamSession:
    """Drives the streaming loop: ingest watch deltas → scatter-commit →
    schedule on the resident state → fold placements back.

    v2 scope (ISSUE 9): providers AND compiled policies — every policy the
    Pallas fused scan can express stays device-resident, keyed on its plan
    signature. Unsupported feature combinations (extenders, unsupported
    predicates) route whole batches through the reference backend,
    classified like every other fallback.
    """

    def __init__(self, snapshot: Optional[ClusterSnapshot] = None, *,
                 incremental: Optional[IncrementalCluster] = None,
                 provider: str = DEFAULT_PROVIDER,
                 hard_pod_affinity_symmetric_weight: int = 10,
                 always_restage: bool = False,
                 policy=None, compiled_policy=None):
        """always_restage: disable the O(delta) fast path — every cycle pays
        the full compile + device staging. The bench's restage-vs-stream
        comparison arm; placements are identical either way.
        policy/compiled_policy: a scheduler Policy compiled for residency
        (compile-time validation mirrors JaxBackend); swap mid-session via
        set_policy (a plan-signature change restages once)."""
        if provider not in _backend._KNOWN_PROVIDERS:
            raise KeyError(f"plugin {provider!r} has not been registered")
        if policy is not None and compiled_policy is None:
            compiled_policy = compile_policy(policy)
        ensure_x64()
        ensure_responsive_platform()
        self.inc = (incremental if incremental is not None
                    else IncrementalCluster(snapshot))
        self.provider = provider
        self.hard_weight = hard_pod_affinity_symmetric_weight
        self.always_restage = always_restage
        self.policy = policy
        self.cp = compiled_policy
        self._plan_key = policy_plan_key(compiled_policy)
        self.device = DeviceResidentCluster()
        self.cycles = 0
        self.restage_counts: Dict[str, int] = {}
        self.path_counts: Dict[str, int] = {}
        self._forced: Optional[str] = None
        self._reflectors: List[Reflector] = []
        self._statics_patch = None    # (padded idx, StaticsDelta) or None
        self._pending: Optional[_PendingCycle] = None
        self._last_path: Optional[str] = None
        self._gang_jax = None         # lazy JaxBackend for gang cycles
        self.persist = None           # stream.persist.StreamPersistence
        # node-sharded residency (ISSUE 16 sub-problem c): set by the
        # restage when TPUSIM_SHARDS engages — the resident twin then lives
        # shard-even padded over the mesh's "node" axis, stream cycles run
        # the shard_map scan, and delta scatter-commits touch only the
        # owner shard's block (O(delta-per-shard))
        self._shard_mesh = None
        self._shard_layout: Optional[Dict[str, int]] = None
        # HBM residency accounting (ISSUE 14): polled at scrape/snapshot
        # time only; the weakref drops the source with the session
        analytics.register_hbm_source(
            "stream_twin", self.device,
            lambda dev: (analytics.tree_nbytes((dev.statics, dev.carry)),
                         1 if dev.valid else 0))

    def set_policy(self, policy=None, compiled_policy=None) -> None:
        """Swap the session's scheduling policy. The next cycle restages
        exactly once, classified policy_plan_change, unless the new plan
        signature matches the resident one."""
        if policy is not None and compiled_policy is None:
            compiled_policy = compile_policy(policy)
        self.policy = policy
        self.cp = compiled_policy
        self._plan_key = policy_plan_key(compiled_policy)

    # -- ingest -----------------------------------------------------------

    def apply(self, event_type: str, obj) -> None:
        self.inc.apply(event_type, obj)

    def apply_events(self, events) -> None:
        self.inc.apply_events(events)

    def ingest(self, watch_buffer) -> int:
        """Drain a WatchBuffer into the host picture. A torn stream (410
        Gone analog) forces a restage on the next cycle and re-raises so
        the caller can relist (or use watch()/sync(), which do)."""
        try:
            return self.inc.ingest(watch_buffer)
        except WatchExpiredError:
            self.force_restage("watch_expired")
            raise

    def watch(self, client, resource: ResourceType, **kwargs) -> Reflector:
        """Attach a Reflector stream feeding this session; its 410-Gone
        recovery relists force a device restage (the synthetic diff may not
        be O(delta)-expressible)."""
        r = Reflector(client, resource, handler=self.inc.apply,
                      on_relist=lambda _n: self.force_restage("watch_expired"),
                      **kwargs)
        self._reflectors.append(r)
        return r

    def sync(self) -> int:
        """Drain every attached Reflector; returns events applied."""
        return sum(r.sync() for r in self._reflectors)

    def force_restage(self, reason: str) -> None:
        """Invalidate residency before the next cycle (first reason wins)."""
        if self._forced is None:
            self._forced = reason

    # -- persistence (stream.persist) -------------------------------------

    def attach_persistence(self, persistence) -> None:
        """Journal this session's committed deltas, batches, binds, and
        emissions through a StreamPersistence (WAL + checkpoints)."""
        persistence.attach(self)

    def _persist_suppressed(self):
        """Gate the WAL's watch-delta hook around fold-back binds: binds
        are journaled as bind records, not as synthetic MODIFIED events."""
        return (self.persist.suppress_events() if self.persist is not None
                else nullcontext())

    # -- the cycle --------------------------------------------------------

    def schedule(self, pods: List[Pod],
                 _routed=None) -> List[Placement]:
        """One decision cycle: route the batch through the resident fast
        path when residency holds, else a classified restage; fold scheduled
        placements back into the host picture (and, on the fast path, rely
        on the scan having already bound them on device). `_routed`: a
        (reason, cols) pair from a _route call this cycle already made
        (schedule_pipelined's off-stream degrade) — routing is not
        re-entrant across the forced latch and the column journal."""
        if not pods:
            return []
        # one trace context per decision cycle (ISSUE 20): every span the
        # cycle emits — and every WAL frame it ships — carries this id.
        # A context already active (serve front door, pipelined degrade)
        # is the parent; start() is None (and activate a no-op) unless a
        # flight recorder is installed.
        with tracectx.activate(tracectx.start(parent=tracectx.current())):
            return self._schedule_cycle(pods, _routed)

    def _schedule_cycle(self, pods: List[Pod],
                        _routed=None) -> List[Placement]:
        self.cycles += 1
        inc = self.inc
        t0 = perf_counter()
        cid = (self.persist.begin_cycle(pods)
               if self.persist is not None else None)
        if not inc.nodes:
            # final disposition like any other cycle: one path label plus
            # the latency observations (the accounting-identity contract)
            msg = "no nodes available to schedule pods"
            placements = [Placement(pod=mark_unschedulable(p, msg),
                                    reason="Unschedulable", message=msg)
                          for p in pods]
            self._note_path("no_nodes", len(pods))
            if cid is not None:
                self.persist.log_bind(cid, [])
                self.persist.log_emit(cid, placements)
            self._observe_cycle("no_nodes", t0)
            return placements
        if has_gangs(pods):
            return self._gang_cycle(pods, t0, cid)
        reason, cols = _routed if _routed is not None else self._route(pods)
        if reason is None:
            placements = self._stream_cycle(pods, cols)
        else:
            placements = self._restage_cycle(pods, reason)
        bound = [pl for pl in placements if pl.node_name]
        with self._persist_suppressed():
            for pl in bound:
                inc.apply(MODIFIED, pl.pod)
        if self.device.valid:
            # the scan already applied these binds to the resident carry
            # with identical integer arithmetic — replaying the fold-back
            # journal next cycle would be a byte-for-byte no-op
            inc.drain_journal()
        if cid is not None:
            self.persist.log_bind(cid, bound)
            self.persist.log_emit(cid, placements)
        self._observe_cycle(self._last_path, t0)
        return placements

    def _gang_cycle(self, pods: List[Pod], t0: float,
                    cid) -> List[Placement]:
        """A gang decision is a multi-pod cycle solved through the group
        driver (tpusim/gang) against the live host picture: member lanes +
        rank-aware joint packing, committed all-or-nothing. The driver
        applies binds to `inc` directly, so the rows sit in the fold-back
        journal and the NEXT cycle's scatter-commit carries them onto the
        resident twin exactly like external churn — O(delta), residency
        stays valid, nothing restages. The WAL hook is suppressed around
        the driver (binds are journaled as bind records below, not as
        synthetic watch events)."""
        with self._persist_suppressed():
            placements = schedule_with_gangs(
                self._gang_backend(), self.inc, pods, source="stream-gang")
        bound = [pl for pl in placements if pl.node_name]
        self._note_path("gang", len(pods))
        if cid is not None:
            self.persist.log_bind(cid, bound)
            self.persist.log_emit(cid, placements)
        self._observe_cycle("gang", t0)
        return placements

    def _gang_backend(self):
        """Lazy JaxBackend for gang cycles: the group driver's per-pod
        segments and member lanes run through the batch backend, not the
        resident twin (a gang decision re-snapshots by design)."""
        if self._gang_jax is None:
            self._gang_jax = _backend.JaxBackend(
                provider=self.provider,
                hard_pod_affinity_symmetric_weight=self.hard_weight,
                policy=self.policy, compiled_policy=self.cp)
        return self._gang_jax

    def _route(self, pods: List[Pod]):
        """Decide stream-vs-restage for a batch: returns (None, cols) when
        the resident state can serve it, else (reason, cols-or-None).
        Consumes the forced-restage latch and the column journal (a restage
        rebuilds everything, so a lost patch is harmless)."""
        inc = self.inc
        reason = self._forced
        self._forced = None
        if reason is None and self.always_restage:
            reason = "forced_restage"
        if reason is None:
            reason = self.device.residency_miss(inc, self._plan_key)
        cols = None
        if reason is None:
            cols, key_lists = inc._batch_columns(pods)
            if len(inc._scalar_names) != self.device.scalar_width:
                # the batch itself widened the scalar universe
                reason = "scalar_set"
            else:
                reason = self.device.remap_signatures(inc, cols, key_lists)
            if reason is None and not inc.assign_group_ids(cols, pods):
                reason = "group_shape"
            if reason is None and self.device.config.has_interpod \
                    and inc._journal_presence:
                # presence_dom has no scatter path: external presence churn
                # under inter-pod affinity must rebuild it host-side
                reason = "interpod_delta"
            if reason is None and self.cp is not None:
                # per-pod policy signature columns against the RESIDENT
                # interning (image multisets, ServiceAffinity pins)
                reason = remap_policy_columns(self.cp, self.device.pol_res,
                                              pods, cols)
            if reason is None:
                reason = self._prepare_statics_delta()
        return reason, cols

    # -- paths ------------------------------------------------------------

    def _prepare_statics_delta(self) -> Optional[str]:
        """Turn the column journal (label/taint-only node churn) into a
        pending StaticsDelta scatter: authoritative post-churn columns for
        every churned node, gathered from the host signature-row memo
        (patched in place by _update_node, so it IS current) and recomputed
        against the RESIDENT policy interning. Returns a restage reason when
        the resident tables cannot express the new columns (evicted
        signature row with no representative, label value outside the
        resident domain space), else None with the patch staged for the
        next dispatch."""
        inc = self.inc
        dev = self.device
        touched = inc.drain_column_journal()
        if not touched:
            return None
        n = len(touched)
        idx = _pad_index(np.fromiter(sorted(touched), np.int32, count=n),
                         bucket_size(n))
        u = len(idx)
        cols: Dict[str, np.ndarray] = {}
        for col_kind, _fn, table_kinds in _SIG_KINDS:
            keys_by_row = sorted(dev.sig_rows[col_kind].items(),
                                 key=lambda kv: kv[1])
            for tk in table_kinds:
                if tk == "taint_ok_noexec" \
                        and not dev.compiled.has_noexec_table:
                    # the resident table is the all-pass dummy compile()
                    # stages when no pod tolerates NoExecute predicates
                    cols[tk] = np.ones((max(len(keys_by_row), 1), u),
                                       dtype=bool)
                    continue
                fn, dtype = inc._row_fns[tk]
                out = np.zeros((max(len(keys_by_row), 1), u), dtype=dtype)
                for sig_key, row in keys_by_row:
                    memo = inc._sig_rows.get((tk, sig_key))
                    if memo is not None:
                        out[row] = memo[idx]
                        continue
                    rep = inc._sig_reps.get(sig_key)
                    if rep is None:
                        return "sig_evict"
                    out[row] = np.fromiter((fn(rep, int(i)) for i in idx),
                                           dtype=dtype, count=u)
                cols[tk] = out
        st = dev.statics
        shapes = (st.label_ok.shape[0], st.image_score.shape[0],
                  st.saa_dom.shape[0], st.sa_val.shape[0])
        pol = policy_delta_columns(self.cp, dev.pol_res, dev.ptabs,
                                   inc.nodes, idx, shapes)
        if isinstance(pol, str):
            return pol
        label_ok, label_prio, image_score, saa_dom, sa_val = pol
        self._statics_patch = (idx, StaticsDelta(
            selector_ok=cols["selector_ok"], taint_ok=cols["taint_ok"],
            taint_ok_noexec=cols["taint_ok_noexec"],
            intolerable=cols["intolerable"],
            affinity_count=cols["affinity_count"],
            avoid_score=cols["avoid_score"], host_ok=cols["host_ok"],
            label_ok=label_ok, label_prio=label_prio,
            image_score=image_score, saa_dom=saa_dom, sa_val=sa_val))
        return None

    def _commit_sa_lock(self) -> np.ndarray:
        """The sa_lock re-arm values a restage would stage RIGHT NOW: the
        live first-matching-pod pins for ServiceAffinity policies (snapshot
        pod order — inc._pods preserves insertion order like the reference
        cache), all-unlocked otherwise."""
        dev = self.device
        if self.cp is not None and self.cp.spec.sa_enabled:
            return sa_lock_init_rows(dev.compiled.groups.saa_defs,
                                     list(self.inc._pods.values()),
                                     dev.compiled.node_index)
        return np.full(dev.compiled.groups.saa_rows.shape[0], -1,
                       dtype=np.int32)

    def _apply_statics_patch(self) -> None:
        """Scatter the pending label/taint-churn statics columns into the
        resident tables (donated in-place HBM patch)."""
        if self._statics_patch is None:
            return
        idx, delta = self._statics_patch
        self._statics_patch = None
        dev = self.device
        sp = flight.span("statics_commit", "device")
        dev.statics = apply_statics_delta_donated(dev.statics, idx, delta)
        if sp:
            sp.set("cols", int(len(idx)))
            sp.end()

    def _stream_cycle(self, pods: List[Pod], cols) -> List[Placement]:
        dev = self.device

        def dispatch():
            self._apply_statics_patch()
            dev.commit(self.inc, self._commit_sa_lock())
            p = len(pods)
            xs_host = pad_infeasible_rows(pod_columns_to_host(cols),
                                          bucket_size(p) - p)
            carry, placements, intervened = self._dispatch(
                dev.config, dev.carry, dev.statics,
                self._stage_xs(xs_host), pods, dev.compiled)
            # the donated input buffer is gone either way; the scan's final
            # carry IS the post-bind resident state
            dev.carry = carry
            return placements, intervened

        return self._run_guarded(pods, "stream_scan", dispatch)

    def _restage_cycle(self, pods: List[Pod], reason: str) -> List[Placement]:
        inc = self.inc
        dev = self.device
        cp = self.cp
        dev.invalidate()
        inc.drain_journal()  # structural restage: indices may have shifted
        self._statics_patch = None
        from tpusim.engine.predicates import (
            POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
        )

        need_noexec = (cp is not None and cp.spec.pred_keys is not None
                       and POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED
                       in cp.spec.pred_keys)
        need_saa = cp is not None and (bool(cp.spec.saa_weights)
                                       or cp.spec.sa_enabled)
        t0 = perf_counter()
        with flight.span("compile_cluster") as csp:
            compiled, cols = inc.compile(pods, need_noexec=need_noexec,
                                         need_saa=need_saa)
            if csp:
                csp.set("pods", len(pods))
                csp.set("nodes", len(inc.nodes))
        compile_us = since_in_microseconds(t0)
        register().backend_compile_latency.observe(compile_us)
        analytics.note_compile(
            "stream_restage",
            f"plan={self._plan_key}/nodes={len(inc.nodes)}", compile_us)
        unsupported = list(compiled.unsupported)
        if cp is not None:
            unsupported.extend(cp.unsupported)
        if unsupported:
            detail = "; ".join(sorted(set(unsupported))[:5])
            log.warning("stream runtime falling back to reference for: %s",
                        detail)
            return self._host_cycle(pods, "reference_fallback")
        hard_weight = self.hard_weight
        if cp is not None and cp.hard_weight is not None:
            hard_weight = cp.hard_weight
        config = config_for(
            [compiled],
            most_requested=self.provider in _backend._MOST_REQUESTED_PROVIDERS,
            num_reason_bits=NUM_FIXED_BITS + len(compiled.scalar_names),
            hard_weight=hard_weight)
        statics_host = statics_to_host(compiled)
        carry_host = carry_init_host(compiled)
        ptabs = pol_res = None
        if cp is not None:
            # mirror JaxBackend._schedule_on_device's staging recipe: the
            # policy tables overwrite the trivial custom-plugin rows (shapes
            # match exactly, so the replace is byte-identical for policies
            # without the corresponding feature), and the residency capture
            # records the interning those tables were built with
            from dataclasses import replace as _dc_replace

            config = _dc_replace(config, policy=cp.spec)
            snapshot = inc.to_snapshot()
            ptabs = build_policy_tables(cp, snapshot, pods, compiled, cols)
            if cp.saa_entries:
                config = _dc_replace(config, n_saa_doms=ptabs.n_saa_doms)
            pol_res = build_policy_residency(cp, snapshot, pods, compiled,
                                             ptabs)
            statics_host = statics_host._replace(
                label_ok=ptabs.label_ok, label_prio=ptabs.label_prio,
                image_score=ptabs.image_score, saa_dom=ptabs.saa_dom,
                sa_pin=ptabs.sa_pin, sa_val=ptabs.sa_val)
            if cp.spec.sa_enabled:
                carry_host = carry_host._replace(sa_lock=ptabs.sa_lock_init)
        self._decide_shard_layout(config)
        statics, carry0 = self._stage_resident(statics_host, carry_host)
        p = len(pods)
        xs_host = pad_infeasible_rows(pod_columns_to_host(cols),
                                      bucket_size(p) - p)
        xs = self._stage_xs(xs_host)
        if self._shard_mesh is not None and not self._shard_verified(
                config, statics, carry0, xs,
                stage_tree(statics_host), stage_tree(carry_host),
                stage_tree(xs_host)):
            # first-use verify disagreed: residency drops back to the
            # single-device layout for the process (_SHARD_AUTO.disabled)
            statics, carry0 = self._stage_resident(statics_host, carry_host)
            xs = stage_tree(xs_host)

        def dispatch():
            carry, placements, intervened = self._dispatch(
                config, carry0, statics, xs, pods, compiled)
            if not intervened:
                dev.adopt(inc, compiled, config, statics, carry,
                          plan_key=self._plan_key, ptabs=ptabs,
                          pol_res=pol_res)
            return placements, intervened

        return self._run_guarded(pods, "restage_scan", dispatch, reason)

    def _run_guarded(self, pods: List[Pod], path: str,
                     dispatch: Callable[[], Tuple[List[Placement], bool]],
                     restage_reason: Optional[str] = None) -> List[Placement]:
        """The chaos seam, mirroring JaxBackend.schedule: breaker-denied or
        faulted dispatches route to the host pipeline, probes and
        verify="all" dispatches are host-verified before placements are
        emitted, and ANY intervention invalidates residency (the next cycle
        re-arms from host truth).

        Classification is deferred to here so each off-stream cycle carries
        exactly ONE label — its final disposition: a restage cycle that the
        breaker denies counts as breaker_open, not as its structural reason
        plus breaker_open."""
        breaker = _backend._CHAOS["breaker"]
        if breaker is None:
            placements, intervened = dispatch()
            if restage_reason is not None:
                self._classify(restage_reason)
            if intervened:
                self.device.invalidate()
            self._note_path(path, len(pods))
            return placements
        from tpusim.chaos.engine import DeviceFault

        if not breaker.allow():
            flight.note_route("breaker_fallback", len(pods))
            return self._host_cycle(pods, "breaker_open")
        probing = breaker.probing
        try:
            placements, intervened = dispatch()
        except DeviceFault as exc:
            breaker.record_failure(f"{type(exc).__name__}: {exc}")
            flight.note_route("breaker_fallback", len(pods))
            return self._host_cycle(pods, "device_fault")
        if probing or _backend._CHAOS["verify"] == "all":
            expected = self._reference(pods)
            if placement_hash(placements) != placement_hash(expected):
                breaker.record_failure("device/host placement divergence")
                flight.note_route("breaker_fallback", len(pods))
                self.device.invalidate()
                self._classify("verify_divergence")
                self._note_path("host", len(pods))
                return expected
        breaker.record_success()
        if restage_reason is not None:
            self._classify(restage_reason)
        if intervened:
            self.device.invalidate()
        self._note_path(path, len(pods))
        return placements

    def _decide_shard_layout(self, config) -> None:
        """Re-decide the residency layout at restage time: TPUSIM_SHARDS>1
        (eligible, enough devices, not process-disabled) shards the twin's
        node axis; anything else is a classified fallback to the
        single-device layout. Restage is the only place the layout can
        change — stream cycles inherit whatever the twin was staged as."""
        self._shard_mesh = None
        self._shard_layout = None
        n_shards = _backend._shard_count()
        if n_shards <= 1 or _backend._SHARD_AUTO["disabled"]:
            return
        import jax

        from tpusim.jaxe.kernels import shard_route_eligible

        ok, why = shard_route_eligible(config)
        if ok and len(jax.devices()) < n_shards:
            ok, why = False, "device_count"
        if not ok:
            register().shard_fallback.inc(why)
            flight.note_fast_fallback(
                "shard_" + why, "stream residency staying single-device")
            log.info("stream residency staying single-device (%s)", why)
            return
        from tpusim.jaxe.sharding import make_mesh

        self._shard_mesh = make_mesh(n_shards, snap=1)

    def _stage_resident(self, statics_host, carry_host):
        """Stage the restage's host trees as the resident twin: default
        placement, or shard-even padded + node-sharded over the mesh."""
        mesh = self._shard_mesh
        if mesh is None:
            return stage_tree(statics_host), stage_tree(carry_host)
        from tpusim.jaxe.sharding import node_shardings, pad_node_axis

        n_shards = mesh.shape["node"]
        with flight.span("shard:stage") as ssp:
            st_h, ca_h, n_real = pad_node_axis(statics_host, carry_host,
                                               n_shards)
            st_sh, ca_sh = node_shardings(mesh)
            statics = stage_tree(st_h, st_sh)
            carry = stage_tree(ca_h, ca_sh)
            if ssp:
                ssp.set("shards", n_shards)
                ssp.set("nodes", n_real)
        per = st_h.alloc_cpu.shape[0] // n_shards
        self._shard_layout = {"shards": n_shards, "nodes": n_real,
                              "nodes_per_shard": per}
        m = register()
        m.shard_count.set(n_shards)
        for s in range(n_shards):
            m.shard_node_occupancy.set(
                str(s), max(0, min(n_real - s * per, per)))
        return statics, carry

    def _stage_xs(self, xs_host):
        """Pod columns are replicated on the sharded residency (every shard
        reduces every pod over its node block)."""
        mesh = self._shard_mesh
        if mesh is None:
            return stage_tree(xs_host)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return stage_tree(xs_host, NamedSharding(mesh, P()))

    def _shard_verified(self, config, statics, carry, xs,
                        statics_1d, carry_1d, xs_1d) -> bool:
        """First-use verify for the sharded residency, the same seam as the
        backend route: run the restage batch through BOTH programs on the
        fresh restage trees and compare choices/counts bit-for-bit. A
        match pins (shards, config) in _SHARD_AUTO (later restages and
        every stream cycle trust); a mismatch disables the sharded route
        process-wide and the caller re-stages single-device."""
        import os as _os

        mesh = self._shard_mesh
        n_shards = mesh.shape["node"]
        sig = (n_shards, config)
        if _os.environ.get("TPUSIM_SHARD_VERIFY") == "0" \
                or sig in _backend._SHARD_AUTO["verified_sigs"]:
            return True
        from dataclasses import replace as _dc_replace

        from tpusim.jaxe.kernels import schedule_scan, sharded_scan_fn

        _, sch, scnt, _ = sharded_scan_fn(
            _dc_replace(config, shard_axis="node"), mesh)(carry, statics,
                                                          xs)
        _, ch, cnt, _ = schedule_scan(config, carry_1d, statics_1d, xs_1d)
        if np.array_equal(np.asarray(sch), np.asarray(ch)) \
                and np.array_equal(np.asarray(scnt), np.asarray(cnt)):
            _backend._SHARD_AUTO["verified_sigs"].add(sig)
            flight.note_auto_transition("shard_pin", str(n_shards))
            return True
        _backend._SHARD_AUTO["disabled"] = True
        register().shard_count.set(0)
        flight.note_auto_transition("shard_verify_fail", str(n_shards))
        log.warning("sharded stream residency DISAGREES with the "
                    "single-device scan (shards=%d); disabling the sharded "
                    "route for this process", n_shards)
        self._shard_mesh = None
        self._shard_layout = None
        return False

    def _scan(self, config, carry, statics, xs):
        """The per-cycle donated scan: single-device, or the shard_map
        program when the twin is node-sharded (choices come back as GLOBAL
        node indices either way, bit-identical by the verify seam)."""
        mesh = self._shard_mesh
        if mesh is None:
            return schedule_scan_donated(config, carry, statics, xs)
        from dataclasses import replace as _dc_replace

        from tpusim.jaxe.kernels import sharded_scan_fn

        with flight.span("shard:scan", "device"):
            return sharded_scan_fn(_dc_replace(config, shard_axis="node"),
                                   mesh, donate=True)(carry, statics, xs)

    def _dispatch(self, config, carry, statics, xs, pods: List[Pod],
                  compiled) -> Tuple[object, List[Placement], bool]:
        """Run the donated scan under the chaos injector seam. Returns
        (final_carry, placements, intervened) — `intervened` flags a
        scripted corruption this dispatch (the emitted results may not
        match the device's true decisions, so residency must drop)."""
        metrics = register()
        injector = _backend._CHAOS["injector"]
        corrupt_kind = None
        if injector is not None:
            corrupt_kind = injector.begin_dispatch()  # may raise DeviceFault
        t0 = perf_counter()
        dsp = flight.span("device_dispatch", "device")
        with flight.profiled("tpusim:stream_scan"):
            final_carry, choices, counts, _adv = self._scan(
                config, carry, statics, xs)
        p = len(pods)
        choices = np.asarray(choices)[:p]
        counts = np.asarray(counts)[:p]
        if injector is not None:
            if corrupt_kind is not None:
                from tpusim.chaos.engine import DeviceInjector

                choices, counts = DeviceInjector.corrupt(corrupt_kind,
                                                         choices, counts)
            from tpusim.chaos.engine import DeviceOutputError

            n_nodes = len(compiled.statics.names)
            if choices.size and (int(choices.max()) >= n_nodes
                                 or int(choices.min()) < -1):
                raise DeviceOutputError(
                    f"device choice out of range [-1, {n_nodes})")
            if np.isnan(np.asarray(counts, dtype=np.float64)).any():
                raise DeviceOutputError("NaN in device reason counts")
        if dsp:
            dsp.set("pods", p)
            dsp.end()
        metrics.backend_dispatch_latency.observe(since_in_microseconds(t0))
        metrics.scheduling_algorithm_latency.observe(
            since_in_microseconds(t0))
        strings = reason_strings(compiled.scalar_names)
        with flight.span("decode_placements"):
            placements, _ = _backend.decode_placements(
                pods, choices, counts, compiled.statics.names, strings)
        # decision provenance (ISSUE 13): capture the decoded batch only —
        # no EngineConfig change, so residency/donation and the restage
        # classification are untouched (failure text is already the
        # byte-identical FitError rendering from decode_placements)
        provenance.capture(placements, "stream", cycle=self.cycles)
        # cluster analytics (ISSUE 14): one extra O(N) reduction dispatch
        # over columns the scan already owns — the scan program itself is
        # untouched, so placement hashes and restage classification are
        # pinned; one None-check when disabled
        analytics.capture(statics, final_carry,
                          len(compiled.statics.names), "stream",
                          cycle=self.cycles, names=compiled.statics.names,
                          mesh=self._shard_mesh)
        return final_carry, placements, corrupt_kind is not None

    def _host_cycle(self, pods: List[Pod], reason: str) -> List[Placement]:
        """Reference-backend cycle (chaos fallback or unsupported features):
        residency drops — the device never saw these binds."""
        self._classify(reason)
        self.device.invalidate()
        placements = self._reference(pods)
        self._note_path("host", len(pods))
        return placements

    def _reference(self, pods: List[Pod]) -> List[Placement]:
        placements = ReferenceBackend(
            provider=self.provider,
            hard_pod_affinity_symmetric_weight=self.hard_weight,
            policy=self.policy,
        ).schedule(pods, self.inc.to_snapshot())
        provenance.capture(placements, "stream_host", cycle=self.cycles)
        return placements

    # -- pipelined execution ----------------------------------------------

    def poll_placed(self) -> List[Placement]:
        """Block on the in-flight pipelined cycle's device choices (if
        any), fold its binds into the host picture, and return the
        placements that BOUND — the note_bound feed for a pipelined
        driver. MUST be called before the driver applies the next cycle's
        watch events, so the host picture evolves in the synchronous
        order. Full decode (fit errors, reason strings) stays deferred to
        the next schedule_pipelined/flush."""
        p = self._pending
        if p is None:
            return []
        if p.placements is not None:
            return [pl for pl in p.placements if pl.node_name]
        self._fold_binds(p)
        return p.bound

    def schedule_pipelined(self, pods: List[Pod]) -> Optional[List[Placement]]:
        """One pipelined decision cycle: dispatch THIS batch's device
        program without blocking on its result and return the PREVIOUS
        cycle's placements (None before any cycle completes). The decode
        of cycle N-1 overlaps cycle N's device execution. Emitted
        placements are byte-identical to schedule(): any off-stream
        condition (chaos seam armed, restage reason, no nodes) runs that
        cycle synchronously, buffered one cycle so emission order is
        preserved. Call flush() for the tail."""
        if not pods:
            return self.flush()
        prev_p, self._pending = self._pending, None
        if prev_p is not None and prev_p.placements is None:
            # defensive: a poll_placed-first driver has already folded
            self._fold_binds(prev_p)
        # the verify mode alone is inert (it only gates behavior once a
        # breaker is installed), so only live seams force the sync path
        chaos = (_backend._CHAOS["breaker"] is not None
                 or _backend._CHAOS["injector"] is not None)
        routed = None
        if not chaos and self.inc.nodes and not has_gangs(pods):
            # gang batches run off-stream: schedule() routes them through
            # the group driver's multi-pod cycle
            routed = self._route(pods)
        if routed is not None and routed[0] is None:
            self.cycles += 1
            with tracectx.activate(tracectx.start(
                    parent=tracectx.current())):
                t0 = perf_counter()
                cid = (self.persist.begin_cycle(pods)
                       if self.persist is not None else None)
                self._dispatch_async(pods, routed[1], t0, cid)
            register().stream_pipeline_depth.set(1.0)
            osp = flight.span("stream_overlap")
            prev = self._finalize(prev_p)
            if osp:
                osp.end()
            return prev
        # off-stream: drain the pipeline, then run this cycle through the
        # full synchronous path (chaos seam, restage classification)
        prev = self._finalize(prev_p)
        placements = self.schedule(pods, _routed=routed)
        self._pending = _PendingCycle(pods, placements=placements)
        register().stream_pipeline_depth.set(0.0)
        return prev

    def flush(self) -> List[Placement]:
        """Drain the in-flight (or sync-buffered) pipelined cycle and
        return its placements ([] when none): the tail of a pipelined run
        and the drain point for mid-run mode switches."""
        p, self._pending = self._pending, None
        out = self._finalize(p)
        register().stream_pipeline_depth.set(0.0)
        return out if out is not None else []

    def _fold_binds(self, p: _PendingCycle) -> None:
        """Synchronize on the pending cycle's choices and apply its binds
        to the host IncrementalCluster. The journal entries the fold-back
        creates are rolled back to the pre-fold mark: the scan already
        applied these binds to the resident carry with identical integer
        arithmetic (the same invariant the synchronous path relies on when
        it drains after _stream_cycle), and re-scattering them would both
        waste commit bandwidth and push the journal into bucket sizes the
        warmed jit cache has never traced. Interleaved watch deltas
        journaled BEFORE the fold sit inside the mark and survive."""
        if p.folded:
            return
        waited0 = perf_counter()
        choices = np.asarray(p.choices)[:len(p.pods)]
        waited = perf_counter() - waited0
        elapsed = max(waited0 - p.dispatched_at + waited, 1e-9)
        register().stream_overlap_fraction.set(max(0.0, 1.0 - waited / elapsed))
        p.choices = choices
        names = p.compiled.statics.names
        mark = self.inc.journal_mark()
        with self._persist_suppressed():
            for pod, c in zip(p.pods, choices):
                c = int(c)
                if c >= 0:
                    bound = bind_pod(pod, names[c])
                    self.inc.apply(MODIFIED, bound)
                    p.bound.append(Placement(pod=bound, node_name=names[c]))
        self.inc.journal_rollback(mark)
        p.folded = True
        if self.persist is not None and p.wal_cycle is not None:
            # journaled at fold time: cycle N's binds land BEFORE cycle
            # N+1's watch events, the order the host picture mutates —
            # under cycle N's trace context so the shipped frame links
            # back to the dispatching cycle, not the overlapping one
            with tracectx.activate(p.trace):
                self.persist.log_bind(p.wal_cycle, p.bound)

    def _finalize(self, p: Optional[_PendingCycle]
                  ) -> Optional[List[Placement]]:
        """Decode a pending cycle into its placement list (None for None):
        the deferred host half of a pipelined cycle, overlapping the next
        cycle's device execution when called from schedule_pipelined."""
        if p is None:
            return None
        if p.placements is not None:
            return p.placements
        with tracectx.activate(p.trace):
            self._fold_binds(p)
            counts = np.asarray(p.counts)[:len(p.pods)]
            strings = reason_strings(p.compiled.scalar_names)
            with flight.span("stream_decode"):
                placements, _ = _backend.decode_placements(
                    p.pods, p.choices, counts, p.compiled.statics.names,
                    strings, prebound=p.bound)
            p.placements = placements
            provenance.capture(placements, "stream",
                               cycle=p.wal_cycle if p.wal_cycle is not None
                               else self.cycles)
            self._note_path("pipelined", len(p.pods))
            if self.persist is not None and p.wal_cycle is not None:
                self.persist.log_emit(p.wal_cycle, placements)
            self._observe_cycle("pipelined", p.t0)
            return placements

    def _dispatch_async(self, pods: List[Pod], cols, t0: float,
                        wal_cycle: Optional[int] = None) -> None:
        """Commit pending deltas and launch the donated scan WITHOUT
        forcing its outputs — JAX's async dispatch returns futures, so the
        host is free to decode the previous cycle while the device runs.
        The scan's final carry is adopted immediately (a device-side
        future too)."""
        dev = self.device
        self._apply_statics_patch()
        dev.commit(self.inc, self._commit_sa_lock())
        p = len(pods)
        xs_host = pad_infeasible_rows(pod_columns_to_host(cols),
                                      bucket_size(p) - p)
        dsp = flight.span("device_dispatch", "device")
        with flight.profiled("tpusim:stream_scan"):
            final_carry, choices, counts, _adv = self._scan(
                dev.config, dev.carry, dev.statics,
                self._stage_xs(xs_host))
        if dsp:
            dsp.set("pods", p)
            dsp.end()
        dev.carry = final_carry
        # analytics rides the un-forced final carry: the reduction is
        # itself an async dispatch, so the pipeline's decode/device overlap
        # is preserved (nothing here blocks)
        analytics.capture(dev.statics, final_carry,
                          len(dev.compiled.statics.names), "stream",
                          cycle=self.cycles,
                          names=dev.compiled.statics.names,
                          mesh=self._shard_mesh)
        self._pending = _PendingCycle(pods, choices, counts, dev.compiled,
                                      t0, perf_counter(),
                                      wal_cycle=wal_cycle)

    # -- overlay what-if queries (ISSUE 19) -------------------------------

    def overlay_query(self, pods: List[Pod],
                      _path: str = "resident") -> Optional[List[Placement]]:
        """Answer a what-if query against the LIVE resident twin in
        O(scenario): fork the donated carry behind a journal mark,
        scatter-commit pending churn exactly like the next real cycle
        would (authoritative and idempotent — the restored journal makes
        that cycle's commit a byte-identical re-scatter), run the fused
        scan over the query batch, decode placements, and roll the carry
        back to host truth (kernels.overlay_restore_donated over the nodes
        the query bound, per-batch lanes restored from pre-mark copies).
        The query batch is never folded back: WAL, persistence, restage
        classification and the cycle chain are untouched, and placements
        are placement-hash-identical to staging inc.to_snapshot() plus the
        query through whatif.run_what_if (the stream-vs-restage parity
        contract applied to a batch that never binds).

        Returns None when the query cannot ride the resident twin — no
        residency, a restage-class change (novel scalar/signature/group/
        policy columns), gang semantics, or a chaos-seam intervention
        mid-query — and the caller (serve.ServeExecutor) falls back to
        the staged path. A restage reason discovered here is latched via
        force_restage so the next real cycle classifies it exactly as
        _route would have."""
        if not pods:
            return []
        t0 = perf_counter()
        routed = self._overlay_route(pods)
        if isinstance(routed, str):
            register().overlay_fallback.inc(routed)
            flight.note_route("overlay_fallback", len(pods))
            return None
        placements = self._overlay_dispatch(pods, routed)
        if placements is None:
            return None
        m = register()
        m.overlay_queries.inc(_path)
        ctx = tracectx.current()
        m.overlay_latency.observe(
            since_in_microseconds(t0),
            exemplar=ctx.trace_id if ctx is not None else None)
        return placements

    def _overlay_route(self, pods: List[Pod]):
        """The overlay twin of _route: prove the resident arrays can serve
        the query batch WITHOUT perturbing the live session. Returns the
        batch's remapped PodColumns on success, else the fallback reason.
        Stricter than _route — any condition a real cycle would restage
        over is a refusal here (the staged path answers instead), plus the
        configs whose carry fields have no rollback path."""
        inc = self.inc
        dev = self.device
        if self._pending is not None and self._pending.placements is None:
            # pipelined in-flight cycle: fold its binds into the host
            # picture first so the mark below brackets the same logical
            # state the resident carry already holds
            self._fold_binds(self._pending)
        if self._forced is not None or self.always_restage:
            return "forced_restage"
        if not inc.nodes:
            return "no_nodes"
        if has_gangs(pods):
            return "gang_semantics"
        breaker = _backend._CHAOS["breaker"]
        if breaker is not None and (breaker.probing
                                    or _backend._CHAOS["verify"] == "all"
                                    or not breaker.allow()):
            # probe/verify cycles carry a host-parity obligation the
            # overlay cannot discharge; an open breaker denies dispatch
            return "breaker_open"
        reason = dev.residency_miss(inc, self._plan_key)
        if reason is not None:
            return reason
        if dev.config.has_interpod or dev.config.has_maxpd:
            # presence_dom / used_vols have no overlay rollback path
            return "no_rollback_path"
        n_scalars = len(inc._scalar_names)
        cols, key_lists = inc._batch_columns(pods)
        if len(inc._scalar_names) != n_scalars:
            # the QUERY widened the scalar universe: un-note the synthetic
            # names (no live object references them — _note_scalar only
            # appends) so the live session keeps its resident width
            for name in inc._scalar_names[n_scalars:]:
                del inc._scalar_idx[name]
            del inc._scalar_names[n_scalars:]
            if inc._statics is not None:
                inc._statics.alloc_scalar = \
                    inc._statics.alloc_scalar[:, :n_scalars]
            if inc._dyn is not None:
                inc._dyn.used_scalar = inc._dyn.used_scalar[:, :n_scalars]
            return "scalar_set"
        reason = dev.remap_signatures(inc, cols, key_lists)
        if reason is not None:
            return reason
        if not inc.assign_group_ids(cols, pods):
            return "group_shape"
        if self.cp is not None:
            reason = remap_policy_columns(self.cp, dev.pol_res, pods, cols)
            if reason is not None:
                return reason
        reason = self._prepare_statics_delta()
        if reason is not None:
            # the column journal cannot land as a scatter: the next REAL
            # cycle must restage for it, classified exactly as _route
            # would have classified it
            self.force_restage(reason)
            return reason
        return cols

    def _overlay_dispatch(self, pods: List[Pod],
                          cols) -> Optional[List[Placement]]:
        """The mark → commit → scan → decode → rollback bracket. Pending
        churn is early-committed (the next real commit re-scatters the
        same authoritative rows, so the cycle chain is byte-unchanged);
        the per-batch lanes (sa_lock/rr) are saved host-side before the
        donation destroys them and restored verbatim on rollback, so with
        an empty journal the post-rollback carry is byte-identical to
        pre-mark. Chaos interventions (DeviceFault, scripted corruption)
        drop the overlay: journal rolled back, residency invalidated, None
        returned — the next real cycle re-arms from host truth."""
        inc = self.inc
        dev = self.device
        injector = _backend._CHAOS["injector"]
        breaker = _backend._CHAOS["breaker"]
        mark = inc.journal_mark()
        rr_save = np.asarray(dev.carry.rr)
        sa_save = np.asarray(dev.carry.sa_lock)
        try:
            self._apply_statics_patch()
            dev.commit(inc, self._commit_sa_lock())
            corrupt_kind = (injector.begin_dispatch()
                            if injector is not None else None)
            p = len(pods)
            xs_host = pad_infeasible_rows(pod_columns_to_host(cols),
                                          bucket_size(p) - p)
            with flight.span("overlay_scan", "device"):
                final_carry, choices, counts, _adv = self._scan(
                    dev.config, dev.carry, dev.statics,
                    self._stage_xs(xs_host))
            dev.carry = final_carry
            choices = np.asarray(choices)[:p]
            counts = np.asarray(counts)[:p]
        except Exception as exc:
            inc.journal_rollback(mark)
            dev.invalidate()
            from tpusim.chaos.engine import DeviceFault
            if isinstance(exc, DeviceFault):
                if breaker is not None:
                    breaker.record_failure(f"{type(exc).__name__}: {exc}")
                register().overlay_fallback.inc("device_fault")
                flight.note_route("overlay_fallback", len(pods))
                return None
            raise
        if corrupt_kind is not None:
            # the reported choices may not be the device's true decisions,
            # so the row-wise restore below cannot be trusted to cover
            # every bound node — drop residency instead (the next real
            # cycle restages from host truth, placements unchanged by the
            # restage-parity contract)
            inc.journal_rollback(mark)
            dev.invalidate()
            register().overlay_fallback.inc("corruption")
            flight.note_route("overlay_fallback", len(pods))
            return None
        self._overlay_rollback(cols, choices, mark, sa_save, rr_save)
        if breaker is not None:
            breaker.record_success()
        strings = reason_strings(dev.compiled.scalar_names)
        with flight.span("overlay_decode"):
            placements, _ = _backend.decode_placements(
                pods, choices, counts, dev.compiled.statics.names, strings)
        provenance.capture(placements, "overlay", cycle=self.cycles)
        return placements

    def _overlay_rollback(self, cols, choices: np.ndarray, mark,
                          sa_save: np.ndarray, rr_save: np.ndarray) -> None:
        """Scatter the query's bound rows back to host truth — the exact
        gather commit() performs, restricted to the nodes the query bound
        (the query never touched inc, so inc._dyn/_presence still hold the
        pre-query authoritative values) — and restore the journal mark.
        Rides the same pow2 buckets as commit, so warm query shapes reuse
        one compiled restore program."""
        inc = self.inc
        dev = self.device
        bound = sorted({int(c) for c in choices if int(c) >= 0})
        dyn = inc._ensure_dyn()
        idx = np.fromiter(bound, dtype=np.int32, count=len(bound))
        idx = _pad_index(idx, bucket_size(max(len(idx), 1)))
        rows = DeltaRows(
            used_cpu=dyn.used_cpu[idx], used_mem=dyn.used_mem[idx],
            used_gpu=dyn.used_gpu[idx], used_eph=dyn.used_eph[idx],
            used_scalar=dyn.used_scalar[idx],
            nonzero_cpu=dyn.nonzero_cpu[idx],
            nonzero_mem=dyn.nonzero_mem[idx],
            pod_count=dyn.pod_count[idx])
        cell_list = sorted({(int(cols.group_id[j]), int(c))
                            for j, c in enumerate(choices) if int(c) >= 0})
        gid = np.fromiter((g for g, _ in cell_list), dtype=np.int32,
                          count=len(cell_list))
        nid = np.fromiter((n for _, n in cell_list), dtype=np.int32,
                          count=len(cell_list))
        size = bucket_size(max(len(gid), 1))
        gid, nid = _pad_index(gid, size), _pad_index(nid, size)
        if inc._presence is not None:
            val = inc._presence[gid, nid].astype(np.int32)
        else:
            val = np.zeros(size, np.int32)
        sp = flight.span("overlay_rollback", "device")
        dev.carry = overlay_restore_donated(dev.carry, idx, rows, gid, nid,
                                            val, sa_save, rr_save)
        if sp:
            sp.set("rows", int(len(bound)))
            sp.end()
        inc.journal_rollback(mark)

    # -- accounting -------------------------------------------------------

    def _classify(self, reason: str, detail: Optional[str] = None) -> None:
        self.restage_counts[reason] = self.restage_counts.get(reason, 0) + 1
        flight.note_stream_restage(reason, detail)

    def _note_path(self, path: str, pods: int) -> None:
        self.path_counts[path] = self.path_counts.get(path, 0) + 1
        self._last_path = path
        flight.note_stream_cycle(path, pods)

    def _observe_cycle(self, path: str, t0: float) -> None:
        """Per-cycle latency, twice: the legacy e2e histogram (unchanged
        semantics) and the per-path stream histogram (ISSUE 9). When a
        trace context is active the cycle's trace id rides the histograms
        as an exemplar (ISSUE 20): the slow-cycle spike on a dashboard
        resolves to the exact flight-recorder trace that produced it."""
        us = since_in_microseconds(t0)
        ctx = tracectx.current()
        ex = ctx.trace_id if ctx is not None else None
        m = register()
        m.e2e_scheduling_latency.observe(us, exemplar=ex)
        m.stream_cycle_latency.observe(path, us, exemplar=ex)
        slo.observe_cycle(path, us)

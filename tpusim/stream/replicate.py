"""WAL-shipping hot standby + chaos-driven leader failover (ISSUE 18).

The WAL (stream.persist) made one process recoverable; this module makes
the control plane replicated. A leader's StreamPersistence grows two
seams (``on_append`` / ``on_checkpoint``) that a ``WalShipper`` drains
over a length-prefixed socket protocol to a ``FollowerTwin`` — a live
standby that does NOT merely store the records: it replays every shipped
cycle through its OWN scheduler (the same incremental replay discipline
as ``recover_stream_session``), so at any instant the follower holds a
warm host picture, a warm device twin, and a placement-hash chain it can
cross-check byte-for-byte against the leader's emissions. Divergence
latches: a follower whose own deterministic decisions ever disagree with
a shipped emission refuses promotion.

Wire protocol — 4-byte big-endian length prefix + one JSON object:

    {"t":"hello","next":N,"chain":H}      follower -> shipper on connect:
                                          resume from sequence N
    {"t":"rec","seq":N,"rec":R,"ofs":B}   one WAL record; B = the byte
                                          offset AFTER it in the leader's
                                          journal (the follower's applied
                                          position, and the promotion
                                          replay's tail_wal resume point)
    {"t":"ckpt","seq":N,"meta":M}         checkpoint manifest (sans
                                          snapshot): chain cross-check
                                          anchor + shard-layout/durability
                                          announcements
    {"t":"ack","seq":N,"chain":H}         follower -> shipper: applied
                                          through N, chain head H
    {"t":"snap","seq":N,"meta":M,"open":B}  shipper -> follower, only
                                          when the hello carried
                                          ``"bootstrap": true`` with
                                          next=0 (ISSUE 19 late join):
                                          the FULL checkpoint manifest
                                          (snapshot included) plus any
                                          admitted-but-unemitted batches
                                          below its WAL offset; the
                                          follower rebuilds from it and
                                          resumes the stream at N+1

Sequence numbers are assigned by the shipper in append order; acks are
cumulative. Reconnect-with-resume is the follower's ``hello``: the
shipper retains its frame log and resends from ``next`` after any
connection loss, so a flapping link degrades to lag, never to loss.

Failover is chaos-driven: a ``FailoverController`` watches the leader's
``/healthz`` (or any probe callable), and on death promotes the FRESHEST
non-diverged follower. Promotion replays only the unshipped tail of the
leader's durable WAL (``tail_wal`` from the follower's applied offset),
re-scheduling crash-tail cycles exactly like cold recovery — but from a
warm twin, so the replayed-record count is the replication lag, not the
checkpoint interval. The byte-identical chain head is the promotion
invariant, checked against the leader's last durable checkpoint manifest
in BOTH directions (follower ahead: chain history; follower behind: the
fold must pass through the manifest's chain during tail replay).
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from dataclasses import dataclass, field
from time import monotonic, perf_counter, sleep
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tpusim.api.types import Pod
from tpusim.backends import Placement, bind_pod, placement_hash
from tpusim.engine.providers import DEFAULT_PROVIDER
from tpusim.framework.metrics import register, since_in_microseconds
from tpusim.framework.store import MODIFIED
from tpusim.obs import recorder as flight
from tpusim.obs import tracectx
from tpusim.obs.recorder import flow_end, flow_start
from tpusim.stream.persist import (
    _LOADERS,
    StreamPersistence,
    chain_fold,
    tail_wal,
)

_FRAME_LIMIT = 64 << 20   # a single frame larger than this is corruption
_CKPT_FIELDS = ("cycle", "next_cycle", "chain", "wal_offset",
                "wal_records", "shard_layout", "durability", "plan_sig")


class ReplicationError(RuntimeError):
    """A broken replication stream (oversized frame, protocol garbage)."""


class PromotionRefused(RuntimeError):
    """The candidate follower cannot become leader: its replayed chain
    diverged from the leader's durable truth (or it never attached)."""


# -- module-level replication status (the /healthz seam) -------------------
#
# obs.server.health_payload reads this lazily: role transitions and the
# shipper's live lag land here so a scrape of EITHER side of the pair is
# self-describing. Process-scoped by design — in-process test pairs share
# it, which mirrors sharing the metrics registry.

_state_lock = threading.Lock()
_state: Dict[str, object] = {"role": "none", "replication_lag_records": 0,
                             "last_shipped_seq": -1}


def set_role(role: str) -> None:
    """leader | follower | candidate | none."""
    with _state_lock:
        _state["role"] = role
    register().replication_role.set_info(role=role)


def _set_state(**kw) -> None:
    with _state_lock:
        _state.update(kw)


def get_status() -> Dict[str, object]:
    with _state_lock:
        return dict(_state)


# -- framing ---------------------------------------------------------------

def _send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def _read_frame(reader) -> Optional[dict]:
    hdr = reader.read(4)
    if len(hdr) < 4:
        return None
    n = struct.unpack(">I", hdr)[0]
    if n > _FRAME_LIMIT:
        raise ReplicationError(f"replication frame of {n} bytes exceeds "
                               f"the {_FRAME_LIMIT}-byte limit")
    data = reader.read(n)
    if len(data) < n:
        return None
    return json.loads(data)


# -- leader side -----------------------------------------------------------

class WalShipper:
    """Streams a StreamPersistence's WAL records to one follower.

    Hooks ``persist.on_append`` / ``persist.on_checkpoint``: every
    durable record is framed with a sequence number and enqueued
    synchronously (the crash model stays exact — the record that kills
    the leader is enqueued before the crash fires); a sender thread
    drains the queue to the follower and an ack reader advances the
    cumulative acked sequence. The frame log is retained for
    reconnect-with-resume. ``drain()`` blocks until the follower has
    acked everything — the deterministic barrier the tests and the
    graceful-shutdown path use; a crashing leader simply never drains.
    """

    def __init__(self, persist: StreamPersistence,
                 address: Tuple[str, int], *,
                 retry_interval: float = 0.02):
        self.persist = persist
        self.address = address
        self.retry_interval = retry_interval
        self._frames: List[dict] = []        # seq == index
        self._meta: List[Tuple[float, int, bool]] = []  # (t_enq, ofs, is_rec)
        self._cond = threading.Condition()
        self._acked = -1
        self._acked_ofs = 0
        self._acked_chain = ""
        self._end_ofs = 0
        self._recs = 0
        self._recs_acked = 0
        self._stop = False
        persist.on_append = self._on_append
        persist.on_checkpoint = self._on_checkpoint
        set_role("leader")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpusim-wal-shipper")
        self._thread.start()

    # persistence hooks — called on the scheduling thread, never block

    def _on_append(self, rec: dict, kind: str, cycle: int,
                   start: int, end: int) -> None:
        # the hooks fire synchronously on the scheduling thread, so the
        # active trace context IS the originating cycle's (ISSUE 20): the
        # frame carries it across the socket and the follower's replay
        # spans join the leader's trace. The flow `s` is emitted once per
        # ENQUEUE, not per send — reconnect resends must not duplicate it.
        ctx = tracectx.current()
        with self._cond:
            seq = len(self._frames)
            fr = {"t": "rec", "seq": seq, "rec": rec, "ofs": end}
            if ctx is not None:
                fr["tr"] = ctx.to_wire()
            self._frames.append(fr)
            self._meta.append((perf_counter(), end, True))
            self._end_ofs = end
            self._recs += 1
            self._cond.notify_all()
        if ctx is not None:
            flow_start("wal:ship", str(seq), cat="wal", site="wal")
        self._publish_lag()

    def _on_checkpoint(self, meta: dict) -> None:
        slim = {k: meta.get(k) for k in _CKPT_FIELDS}
        ctx = tracectx.current()
        with self._cond:
            seq = len(self._frames)
            fr = {"t": "ckpt", "seq": seq, "meta": slim}
            if ctx is not None:
                fr["tr"] = ctx.to_wire()
            self._frames.append(fr)
            self._meta.append((perf_counter(), int(meta.get("wal_offset", 0)),
                               False))
            self._cond.notify_all()
        if ctx is not None:
            flow_start("wal:ship", str(seq), cat="wal", site="wal")
        self._publish_lag()

    def _publish_lag(self) -> None:
        reg = register()
        with self._cond:
            lag_records = self._recs - self._recs_acked
            lag_bytes = max(0, self._end_ofs - self._acked_ofs)
            oldest = (self._meta[self._acked + 1][0]
                      if self._acked + 1 < len(self._meta) else None)
        reg.replication_lag_records.set(float(lag_records))
        reg.replication_lag_bytes.set(float(lag_bytes))
        reg.replication_lag_seconds.set(
            max(0.0, perf_counter() - oldest) if oldest is not None else 0.0)
        _set_state(replication_lag_records=lag_records)

    # sender / ack machinery

    def _connect(self) -> Optional[socket.socket]:
        while True:
            with self._cond:
                if self._stop:
                    return None
            try:
                return socket.create_connection(self.address, timeout=5.0)
            except OSError:
                with self._cond:
                    self._cond.wait(self.retry_interval)

    def _run(self) -> None:
        while True:
            sock = self._connect()
            if sock is None:
                return
            try:
                reader = sock.makefile("rb")
                hello = _read_frame(reader)
                if hello is None or hello.get("t") != "hello":
                    continue
                cursor = int(hello.get("next", 0))
                rec_ = flight.get_recorder()
                if rec_ is not None and "clk" in hello:
                    # the clock-alignment handshake (tools/trace_merge.py):
                    # the follower's recorder-relative reading at hello
                    # send, paired with OUR reading at hello receive —
                    # the shared instant both timelines can be shifted to
                    rec_.set_anchor("peer_clk_us", float(hello["clk"]))
                    rec_.set_anchor("peer_clk_rx_us")
                if hello.get("bootstrap") and cursor == 0:
                    snap_fr, cursor = self._bootstrap_frame()
                    if snap_fr is not None:
                        _send_frame(sock, snap_fr)
                ack_thread = threading.Thread(
                    target=self._ack_loop, args=(reader,), daemon=True)
                ack_thread.start()
                while True:
                    with self._cond:
                        while cursor >= len(self._frames) and not self._stop:
                            self._cond.wait(0.1)
                        if self._stop:
                            return
                        batch = self._frames[cursor:]
                    for fr in batch:
                        _send_frame(sock, fr)
                        cursor = fr["seq"] + 1
                        register().replication_last_shipped_seq.set(
                            float(fr["seq"]))
                        _set_state(last_shipped_seq=fr["seq"])
            except (OSError, ReplicationError):
                continue   # reconnect; the follower's hello resumes us
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

    def _bootstrap_frame(self) -> Tuple[Optional[dict], int]:
        """Snapshot bootstrap for a late-joining follower (ISSUE 19).

        A follower that was not up at leader start has no cycle-0
        snapshot to replay from, and the shipper's frame log only goes
        back to its own attach. Instead of replaying history, ship the
        leader's latest durable checkpoint manifest (which carries the
        full host snapshot) plus the WAL byte offset it is consistent
        with: the follower rebuilds a warm session from the manifest and
        resumes the live stream from the first frame PAST that offset.
        Admitted-but-unemitted batches below the cut (a pipelined
        in-flight cycle) ride along so the later bind/emit records find
        their arrivals. Assumes the shipper was attached before any
        record landed past the manifest's offset — true for the standard
        wiring where attach() cuts the genesis checkpoint and the
        shipper is constructed immediately after.
        """
        try:
            with open(self.persist.checkpoint_path, "r",
                      encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None, 0
        wal_ofs = int(manifest.get("wal_offset", 0))
        with self._cond:
            frames = list(self._frames)
            meta = list(self._meta)
        cursor = len(frames)
        for i, (_t_enq, ofs, is_rec) in enumerate(meta):
            if is_rec and ofs > wal_ofs:
                cursor = i
                break
        emitted = {int(fr["rec"]["c"]) for fr in frames[:cursor]
                   if fr["t"] == "rec" and fr["rec"]["k"] == "emit"}
        open_batches = [[int(fr["rec"]["c"]), fr["rec"]["pods"]]
                        for fr in frames[:cursor]
                        if fr["t"] == "rec" and fr["rec"]["k"] == "batch"
                        and int(fr["rec"]["c"]) not in emitted]
        return ({"t": "snap", "seq": cursor - 1, "meta": manifest,
                 "open": open_batches}, cursor)

    def _ack_loop(self, reader) -> None:
        try:
            while True:
                fr = _read_frame(reader)
                if fr is None:
                    return
                if fr.get("t") != "ack":
                    continue
                seq = int(fr["seq"])
                with self._cond:
                    if seq > self._acked:
                        for s in range(self._acked + 1, seq + 1):
                            t_enq, ofs, is_rec = self._meta[s]
                            if is_rec:
                                self._recs_acked += 1
                                register().replication_ship_latency.observe(
                                    since_in_microseconds(t_enq))
                        self._acked = seq
                        self._acked_ofs = self._meta[seq][1]
                    self._acked_chain = str(fr.get("chain", ""))
                    self._cond.notify_all()
                self._publish_lag()
        except (OSError, ValueError, ReplicationError):
            return

    # public surface

    @property
    def acked_seq(self) -> int:
        with self._cond:
            return self._acked

    @property
    def acked_chain(self) -> str:
        with self._cond:
            return self._acked_chain

    def lag_records(self) -> int:
        with self._cond:
            return self._recs - self._recs_acked

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every enqueued frame is acked (or timeout)."""
        deadline = monotonic() + timeout
        with self._cond:
            while self._acked < len(self._frames) - 1:
                remaining = deadline - monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(0.1, remaining))
        self._publish_lag()
        return True

    def close(self, *, drain: bool = True, timeout: float = 30.0) -> bool:
        """Detach from the persistence and stop the sender. drain=False
        models leader death: whatever the wire has not carried yet stays
        unshipped, and only the durable WAL knows the tail."""
        drained = self.drain(timeout) if drain else False
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self.persist.on_append == self._on_append:
            self.persist.on_append = None
        if self.persist.on_checkpoint == self._on_checkpoint:
            self.persist.on_checkpoint = None
        self._thread.join(timeout=5.0)
        return drained


# -- follower side ---------------------------------------------------------

@dataclass
class PromotionReport:
    """What a promotion replayed, and what it cost."""

    resume_cycle: int = 0         # first cycle the driver runs post-failover
    tail_records: int = 0         # WAL records replayed past applied_ofs
    applied_records: int = 0      # records the follower had applied live
    recomputed: List[int] = field(default_factory=list)
    settled_live: List[int] = field(default_factory=list)
    chain: str = ""
    wal_records: int = 0
    replay_s: float = 0.0
    rto_s: float = 0.0            # stamped by the FailoverController
    violations: List[str] = field(default_factory=list)


class FollowerTwin:
    """A live standby: applies shipped WAL records by replaying each
    cycle through its own StreamSession.

    Apply discipline (the WAL's ordering invariants make this exact in
    both the sync and pipelined drivers):

      ev(c)    -> apply to the host picture immediately (ev records for
                  cycle c always precede batch(c), and never interleave
                  into an open cycle)
      batch(c) -> buffer the arrival pods
      bind(c)  -> the leader folded cycle c: schedule batch c through
                  OUR scheduler now (bind(c) precedes any cycle-c+1
                  record, so the host pictures align), fold our own
                  binds, remember our placements
      emit(c)  -> cross-check our placement hash against the shipped
                  one; fold the chain; divergence latches promotion off
      ckpt     -> chain-history cross-check + shard-layout restage
                  announcement

    The twin therefore stays one warm scheduler, not a cold journal:
    promotion replays only the unshipped tail.
    """

    def __init__(self, snapshot=None, *, incremental=None,
                 provider: str = DEFAULT_PROVIDER, policy=None,
                 always_restage: bool = False,
                 listen: Tuple[str, int] = ("127.0.0.1", 0),
                 bootstrap: bool = False):
        from tpusim.stream.runtime import StreamSession

        self._provider = provider
        self._policy = policy
        self._always_restage = always_restage
        # late join (ISSUE 19): ask the shipper for the leader's latest
        # checkpoint manifest + WAL offset in the hello exchange instead
        # of requiring the leader's cycle-0 snapshot source; the session
        # below starts empty and is rebuilt from the shipped manifest
        self._bootstrap = bootstrap
        self.bootstrapped = False
        self.session = StreamSession(snapshot, incremental=incremental,
                                     provider=provider, policy=policy,
                                     always_restage=always_restage)
        self.batches: Dict[int, List[Pod]] = {}
        self.bound_by_cycle: Dict[int, List[Tuple[str, str]]] = {}
        self.events_applied: Dict[int, int] = {}
        self.chain = ""
        self.chain_history: Dict[int, str] = {0: ""}
        self.cycles_emitted = 0
        self.decisions = 0
        self.scheduled = 0
        self.next_cycle = 0
        self.applied_seq = -1
        self.applied_ofs = 0
        self.wal_records_applied = 0
        self.diverged: Optional[str] = None
        self.shard_layout: Optional[dict] = None
        self.durability: Optional[dict] = None
        self.promoted = False
        self.persist: Optional[StreamPersistence] = None
        self._live_pending: Dict[int, List[Placement]] = {}
        self._lock = threading.RLock()
        self._stop = False
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(listen)
        self._server.listen(1)
        self._server.settimeout(0.2)
        self.address = self._server.getsockname()
        set_role("follower")
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="tpusim-follower")
        self._thread.start()

    # -- receive loop -----------------------------------------------------

    def _serve(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with conn:
                try:
                    self._pump(conn)
                except (OSError, ValueError, ReplicationError):
                    continue   # shipper reconnects and resumes

    def _pump(self, conn: socket.socket) -> None:
        reader = conn.makefile("rb")
        with self._lock:
            hello = {"t": "hello", "next": self.applied_seq + 1,
                     "chain": self.chain}
            rec_ = flight.get_recorder()
            if rec_ is not None:
                # clock-alignment handshake: our recorder-relative reading
                # at hello send; the shipper pins it (plus its own receive
                # reading) as anchors for tools/trace_merge.py
                hello["clk"] = rec_.now_us()
                rec_.set_anchor("hello_tx_us", hello["clk"])
            if self._bootstrap and self.applied_seq < 0:
                hello["bootstrap"] = True
            _send_frame(conn, hello)
        while True:
            fr = _read_frame(reader)
            if fr is None:
                return
            t0 = perf_counter()
            if fr.get("t") == "snap":
                with self._lock:
                    if self._stop:
                        return
                    self._apply_bootstrap(fr)
                    seq, chain = self.applied_seq, self.chain
                if seq >= 0:
                    _send_frame(conn, {"t": "ack", "seq": seq,
                                       "chain": chain})
                continue
            seq = int(fr.get("seq", -1))
            ctx = tracectx.TraceContext.from_wire(fr.get("tr"))
            with self._lock:
                if self._stop:
                    return
                if seq <= self.applied_seq:
                    continue   # duplicate after a resume race
                if seq != self.applied_seq + 1:
                    return     # gap: drop; the next hello renegotiates
                # replay under the LEADER's trace context (ISSUE 20): the
                # apply span — and every scheduler span the replayed cycle
                # emits beneath it — carries the originating cycle's trace
                # id, and the flow `f` closes the leader's `s` arrow. The
                # dedup/gap guards above already ran, so a reconnect
                # resend never lands a second `f` for the same seq.
                with tracectx.activate(ctx), \
                        flight.span("replicate:apply") as asp:
                    if asp:
                        asp.set("seq", seq)
                        asp.set("frame", str(fr.get("t")))
                    if ctx is not None:
                        flow_end("wal:ship", str(seq), cat="wal")
                    if fr.get("t") == "rec":
                        self._apply_record(fr["rec"], int(fr.get("ofs", 0)))
                    elif fr.get("t") == "ckpt":
                        self._apply_ckpt(fr.get("meta") or {})
                self.applied_seq = seq
                chain = self.chain
            register().replication_apply_latency.observe(
                since_in_microseconds(t0))
            _send_frame(conn, {"t": "ack", "seq": seq, "chain": chain})

    def _apply_bootstrap(self, fr: dict) -> None:
        """Late join: rebuild the twin from the shipped checkpoint
        manifest instead of a cycle-0 snapshot, then resume the live
        stream from the first frame past the manifest's WAL offset."""
        if not self._bootstrap or self.applied_seq >= 0:
            return   # unsolicited or duplicate snap frame
        from tpusim.api.snapshot import ClusterSnapshot
        from tpusim.stream.runtime import StreamSession

        meta = fr.get("meta") or {}
        snapshot = ClusterSnapshot.from_obj(meta["snapshot"])
        self.session = StreamSession(snapshot, provider=self._provider,
                                     policy=self._policy,
                                     always_restage=self._always_restage)
        self.chain = str(meta.get("chain", ""))
        ck_cycle = int(meta.get("cycle", 0))
        self.cycles_emitted = ck_cycle
        self.chain_history = {0: "", ck_cycle: self.chain}
        self.decisions = int(meta.get("decisions", 0))
        self.scheduled = int(meta.get("scheduled", 0))
        self.next_cycle = int(meta.get("next_cycle", 0))
        self.shard_layout = meta.get("shard_layout") or self.shard_layout
        self.durability = meta.get("durability") or self.durability
        self.applied_ofs = int(meta.get("wal_offset", 0))
        self.wal_records_applied = int(meta.get("wal_records", 0))
        self.applied_seq = int(fr.get("seq", -1))
        self.batches = {int(c): [Pod.from_obj(o) for o in pods]
                        for c, pods in (fr.get("open") or [])}
        for c in self.batches:
            self.next_cycle = max(self.next_cycle, c + 1)
        self.bound_by_cycle = {}
        self._live_pending = {}
        self.bootstrapped = True
        flight.note_route("follower_bootstrap", len(self.batches))

    # -- read replica (ISSUE 19) -------------------------------------------

    def overlay_query(self, pods) -> Optional[List[Placement]]:
        """Serve a live what-if from the standby's warm twin: overlay
        queries are read-only (mark -> scan -> rollback leaves the carry
        byte-identical to pre-mark), so a non-diverged follower answers
        them without perturbing replay. Serialises with the apply loop
        under the twin lock; returns None when the replica cannot answer
        (diverged, stopped, or the overlay itself refused)."""
        with self._lock:
            if self._stop or self.diverged is not None:
                register().overlay_fallback.inc("replica_unavailable")
                return None
            return self.session.overlay_query(pods, _path="follower")

    def _diverge(self, msg: str) -> None:
        if self.diverged is None:
            self.diverged = msg
            register().replication_divergence.inc()
            flight.note_fault("replication_divergence", {"detail": msg})

    def _apply_record(self, rec: dict, end_ofs: int) -> None:
        self.wal_records_applied += 1
        self.applied_ofs = max(self.applied_ofs, end_ofs)
        if self.diverged is not None:
            return   # latched: keep acking so the leader is not wedged,
            #          but the twin stops mutating (it can never promote)
        k, c = rec["k"], int(rec["c"])
        if k == "ev":
            self.session.apply(rec["t"], _LOADERS[rec["r"]](rec["o"]))
            self.events_applied[c] = self.events_applied.get(c, 0) + 1
        elif k == "batch":
            self.batches[c] = [Pod.from_obj(o) for o in rec["pods"]]
            self.next_cycle = max(self.next_cycle, c + 1)
        elif k == "bind":
            pods = self.batches.get(c)
            if pods is None:
                self._diverge(f"bind record for unknown batch {c}")
                return
            placements = self.session.schedule(pods)
            bound = sorted((pl.pod.key(), pl.node_name)
                           for pl in placements if pl.node_name)
            theirs = sorted((key, node) for key, node in rec["b"])
            if bound != theirs:
                self._diverge(
                    f"bind divergence at cycle {c}: our scheduler bound "
                    f"{len(bound)} pods, the leader bound {len(theirs)} "
                    "(or to different nodes)")
                return
            self.bound_by_cycle[c] = list(bound)
            self._live_pending[c] = placements
        elif k == "emit":
            placements = self._live_pending.pop(c, None)
            if placements is None:
                self._diverge(f"emit record for cycle {c} the follower "
                              "never replayed")
                return
            mine = placement_hash(placements)
            if mine != rec["h"]:
                self._diverge(
                    f"placement hash diverges at cycle {c}: follower "
                    f"{mine[:16]} vs leader {rec['h'][:16]}")
                return
            self.chain = chain_fold(self.chain, rec["h"])
            self.decisions += int(rec["n"])
            self.scheduled += int(rec["s"])
            self.cycles_emitted += 1
            self.chain_history[self.cycles_emitted] = self.chain

    def _apply_ckpt(self, meta: dict) -> None:
        if self.diverged is not None:
            return
        layout = meta.get("shard_layout")
        ours = self.session._shard_layout
        if layout and ours and \
                layout.get("shards") != ours.get("shards"):
            # the leader announced a different node-mesh partitioning:
            # restage the twin per the announced layout before the next
            # replayed cycle (classified like any other restage)
            self.session.force_restage("replicated")
        self.shard_layout = layout or self.shard_layout
        self.durability = meta.get("durability") or self.durability
        ck_cycle = meta.get("cycle")
        ck_chain = meta.get("chain")
        if ck_cycle is not None and ck_chain is not None:
            mine = self.chain_history.get(int(ck_cycle))
            if mine is not None and mine != ck_chain:
                self._diverge(
                    f"checkpoint chain diverges at cycle {ck_cycle}: "
                    f"follower {mine[:16]} vs manifest {ck_chain[:16]}")

    # -- promotion --------------------------------------------------------

    def promote(self, directory: str, *, checkpoint_every: int = 0,
                fsync_every: int = 0) -> PromotionReport:
        """Become leader: replay the unshipped tail of the durable WAL in
        ``directory`` from our applied byte offset, re-scheduling
        crash-tail cycles, then attach the journal and keep appending.

        The byte-identical chain head is the invariant: a diverged
        follower refuses, and the replayed fold must agree with the
        leader's last durable checkpoint manifest."""
        with self._lock:
            if self.diverged is not None:
                raise PromotionRefused(
                    f"follower chain diverged; refusing promotion: "
                    f"{self.diverged}")
            if self.promoted:
                raise PromotionRefused("already promoted")
            set_role("candidate")
            t0 = perf_counter()
            report = PromotionReport(
                applied_records=self.wal_records_applied)
            wal_path = os.path.join(directory, StreamPersistence.WAL)
            ck_path = os.path.join(directory, StreamPersistence.CHECKPOINT)
            if not os.path.exists(wal_path):
                set_role("follower")
                raise PromotionRefused(
                    f"no durable WAL at {wal_path}: nothing to promote "
                    "from (is the shared durability directory mounted?)")
            records, torn, _end = tail_wal(wal_path, self.applied_ofs)
            report.tail_records = len(records)
            report.violations.extend(torn)
            ck_cycle = ck_chain = None
            if os.path.exists(ck_path):
                with open(ck_path, "r", encoding="utf-8") as f:
                    ck = json.load(f)
                ck_cycle, ck_chain = int(ck["cycle"]), ck["chain"]
                mine = self.chain_history.get(ck_cycle)
                if mine is not None and mine != ck_chain:
                    set_role("follower")
                    raise PromotionRefused(
                        f"chain head mismatch vs the leader's durable "
                        f"checkpoint at cycle {ck_cycle}: follower "
                        f"{mine[:16]} vs manifest {ck_chain[:16]}")

            # tail prepass: batches + which tail cycles reached emit
            emitted_tail = set()
            for _, rec in records:
                c = int(rec["c"])
                if rec["k"] == "batch":
                    self.batches[c] = [Pod.from_obj(o)
                                       for o in rec["pods"]]
                    self.next_cycle = max(self.next_cycle, c + 1)
                elif rec["k"] == "emit":
                    emitted_tail.add(c)

            persist = StreamPersistence(directory, checkpoint_every=0,
                                        fsync_every=fsync_every)
            persist.next_cycle = self.next_cycle
            persist.cycles_emitted = self.cycles_emitted
            persist.chain = self.chain
            persist.decisions = self.decisions
            persist.scheduled = self.scheduled
            persist.wal_records = self.wal_records_applied + len(records)
            persist.attach(self.session)

            pending: List[int] = []

            def recompute(cid: int) -> None:
                persist.queue_resume(cid)
                with flight.span("promote:recompute") as sp:
                    if sp:
                        sp.set("cycle", cid)
                    placements = self.session.schedule(self.batches[cid])
                report.recomputed.append(cid)
                self.bound_by_cycle[cid] = [
                    (pl.pod.key(), pl.node_name)
                    for pl in placements if pl.node_name]

            def flush_below(cycle: int) -> None:
                while pending and pending[0] < cycle:
                    recompute(pending.pop(0))

            def fold_emit(rec: dict) -> None:
                persist.chain = chain_fold(persist.chain, rec["h"])
                persist.decisions += int(rec["n"])
                persist.scheduled += int(rec["s"])
                persist.cycles_emitted += 1
                if ck_cycle is not None \
                        and persist.cycles_emitted == ck_cycle \
                        and persist.chain != ck_chain:
                    report.violations.append(
                        f"tail-replay chain missed the durable manifest "
                        f"at cycle {ck_cycle}")

            inc = self.session.inc
            rsp = flight.span("replicate:promote")
            # one trace context for the whole promotion (ISSUE 20): the
            # tail-replay timeline — replay, per-cycle recomputes, the
            # settle pass — shares a single trace id in the export
            with tracectx.activate(tracectx.start()), rsp, \
                    persist.suppress_events():
                tsp = flight.span("promote:tail_replay")
                if tsp:
                    tsp.set("records", len(records))
                for _ofs, rec in records:
                    k, c = rec["k"], int(rec["c"])
                    if k == "ev":
                        flush_below(c)
                        inc.apply(rec["t"], _LOADERS[rec["r"]](rec["o"]))
                        self.events_applied[c] = \
                            self.events_applied.get(c, 0) + 1
                    elif k == "batch":
                        if c not in emitted_tail:
                            pending.append(c)
                    elif k == "bind":
                        if c in self._live_pending \
                                or c not in emitted_tail:
                            continue   # live-folded already, or the
                            #            crash tail re-decides instead
                        flush_below(c)
                        pods_by_key = {p.key(): p
                                       for p in self.batches.get(c, [])}
                        for key, node in rec["b"]:
                            pod = pods_by_key.get(key)
                            if pod is None:
                                report.violations.append(
                                    f"bind without batch: {key} in "
                                    f"cycle {c}")
                                continue
                            inc.apply(MODIFIED, bind_pod(pod, node))
                        self.bound_by_cycle[c] = [(key, node)
                                                  for key, node in rec["b"]]
                    elif k == "emit":
                        flush_below(c)
                        live = self._live_pending.pop(c, None)
                        if live is not None \
                                and placement_hash(live) != rec["h"]:
                            report.violations.append(
                                f"live placements diverge from the "
                                f"durable emit at cycle {c}")
                        fold_emit(rec)
                        self.chain_history[persist.cycles_emitted] = \
                            persist.chain
                if tsp:
                    tsp.end()
                # settle everything still open, in cycle order: cycles we
                # scheduled live but whose emit never became durable get
                # their emit appended now (our placements ARE the leader's
                # — per-cycle cross-checks proved it); batch-only crash
                # tails re-decide deterministically
                ssp = flight.span("promote:settle")
                for cid in sorted(set(pending) | set(self._live_pending)):
                    if cid in self._live_pending:
                        persist.log_emit(cid,
                                         self._live_pending.pop(cid))
                        report.settled_live.append(cid)
                    else:
                        pending.remove(cid)
                        recompute(cid)
                if ssp:
                    ssp.set("settled_live", len(report.settled_live))
                    ssp.end()
                if rsp:
                    rsp.set("tail_records", report.tail_records)
                    rsp.set("recomputed", len(report.recomputed))
            if any("chain missed" in v for v in report.violations):
                persist.close()
                set_role("follower")
                raise PromotionRefused(report.violations[-1])

            persist.checkpoint_every = checkpoint_every
            persist.checkpoint()
            self.chain = persist.chain
            self.cycles_emitted = persist.cycles_emitted
            self.decisions = persist.decisions
            self.scheduled = persist.scheduled
            self.next_cycle = persist.next_cycle
            self.persist = persist
            self.promoted = True
            report.resume_cycle = persist.cycles_emitted
            report.chain = persist.chain
            report.wal_records = persist.wal_records
            report.replay_s = perf_counter() - t0
            register().replication_promotions.inc()
            set_role("leader")
            from tpusim.obs import slo as _slo

            tracker = _slo.get_tracker()
            if tracker is not None:
                tracker.reset()   # the promoted twin's error budget
                #                   starts clean — replay is not serving
            flight.note_recovery("promotion", {
                "resume_cycle": report.resume_cycle,
                "tail_records": report.tail_records,
                "recomputed": len(report.recomputed),
                "chain": report.chain[:16]})
            self.stop(keep_session=True)
            return report

    def stop(self, *, keep_session: bool = True) -> None:
        with self._lock:
            if self._stop:
                return
            self._stop = True
        try:
            self._server.close()
        except OSError:
            pass
        if not keep_session and self.persist is not None:
            self.persist.close()


# -- failover --------------------------------------------------------------

def http_probe(url: str, timeout: float = 1.0) -> Callable[[], bool]:
    """Build a leader-health probe from a /healthz URL."""
    from urllib.request import urlopen

    def probe() -> bool:
        with urlopen(url, timeout=timeout) as resp:
            return resp.status == 200
    return probe


class FailoverController:
    """Watches the leader's health and promotes the freshest follower.

    ``probe`` is any callable returning truthy while the leader lives
    (an exception or falsy return counts as a miss); ``misses``
    consecutive misses declare death. RTO is measured end-to-end: first
    missed probe to promoted-and-journaling."""

    def __init__(self, probe: Callable[[], bool],
                 followers: Sequence[FollowerTwin], wal_dir: str, *,
                 interval_s: float = 0.02, misses: int = 2,
                 checkpoint_every: int = 0, fsync_every: int = 0,
                 leader_was_alive: bool = False):
        self.probe = probe
        self.followers = list(followers)
        self.wal_dir = wal_dir
        self.interval_s = interval_s
        self.misses = misses
        self.checkpoint_every = checkpoint_every
        self.fsync_every = fsync_every
        # misses only count once the leader has been OBSERVED alive — a
        # follower started before its leader must wait for first contact,
        # not fail over onto a WAL that does not exist yet. Callers that
        # already witnessed the leader run (the in-process driver catches
        # its ProcessCrash directly) pass leader_was_alive=True.
        self.leader_was_alive = leader_was_alive

    def leader_alive(self) -> bool:
        try:
            return bool(self.probe())
        except Exception:
            return False

    def wait_for_death(self, timeout: float = 30.0) -> float:
        """Poll until ``misses`` consecutive probe failures AFTER the
        leader has been seen alive at least once; returns the
        perf_counter timestamp of the FIRST miss of the fatal streak."""
        deadline = monotonic() + timeout
        streak, first_miss = 0, 0.0
        while True:
            if self.leader_alive():
                self.leader_was_alive = True
                streak = 0
            elif self.leader_was_alive:
                if streak == 0:
                    first_miss = perf_counter()
                streak += 1
                if streak >= self.misses:
                    return first_miss
            if monotonic() >= deadline:
                raise TimeoutError(
                    "leader never died within the watch window"
                    if self.leader_was_alive else
                    "leader was never observed alive within the watch "
                    "window")
            sleep(self.interval_s)

    def failover(self, t_detect: Optional[float] = None
                 ) -> Tuple[FollowerTwin, PromotionReport]:
        """Promote the freshest non-diverged follower; refuse when none
        qualifies. Divergence on the freshest candidate falls through to
        the next-freshest — degraded, never silently wrong."""
        if t_detect is None:
            t_detect = perf_counter()
        candidates = sorted(self.followers,
                            key=lambda f: f.applied_seq, reverse=True)
        last_refusal: Optional[Exception] = None
        for follower in candidates:
            try:
                report = follower.promote(
                    self.wal_dir, checkpoint_every=self.checkpoint_every,
                    fsync_every=self.fsync_every)
            except PromotionRefused as exc:
                last_refusal = exc
                continue
            report.rto_s = perf_counter() - t_detect
            register().replication_rto_seconds.set(report.rto_s)
            return follower, report
        raise PromotionRefused(
            f"no promotable follower among {len(candidates)} candidates: "
            f"{last_refusal}")

    def run(self, timeout: float = 30.0
            ) -> Tuple[FollowerTwin, PromotionReport]:
        """Watch until the leader dies, then fail over."""
        t_detect = self.wait_for_death(timeout)
        return self.failover(t_detect)

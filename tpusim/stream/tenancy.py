"""Multi-tenant residency ledger (ISSUE 19): many live twins, one HBM.

A serving deployment keeps one device-resident twin per tenant cluster so
overlay what-if queries answer in O(scenario), but HBM is finite: the
ResidencyBudget holds every tenant's footprint under a byte budget by
evicting the coldest twin to its checkpoint directory (ISSUE 11's
StreamPersistence) and restoring it on demand in O(WAL-tail) via
recover_stream_session. Eviction is a clean handoff, not a loss:

  evict    flush() the pipelined tail -> checkpoint() (durable manifest +
           device arrays' host truth) -> close the WAL -> drop the session.
           The twin's placement-hash chain head is in the manifest.
  restore  recover_stream_session on the same directory: checkpoint load +
           WAL-tail replay rebuilds the host picture; the next cycle
           restages classified ``recovered``. The chain head folds forward
           from exactly where eviction cut it.

Footprints ride PR 14's HBM residency fabric: each tenant registers a
``tenant_twin`` source with per-tenant byte attribution
(tpusim_hbm_resident_bytes{component="tenant_twin"} +
analytics.hbm_snapshot()["tenant_twin"]["tenants"]), and the ledger's own
families (tpusim_tenant_*) expose evictions, restores, restore latency,
and the per-tenant resident bytes.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.engine.providers import DEFAULT_PROVIDER
from tpusim.framework.metrics import register, since_in_microseconds
from tpusim.obs import analytics
from tpusim.obs import recorder as flight


class TenantTwin:
    """One tenant's slot in the ledger: the live session + persistence
    while resident, the checkpoint directory always. The record object is
    the stable owner of the tenant's HBM source across evict/restore
    round-trips (analytics weakrefs it, so dropping the ledger drops the
    source)."""

    def __init__(self, name: str, directory: str, provider: str,
                 policy, always_restage: bool, checkpoint_every: int,
                 fsync_every: int):
        self.name = name
        self.directory = directory
        self.provider = provider
        self.policy = policy
        self.always_restage = always_restage
        self.checkpoint_every = checkpoint_every
        self.fsync_every = fsync_every
        self.session = None
        self.persist = None
        self.last_used = 0.0
        self.evictions = 0
        self.restores = 0

    @property
    def resident(self) -> bool:
        # ledger residency, not device validity: a freshly restored
        # session holds host truth but restages its twin lazily on the
        # first cycle (nbytes() is 0 until then — honest accounting)
        return self.session is not None

    def nbytes(self) -> int:
        """Device bytes this tenant holds resident right now."""
        if self.session is None:
            return 0
        dev = self.session.device
        if not dev.valid:
            return 0
        return analytics.tree_nbytes((dev.statics, dev.carry))

    def chain(self) -> str:
        """The tenant's placement-hash chain head: live from the attached
        persistence, or the durable manifest's when evicted."""
        if self.persist is not None:
            return self.persist.chain
        import json
        import os

        from tpusim.stream.persist import StreamPersistence

        path = os.path.join(self.directory, StreamPersistence.CHECKPOINT)
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)["chain"]


class ResidencyBudget:
    """LRU ledger over tenant twins under an HBM byte budget. Touching a
    tenant (session()/overlay_query()/schedule()) restores it on demand
    and may evict colder tenants to stay under budget; the toucher itself
    is never its own victim."""

    def __init__(self, budget_bytes: int, *, clock=time.monotonic):
        self.budget_bytes = int(budget_bytes)
        self._clock = clock
        self._tenants: Dict[str, TenantTwin] = {}

    # -- admission ---------------------------------------------------------

    def admit(self, name: str, snapshot: Optional[ClusterSnapshot] = None,
              *, directory: str, provider: str = DEFAULT_PROVIDER,
              policy=None, always_restage: bool = False,
              checkpoint_every: int = 0, fsync_every: int = 0):
        """Bring a tenant under the ledger: a fresh StreamSession over its
        snapshot, persistence attached in `directory` (the eviction
        target), and a per-tenant HBM source. Returns the session."""
        if name in self._tenants:
            raise KeyError(f"tenant {name!r} already admitted")
        from tpusim.stream.persist import StreamPersistence
        from tpusim.stream.runtime import StreamSession

        t = TenantTwin(name, directory, provider, policy, always_restage,
                       checkpoint_every, fsync_every)
        t.session = StreamSession(snapshot, provider=provider, policy=policy,
                                  always_restage=always_restage)
        t.persist = StreamPersistence(directory,
                                      checkpoint_every=checkpoint_every,
                                      fsync_every=fsync_every)
        t.persist.attach(t.session)
        t.last_used = self._clock()
        self._tenants[name] = t
        analytics.register_hbm_source(
            "tenant_twin", t, lambda tw: (tw.nbytes(), 1 if tw.resident
                                          else 0), tenant=name)
        self._enforce(protect=name)
        self._observe()
        return t.session

    def tenants(self) -> List[str]:
        return list(self._tenants)

    def resident(self, name: str) -> bool:
        return self._tenants[name].resident

    def chain(self, name: str) -> str:
        return self._tenants[name].chain()

    def total_bytes(self) -> int:
        return sum(t.nbytes() for t in self._tenants.values())

    # -- the serving surface ----------------------------------------------

    def session(self, name: str):
        """The tenant's live session — restored from its checkpoint + WAL
        tail first if evicted. Touching reorders the LRU and may evict a
        colder tenant to fund the restore."""
        t = self._tenants[name]
        if t.session is None:
            self.restore(name)
        t.last_used = self._clock()
        self._enforce(protect=name)
        self._observe()
        return t.session

    def overlay_query(self, name: str, pods):
        return self.session(name).overlay_query(pods)

    def schedule(self, name: str, pods):
        return self.session(name).schedule(pods)

    # -- eviction / restore ------------------------------------------------

    def evict(self, name: str, reason: str = "manual") -> None:
        """Quiesce + checkpoint the tenant's twin and release its HBM: the
        durable manifest (chain head, WAL offset, host snapshot) is the
        whole twin — restore() rebuilds byte-equivalent host truth from
        it."""
        t = self._tenants[name]
        if t.session is None:
            return
        t.session.flush()          # drain any pipelined in-flight cycle
        t.persist.checkpoint()
        t.persist.close()
        t.session.device.invalidate()
        t.session = None
        t.persist = None
        t.evictions += 1
        register().tenant_evictions.inc(reason)
        flight.note_route("tenant_evict", 0)
        self._observe()

    def restore(self, name: str) -> None:
        """recover_stream_session over the tenant's directory: checkpoint
        load + WAL-tail replay, O(tail) not O(history). The session's next
        cycle restages classified ``recovered``; the chain head continues
        from the eviction manifest."""
        t = self._tenants[name]
        if t.session is not None:
            return
        from tpusim.stream.persist import recover_stream_session

        t0 = time.perf_counter()
        session, _report, persist = recover_stream_session(
            t.directory, provider=t.provider, policy=t.policy,
            always_restage=t.always_restage,
            checkpoint_every=t.checkpoint_every)
        t.session = session
        t.persist = persist
        t.restores += 1
        m = register()
        m.tenant_restores.inc()
        m.tenant_restore_latency.observe(since_in_microseconds(t0))
        self._observe()

    def _enforce(self, protect: Optional[str] = None) -> None:
        """Evict coldest-first until the ledger fits the budget. The
        protected tenant (the one being touched) is exempt — a single
        over-budget twin stays resident rather than thrashing."""
        while self.total_bytes() > self.budget_bytes:
            victims = sorted(
                (t for t in self._tenants.values()
                 if t.resident and t.name != protect),
                key=lambda t: t.last_used)
            if not victims:
                return
            self.evict(victims[0].name, reason="pressure")

    def _observe(self) -> None:
        m = register()
        resident = 0
        for t in self._tenants.values():
            nbytes = t.nbytes()
            resident += 1 if t.resident else 0
            m.tenant_resident_bytes.set(t.name, float(nbytes))
        m.tenant_resident_twins.set(float(resident))

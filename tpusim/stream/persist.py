"""Write-ahead journal + checkpoint/restore for the streaming runtime
(ISSUE 12).

The device-resident twin (stream.runtime.DeviceResidentCluster) survives
arbitrary watch churn per-cycle, but a process crash loses it entirely:
the resident carry lives in HBM, the host IncrementalCluster in process
memory, and neither has any durable form. This module makes the twin
recoverable with the classic WAL + checkpoint pair:

  WAL (``wal.jsonl``) — one compact JSON record per committed host
      mutation or emission, in host-picture order:

        {"k":"ev",   "c":C, "t":TYPE, "r":KIND, "o":OBJ}   committed watch delta
        {"k":"batch","c":C, "pods":[OBJ...]}               cycle C's arrivals
        {"k":"bind", "c":C, "b":[[POD_KEY, NODE]...]}      binds folded into
                                                           the host picture
        {"k":"emit", "c":C, "h":HASH, "n":N, "s":S}        cycle C emitted
                                                           (placement_hash,
                                                           decisions, scheduled)

      ``ev`` records are appended from the IncrementalCluster's
      ``on_event`` hook (jaxe/delta.py), so deltas arriving through ANY
      path — session.apply, Reflector.watch, ingest — are journaled at
      the moment they commit. Bind records are written at fold time: in
      pipelined mode cycle N's binds land BEFORE cycle N+1's events,
      exactly the order the host picture mutates, so a sequential replay
      reproduces the picture byte-for-byte.

  Checkpoint (``checkpoint.json``) — a periodic host snapshot: the
      IncrementalCluster as a ClusterSnapshot, the resumable placement
      chain, counters, and the WAL byte offset the snapshot is
      consistent with. When the device twin is resident, the checkpoint
      additionally ``device_get``s the carry/statics trees (and the
      PolicyTables arrays) to an ``.npz`` keyed on the plan signature —
      the durable image of the HBM state, cross-checked against host
      truth (carry pod_count vs bound pods) so a diverged twin cannot
      checkpoint silently.

Recovery (``recover_stream_session``) = load checkpoint + replay the WAL:
events and committed binds re-apply to a fresh IncrementalCluster;
batches that never reached their ``emit`` record (the crash tail) are
re-SCHEDULED through a fresh session — placements are deterministic, so
the recovered emission chain is byte-identical to an uninterrupted run
(the crash-recovery fuzz asserts this for crashes at every record
boundary, including mid-pipeline). The recovery restage is classified
once as ``tpusim_stream_restage_total{reason="recovered"}``.

The placement chain uses a RESUMABLE fold — ``sha256(prev_hex + hash)``
per emission — because hashlib streaming state cannot be serialized into
a checkpoint.

Crash injection: ``arm_crash`` raises chaos.engine.ProcessCrash
immediately AFTER the matching WAL record is durably written — the
strictest crash model a WAL can be tested under (every prefix of the
record stream is a reachable crash state).
"""

from __future__ import annotations

import hashlib
import json
import os
from contextlib import contextmanager
from dataclasses import dataclass, field
from time import perf_counter, time as wall_time
from typing import Dict, List, Optional, Tuple

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import (
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    Service,
)
from tpusim.backends import Placement, bind_pod, placement_hash
from tpusim.engine.providers import DEFAULT_PROVIDER
from tpusim.framework.metrics import register, since_in_microseconds
from tpusim.framework.store import MODIFIED
from tpusim.obs import recorder as flight

# WAL record kinds double as the crash-point names a process_crash churn
# event targets: the crash fires right after the matching record of the
# armed cycle hits the journal. chaos.plan owns the tuple (plan
# validation needs it without importing the stream package).
from tpusim.chaos.plan import CRASH_POINTS  # noqa: E402  (re-export)

_KINDS: Tuple[Tuple[type, str], ...] = (
    (Pod, "pod"), (Node, "node"), (Service, "service"),
    (PersistentVolume, "pv"), (PersistentVolumeClaim, "pvc"))
_LOADERS = {"pod": Pod.from_obj, "node": Node.from_obj,
            "service": Service.from_obj, "pv": PersistentVolume.from_obj,
            "pvc": PersistentVolumeClaim.from_obj}


class PersistError(RuntimeError):
    """A corrupt or inconsistent checkpoint/WAL pair."""


def _obj_kind(obj) -> str:
    for cls, kind in _KINDS:
        if isinstance(obj, cls):
            return kind
    raise TypeError(f"unsupported WAL object: {type(obj).__name__}")


def chain_fold(prev_hex: str, placement_hex: str) -> str:
    """One step of the resumable placement chain: unlike a streaming
    sha256, the fold state IS a hex digest, so a checkpoint can carry it
    and a recovered session can keep folding where the dead process
    stopped."""
    return hashlib.sha256((prev_hex + placement_hex).encode()).hexdigest()


def _capture_device(dev) -> Dict[str, object]:
    """device_get the resident trees to host numpy: the carry (THE
    resident state), the statics tables, and the host-side PolicyTables
    arrays — everything a plan-signature-matched restore could reuse."""
    import jax
    import numpy as np

    out: Dict[str, object] = {}
    for prefix, tree in (("carry_", dev.carry), ("statics_", dev.statics)):
        if tree is None:
            continue
        for name, value in jax.device_get(tree)._asdict().items():
            out[prefix + name] = np.asarray(value)
    if dev.ptabs is not None:
        for name, value in getattr(dev.ptabs, "__dict__", {}).items():
            if isinstance(value, np.ndarray):
                out["ptab_" + name] = value
    return out


class StreamPersistence:
    """The WAL writer + checkpointer one StreamSession journals through.

    Wiring (StreamSession.attach_persistence): committed watch deltas
    arrive via IncrementalCluster.on_event; the session calls
    begin_cycle at batch admission, log_bind at fold time, log_emit at
    emission. ``checkpoint_every`` > 0 checkpoints after every that-many
    emitted cycles (0 = genesis checkpoint only)."""

    CHECKPOINT = "checkpoint.json"
    WAL = "wal.jsonl"

    def __init__(self, directory: str, *, checkpoint_every: int = 0,
                 fsync_every: int = 0):
        if checkpoint_every < 0:
            raise ValueError(f"checkpoint_every={checkpoint_every}: "
                             "need >= 0")
        if fsync_every < 0:
            raise ValueError(f"fsync_every={fsync_every}: need >= 0")
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.checkpoint_every = checkpoint_every
        # 0 = flush-only (OS buffers may lose the newest records on a HOST
        # crash, though never on a process crash); N = fsync the journal
        # fd every N appends, trading append latency for host-crash
        # durability. The chosen mode is stamped into every checkpoint
        # manifest so recovery/audit can tell what the WAL promises.
        self.fsync_every = fsync_every
        self.wal_path = os.path.join(directory, self.WAL)
        self.checkpoint_path = os.path.join(directory, self.CHECKPOINT)
        self._wal = None
        self.session = None
        self.next_cycle = 0       # cycle id the next batch record gets
        self.cycles_emitted = 0   # emit records written (ever, this WAL)
        self.chain = ""           # resumable fold over emitted hashes
        self.decisions = 0
        self.scheduled = 0
        self.wal_records = 0
        self.checkpoints = 0
        self._suppress = 0
        self._resume_ids: List[int] = []   # recovery recompute cycle ids
        self._crash: Optional[Tuple[int, str]] = None
        self._crashed = False
        # replication seams (stream.replicate.WalShipper): on_append sees
        # every durable record with its byte extent, on_checkpoint every
        # manifest. Both fire AFTER the write is durable and BEFORE any
        # armed crash — a shipped record is always a durable record, and
        # the record that kills the leader still reaches the wire.
        self.on_append = None      # (rec, kind, cycle, start_ofs, end_ofs)
        self.on_checkpoint = None  # (manifest_dict)

    # -- wiring ------------------------------------------------------------

    def attach(self, session) -> "StreamPersistence":
        """Bind to a StreamSession (use session.attach_persistence). A
        fresh directory gets a genesis checkpoint so recovery always has
        a snapshot to replay onto."""
        self.session = session
        session.persist = self
        session.inc.on_event = self.on_inc_event
        if self._wal is None:
            self._wal = open(self.wal_path, "a", encoding="utf-8")
        if not os.path.exists(self.checkpoint_path):
            self.checkpoint()
        return self

    def close(self) -> None:
        if self._wal is not None:
            self._wal.flush()
            self._wal.close()
            self._wal = None
        if self.session is not None \
                and self.session.inc.on_event == self.on_inc_event:
            self.session.inc.on_event = None

    @contextmanager
    def suppress_events(self):
        """Gate on_inc_event off: fold-back binds are journaled as bind
        records (not ev records), and recovery replay re-applies records
        that are already durable."""
        self._suppress += 1
        try:
            yield
        finally:
            self._suppress -= 1

    # -- crash injection ---------------------------------------------------

    def arm_crash(self, cycle: int, point: str) -> None:
        """Raise chaos.engine.ProcessCrash right after the ``point``
        record of cycle ``cycle`` is durably appended."""
        if point not in CRASH_POINTS:
            raise ValueError(f"unknown crash point {point!r} "
                             f"(expected one of {CRASH_POINTS})")
        self._crash = (int(cycle), point)

    def _maybe_crash(self, kind: str, cycle: int) -> None:
        if self._crash is None or self._crashed:
            return
        at, point = self._crash
        if kind == point and cycle == at:
            from tpusim.chaos.engine import ProcessCrash

            self._crashed = True
            flight.note_fault("process_crash",
                             {"cycle": cycle, "point": point})
            raise ProcessCrash(
                f"chaos: injected process crash after the {point} record "
                f"of cycle {cycle}")

    # -- record writing ----------------------------------------------------

    def _append(self, rec: dict, kind: str, cycle: int) -> None:
        start = self._wal.tell()
        self._wal.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._wal.flush()
        if self.fsync_every \
                and (self.wal_records + 1) % self.fsync_every == 0:
            os.fsync(self._wal.fileno())
        self.wal_records += 1
        register().recovery_wal_records.set(float(self.wal_records))
        if self.on_append is not None:
            self.on_append(rec, kind, cycle, start, self._wal.tell())
        self._maybe_crash(kind, cycle)

    def on_inc_event(self, event_type: str, obj) -> None:
        """IncrementalCluster.on_event hook: one committed watch delta.
        Labeled with the UPCOMING cycle id — events precede the batch
        they affect."""
        if self._suppress:
            return
        self._append({"k": "ev", "c": self.next_cycle, "t": event_type,
                      "r": _obj_kind(obj), "o": obj.to_obj()},
                     "events", self.next_cycle)

    def queue_resume(self, cid: int) -> None:
        """Recovery: the next begin_cycle reuses ``cid`` (its batch
        record is already durable) instead of assigning a fresh id."""
        self._resume_ids.append(int(cid))

    def begin_cycle(self, pods: List[Pod]) -> int:
        if self._resume_ids:
            return self._resume_ids.pop(0)
        cid = self.next_cycle
        self.next_cycle += 1
        self._append({"k": "batch", "c": cid,
                      "pods": [p.to_obj() for p in pods]}, "batch", cid)
        return cid

    def log_bind(self, cid: int, bound: List[Placement]) -> None:
        """Cycle ``cid``'s binds, at the moment they fold into the host
        picture. Always written (possibly empty) so every cycle exposes
        all four crash boundaries."""
        self._append({"k": "bind", "c": cid,
                      "b": [[pl.pod.key(), pl.node_name] for pl in bound]},
                     "bind", cid)

    def log_emit(self, cid: int, placements: List[Placement]) -> None:
        h = placement_hash(placements)
        s = sum(1 for p in placements if p.node_name)
        self.chain = chain_fold(self.chain, h)
        self.decisions += len(placements)
        self.scheduled += s
        self.cycles_emitted += 1
        register().stream_chain_head.set_info(head=self.chain,
                                              cycle=str(cid))
        self._append({"k": "emit", "c": cid, "h": h,
                      "n": len(placements), "s": s}, "emit", cid)
        if self.checkpoint_every \
                and self.cycles_emitted % self.checkpoint_every == 0:
            self.checkpoint()

    # -- checkpoint --------------------------------------------------------

    def checkpoint(self) -> dict:
        """Write an atomic host snapshot consistent with the current WAL
        offset (tmp + rename). Replaying WAL[offset:] onto it reproduces
        the live host picture exactly, because every host mutation is a
        durable ev/bind record BEFORE the picture moves on."""
        import numpy as np

        t0 = perf_counter()
        session = self.session
        inc = session.inc
        sp = flight.span("recover:checkpoint")
        with sp:
            self._wal.flush()
            device_npz = None
            device_bound = None
            # bound-to-a-known-node count: the host-truth side of the
            # carry pod_count cross-check (parked pods on unknown nodes
            # have no carry row)
            bound_pods = sum(1 for p in inc._pods.values()
                             if p.spec.node_name in inc._node_index)
            if session.device.valid:
                arrays = _capture_device(session.device)
                if arrays:
                    sig = hashlib.sha256(
                        repr(session.device.plan_key).encode()
                    ).hexdigest()[:12]
                    device_npz = f"device-{sig}.npz"
                    np.savez(os.path.join(self.directory, device_npz),
                             **arrays)
                    quiesced = (session._pending is None
                                and not inc._journal_nodes
                                and not inc._journal_presence)
                    if quiesced and "carry_pod_count" in arrays:
                        # cross-check only at a quiesced boundary: an
                        # in-flight pipelined cycle has already advanced
                        # the carry past the host fold, and undrained
                        # journal deltas (watch events applied to the host
                        # but not yet scatter-committed) lag it behind
                        device_bound = int(arrays["carry_pod_count"].sum())
            meta = {
                "cycle": self.cycles_emitted,
                "next_cycle": self.next_cycle,
                "chain": self.chain,
                "decisions": self.decisions,
                "scheduled": self.scheduled,
                "wal_offset": self._wal.tell(),
                "wal_records": self.wal_records,
                "bound_pods": bound_pods,
                "device_bound": device_bound,
                "plan_sig": repr(session._plan_key),
                "device_npz": device_npz,
                # node-sharded residency layout (ISSUE 16): which shard
                # owns which node block. Recovery replays the WAL tail
                # once (host picture), then the recovered session's first
                # restage re-stages the twin per-owner from this layout's
                # TPUSIM_SHARDS — tail work and restage cost stay
                # O(delta-per-shard) instead of O(cluster)
                "shard_layout": session._shard_layout,
                # the WAL's durability promise at the time this manifest
                # was cut: flush-only survives process crashes, fsync
                # additionally survives host crashes (ISSUE 18)
                "durability": {
                    "mode": "fsync" if self.fsync_every else "flush",
                    "fsync_every": self.fsync_every,
                },
                "snapshot": inc.to_snapshot().to_obj(),
            }
            tmp = self.checkpoint_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(meta, f, separators=(",", ":"))
            os.replace(tmp, self.checkpoint_path)
            if sp:
                sp.set("cycle", self.cycles_emitted)
                sp.set("wal_records", self.wal_records)
        register().recovery_checkpoint_latency.observe(
            since_in_microseconds(t0))
        register().recovery_last_checkpoint_timestamp.set(wall_time())
        self.checkpoints += 1
        flight.note_recovery("checkpoint", {"cycle": self.cycles_emitted,
                                            "wal_records": self.wal_records})
        if self.on_checkpoint is not None:
            self.on_checkpoint(meta)
        return meta


@dataclass
class RecoveryReport:
    """What recover_stream_session reconstructed, and from how much."""

    resume_cycle: int = 0          # first cycle the driver should run
    checkpoint_cycle: int = 0      # cycles already folded at checkpoint
    chain: str = ""                # resumable fold chain after replay
    decisions: int = 0
    scheduled: int = 0
    wal_records: int = 0
    tail_records: int = 0          # records replayed past the checkpoint
    recomputed: List[int] = field(default_factory=list)
    replay_s: float = 0.0
    events_applied: Dict[int, int] = field(default_factory=dict)
    bound_by_cycle: Dict[int, List[Tuple[str, str]]] = \
        field(default_factory=dict)
    violations: List[str] = field(default_factory=list)
    device_arrays: Optional[dict] = None
    shard_layout: Optional[dict] = None   # node-mesh layout at checkpoint


def tail_wal(wal_path: str, offset: int = 0
             ) -> Tuple[List[Tuple[int, dict]], List[str], int]:
    """Incremental WAL reader (ISSUE 18): parse complete records from
    byte ``offset`` to EOF, returning ([(byte offset, record)],
    violations, resume_offset). ``resume_offset`` is the position after
    the last COMPLETE record — hand it back to the next call to follow
    the live tail without re-parsing the prefix. The shipper, the
    follower's promotion replay, and cold recovery all share this one
    parser.

    Torn-line policy: unparseable trailing lines are a live-tail
    artifact (a crash mid-write, or a writer mid-append) — dropped, and
    ``resume_offset`` stops BEFORE them so a later call retries once the
    line completes. An unparseable line followed by further complete
    records is a torn INTERIOR write: the journal itself is corrupt, and
    each such line is reported as a violation."""
    records: List[Tuple[int, Optional[dict]]] = []
    with open(wal_path, "r", encoding="utf-8") as f:
        f.seek(offset)
        while True:
            ofs = f.tell()
            line = f.readline()
            if not line:
                break
            if not line.strip():
                continue
            if not line.endswith("\n"):
                # a partial final line with no terminator is still being
                # written (or was torn by a crash): never a violation
                records.append((ofs, None))
                break
            try:
                records.append((ofs, json.loads(line)))
            except json.JSONDecodeError:
                records.append((ofs, None))
    resume_offset = offset
    while records and records[-1][1] is None:
        records.pop()
    if records:
        last_ofs = records[-1][0]
        with open(wal_path, "rb") as f:
            f.seek(last_ofs)
            resume_offset = last_ofs + len(f.readline())
    violations: List[str] = []
    for ofs, rec in records:
        if rec is None:
            violations.append(f"corrupt WAL record at byte {ofs} "
                              "(torn interior write)")
    return ([(o, r) for o, r in records if r is not None], violations,
            resume_offset)


def read_wal(wal_path: str) -> Tuple[List[Tuple[int, dict]], List[str]]:
    """Parse a whole WAL into [(byte offset, record)] plus violation
    strings — ``tail_wal`` from byte 0, keeping the original two-tuple
    shape recovery and the tests consume."""
    records, violations, _ = tail_wal(wal_path, 0)
    return records, violations


def recover_stream_session(directory: str, *,
                           provider: str = DEFAULT_PROVIDER,
                           policy=None, always_restage: bool = False,
                           checkpoint_every: int = 0):
    """Rebuild a StreamSession from a checkpoint + WAL directory.

    Returns (session, RecoveryReport, StreamPersistence): the session's
    host picture equals the crashed process's at its last durable record;
    batches that never emitted (the crash tail) have been re-scheduled —
    deterministically identical to the lost decisions — and their
    bind/emit records appended, so the WAL ends every cycle committed.
    The persistence object is re-attached and appends to the same WAL;
    the session's next cycle restages classified ``recovered`` (exactly
    once — re-scheduling the tail consumes the latch when there is one).
    """
    from tpusim.jaxe.delta import IncrementalCluster
    from tpusim.stream.runtime import StreamSession

    t0 = perf_counter()
    ck_path = os.path.join(directory, StreamPersistence.CHECKPOINT)
    wal_path = os.path.join(directory, StreamPersistence.WAL)
    if not os.path.exists(ck_path) or not os.path.exists(wal_path):
        raise PersistError(f"{directory}: no checkpoint/WAL pair to "
                           "recover from")
    with open(ck_path, "r", encoding="utf-8") as f:
        ck = json.load(f)
    records, torn = read_wal(wal_path)
    report = RecoveryReport(checkpoint_cycle=int(ck["cycle"]),
                            violations=list(torn),
                            shard_layout=ck.get("shard_layout"))

    snapshot = ClusterSnapshot.from_obj(ck["snapshot"])
    inc = IncrementalCluster(snapshot)
    session = StreamSession(incremental=inc, provider=provider,
                            policy=policy, always_restage=always_restage)

    # metadata pass over the FULL journal: batch pods, committed cycles,
    # per-cycle bind maps (the driver's load-generator fast-forward feed)
    batches: Dict[int, List[Pod]] = {}
    emitted = set()
    max_cid = -1   # over ADMITTED cycles only: ev records labeled with a
    #                never-admitted upcoming cycle must not consume its id
    for _, rec in records:
        k, c = rec["k"], int(rec["c"])
        if k == "batch":
            max_cid = max(max_cid, c)
            batches[c] = [Pod.from_obj(o) for o in rec["pods"]]
        elif k == "emit":
            emitted.add(c)
        elif k == "bind":
            report.bound_by_cycle[c] = [(key, node)
                                        for key, node in rec["b"]]
        elif k == "ev":
            report.events_applied[c] = report.events_applied.get(c, 0) + 1

    # checkpointing stays off until replay finishes: recomputed bind/emit
    # records append at the WAL tail OUT of host-picture order, so a
    # checkpoint mid-replay would anchor a non-replayable offset
    persist = StreamPersistence(directory, checkpoint_every=0)
    persist.next_cycle = max(int(ck["next_cycle"]), max_cid + 1)
    persist.cycles_emitted = int(ck["cycle"])
    persist.chain = ck["chain"]
    persist.decisions = int(ck["decisions"])
    persist.scheduled = int(ck["scheduled"])
    persist.wal_records = len(records)
    persist.attach(session)

    pending: List[int] = []   # batches past the checkpoint with no emit

    def recompute(cid: int) -> None:
        persist.queue_resume(cid)
        placements = session.schedule(batches[cid])
        report.recomputed.append(cid)
        report.bound_by_cycle[cid] = [(pl.pod.key(), pl.node_name)
                                      for pl in placements if pl.node_name]

    def flush_below(cycle: int) -> None:
        while pending and pending[0] < cycle:
            recompute(pending.pop(0))

    offset_limit = int(ck["wal_offset"])
    session.force_restage("recovered")
    rsp = flight.span("recover:replay")
    with rsp, persist.suppress_events():
        for ofs, rec in records:
            if ofs < offset_limit:
                continue
            report.tail_records += 1
            k, c = rec["k"], int(rec["c"])
            if k == "ev":
                # an uncommitted batch below this cycle must re-decide
                # BEFORE later events apply (host-picture order)
                flush_below(c)
                inc.apply(rec["t"], _LOADERS[rec["r"]](rec["o"]))
            elif k == "batch":
                if c not in emitted:
                    pending.append(c)
            elif k == "bind":
                if c not in emitted:
                    continue   # crash tail: the cycle re-decides instead
                flush_below(c)
                pods_by_key = {p.key(): p for p in batches.get(c, [])}
                for key, node in rec["b"]:
                    prev = inc._pods.get(key)
                    if prev is not None and prev.spec.node_name \
                            and prev.spec.node_name != node:
                        report.violations.append(
                            f"double-bind in WAL: {key} bound to "
                            f"{prev.spec.node_name} then {node} in "
                            f"cycle {c}")
                    pod = pods_by_key.get(key)
                    if pod is None:
                        report.violations.append(
                            f"bind without batch: {key} in cycle {c}")
                        continue
                    inc.apply(MODIFIED, bind_pod(pod, node))
            elif k == "emit":
                flush_below(c)
                persist.chain = chain_fold(persist.chain, rec["h"])
                persist.decisions += int(rec["n"])
                persist.scheduled += int(rec["s"])
                persist.cycles_emitted += 1
        flush_below(persist.next_cycle + 1)
        if rsp:
            rsp.set("tail_records", report.tail_records)
            rsp.set("recomputed", len(report.recomputed))

    report.resume_cycle = persist.cycles_emitted
    report.chain = persist.chain
    report.decisions = persist.decisions
    report.scheduled = persist.scheduled
    report.wal_records = persist.wal_records
    # a fresh checkpoint makes the recovered picture the new replay base:
    # everything below this offset (including the out-of-order recomputed
    # tail) is metadata-only for any future recovery
    persist.checkpoint_every = checkpoint_every
    persist.checkpoint()
    report.replay_s = perf_counter() - t0
    register().recovery_replay_latency.observe(since_in_microseconds(t0))
    flight.note_recovery("replay", {
        "resume_cycle": report.resume_cycle,
        "tail_records": report.tail_records,
        "recomputed": len(report.recomputed)})

    # durable device image: load + integrity-check when the plan matches
    if ck.get("device_npz"):
        npz = os.path.join(directory, ck["device_npz"])
        if os.path.exists(npz):
            import numpy as np

            report.device_arrays = dict(np.load(npz))
        db = ck.get("device_bound")
        if db is not None and db != ck.get("bound_pods"):
            report.violations.append(
                f"checkpointed device twin diverged from host truth: "
                f"carry pod_count {db} vs {ck.get('bound_pods')} bound "
                "pods")
    return session, report, persist

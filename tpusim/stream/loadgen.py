"""Seeded churn load generator for the streaming runtime.

Produces the workload shape the stream fast path is built for — and the
failure shapes it must classify: per cycle, a batch of fresh pod arrivals
(scatter-friendly steady state) interleaved with watch-fabric events
(evictions of previously bound pods → O(delta) scatter commits; periodic
node flaps → structural restages). Fully deterministic under a seed, so the
bench, the smoke variant, and the churn-parity fuzz replay identical
sequences.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from tpusim.api.snapshot import ClusterSnapshot, make_pod
from tpusim.api.types import Node, Pod, Taint
from tpusim.backends import Placement
from tpusim.framework.store import DELETED, MODIFIED

# (milli_cpu, memory) request shapes; a mixed-shape run exercises the
# signature remap (every shape still hits the same interned signatures after
# the first restage — requests don't enter the sig keys, selectors do)
DEFAULT_SHAPES: Tuple[Tuple[int, int], ...] = (
    (100, 256 << 20),
    (250, 512 << 20),
    (500, 1 << 30),
)

# Label churn universe: the keys the compat policy corpus gates on (region /
# zone for ServiceAffinity+AntiAffinity, foo for LabelsPresence, bar for
# LabelPreference) with small closed value sets — closed so that a seeded
# cluster interns every value at cold start and pure churn never grows a
# domain-id space (the zero-restage property ISSUE 9's acceptance asserts).
DEFAULT_LABEL_UNIVERSE: Dict[str, Tuple[str, ...]] = {
    "zone": ("z0", "z1", "z2"),
    "region": ("r0", "r1"),
    "bar": ("on", "off"),
    "foo": ("present",),
}

# Taint churn toggles this taint on and off — a NoSchedule key the compat
# policies' tolerations don't cover, so it flips taint_ok columns.
CHURN_TAINT = Taint(key="dedicated", value="batch", effect="NoSchedule")


class ChurnLoadGen:
    """Deterministic churn: arrivals + evictions (+ optional node flaps).

    evict_fraction: per cycle, this fraction of the arrival batch size is
        drawn from the currently-bound population and DELETED (the watch
        fabric's pod-evict shape — lands in the stream runtime as journal
        rows, not a restage).
    node_flap_every: every k-th cycle cordons one node (MODIFIED,
        unschedulable=True) and restores it the next cycle — each flap is a
        structural event the device cannot scatter, forcing a classified
        restage pair.
    label_churn / taint_churn: per cycle, rewrite this many nodes' labels
        (values drawn from label_universe, keys possibly removed) / toggle
        CHURN_TAINT on this many nodes — label/taint-ONLY modifications,
        the exact churn class the v2 statics scatter path absorbs without
        a restage (ISSUE 9).
    gang_size / gang_count: per cycle, append gang_count complete pod
        groups of gang_size members each after the normal arrivals (the
        gang delta class, ISSUE 15). Appended WITHOUT rng draws, so a
        seeded run's churn chain is unchanged when gangs are off.
    """

    def __init__(self, snapshot: ClusterSnapshot, *, seed: int = 0,
                 arrivals: int = 32, evict_fraction: float = 0.25,
                 node_flap_every: int = 0,
                 label_churn: int = 0, taint_churn: int = 0,
                 label_universe: Optional[Dict[str, Tuple[str, ...]]] = None,
                 shapes: Tuple[Tuple[int, int], ...] = DEFAULT_SHAPES,
                 name_prefix: str = "churn",
                 gang_size: int = 0, gang_count: int = 0):
        self.rng = random.Random(seed)
        self.nodes: List[Node] = list(snapshot.nodes)
        self.arrivals = arrivals
        self.evict_fraction = evict_fraction
        self.node_flap_every = node_flap_every
        self.label_churn = label_churn
        self.taint_churn = taint_churn
        self.label_universe = (DEFAULT_LABEL_UNIVERSE
                               if label_universe is None else label_universe)
        self.shapes = shapes
        self.name_prefix = name_prefix
        self.gang_size = gang_size
        self.gang_count = gang_count
        self.serial = 0
        self.gang_serial = 0
        self.bound: Dict[str, Pod] = {}     # pod name -> bound copy
        self._flapped: Optional[Node] = None  # cordoned node awaiting restore
        self.stats = {"arrivals": 0, "evictions": 0, "flaps": 0,
                      "label_churns": 0, "taint_churns": 0,
                      "gang_arrivals": 0, "gangs": 0}

    def batch(self) -> List[Pod]:
        """The cycle's fresh arrivals (Pending pods, no node); gang
        arrivals, when configured, follow the normal ones."""
        out = []
        for _ in range(self.arrivals):
            cpu, mem = self.shapes[self.serial % len(self.shapes)]
            out.append(make_pod(f"{self.name_prefix}-{self.serial}",
                                milli_cpu=cpu, memory=mem))
            self.serial += 1
        self.stats["arrivals"] += len(out)
        if self.gang_size > 0 and self.gang_count > 0:
            from tpusim.gang.group import mark_gang

            for _ in range(self.gang_count):
                name = f"{self.name_prefix}-gang-{self.gang_serial}"
                self.gang_serial += 1
                for j in range(self.gang_size):
                    cpu, mem = self.shapes[self.serial % len(self.shapes)]
                    out.append(mark_gang(
                        make_pod(f"{name}-{j}", milli_cpu=cpu, memory=mem),
                        name))
                    self.serial += 1
                self.stats["gangs"] += 1
                self.stats["gang_arrivals"] += self.gang_size
        return out

    def events(self, cycle: int) -> List[Tuple[str, object]]:
        """Watch-fabric events preceding this cycle's batch."""
        out: List[Tuple[str, object]] = []
        if self._flapped is not None:
            restored = self._flapped.copy()
            restored.spec.unschedulable = False
            out.append((MODIFIED, restored))
            self._flapped = None
        n_evict = int(self.arrivals * self.evict_fraction)
        if n_evict and self.bound:
            names = self.rng.sample(sorted(self.bound),
                                    min(n_evict, len(self.bound)))
            for name in names:
                out.append((DELETED, self.bound.pop(name)))
            self.stats["evictions"] += len(names)
        if self.node_flap_every and cycle and self.nodes \
                and cycle % self.node_flap_every == 0:
            node = self.nodes[self.rng.randrange(len(self.nodes))].copy()
            node.spec.unschedulable = True
            out.append((MODIFIED, node))
            self._flapped = node
            self.stats["flaps"] += 1
        # churn blocks come last so runs with churn disabled draw the same
        # rng sequence (and hence the same chains) as before ISSUE 9
        if self.label_churn and self.nodes:
            for _ in range(self.label_churn):
                i = self.rng.randrange(len(self.nodes))
                node = self.nodes[i].copy()
                labels = dict(node.metadata.labels)
                for key, values in self.label_universe.items():
                    choice = self.rng.randrange(len(values) + 1)
                    if choice == len(values):
                        labels.pop(key, None)
                    else:
                        labels[key] = values[choice]
                node.metadata.labels = labels
                # store back: later events must diff against CURRENT truth
                # for the runtime to see a labels/taints-only modification
                self.nodes[i] = node
                out.append((MODIFIED, node))
                self.stats["label_churns"] += 1
        if self.taint_churn and self.nodes:
            for _ in range(self.taint_churn):
                i = self.rng.randrange(len(self.nodes))
                node = self.nodes[i].copy()
                if node.spec.taints:
                    node.spec.taints = []
                else:
                    node.spec.taints = [Taint(key=CHURN_TAINT.key,
                                              value=CHURN_TAINT.value,
                                              effect=CHURN_TAINT.effect)]
                self.nodes[i] = node
                out.append((MODIFIED, node))
                self.stats["taint_churns"] += 1
        return out

    def note_bound(self, placements: List[Placement]) -> None:
        """Record this cycle's binds as future eviction candidates."""
        for pl in placements:
            if pl.node_name:
                self.bound[pl.pod.name] = pl.pod

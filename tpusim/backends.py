"""SimulatorBackend boundary: Schedule(pod_batch, cluster_state) -> placements.

This is the plugin seam called out in BASELINE.json's north star: the
orchestration layer feeds an ordered pod batch plus a cluster snapshot to a
backend and gets back placements + failure reasons. Two implementations:

  ReferenceBackend — pure-Python, line-for-line reference semantics
                     (the parity oracle and CPU baseline)
  JaxBackend       — the batched TPU engine (tpusim/jaxe)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Pod, PodCondition
from tpusim.engine.generic_scheduler import FitError, SchedulingError
from tpusim.engine.providers import (
    DEFAULT_PROVIDER,
    PluginFactoryArgs,
    create_from_provider,
    default_registry,
)
from tpusim.engine.resources import new_node_info_map


@dataclass
class Placement:
    """One scheduling decision. For parity hashing: (pod name, node|'', reason)."""

    pod: Pod
    node_name: str = ""
    reason: str = ""   # "" on success, "Unschedulable" on predicate failure
    message: str = ""  # FitError reason histogram text

    @property
    def scheduled(self) -> bool:
        return bool(self.node_name)


def bind_pod(pod: Pod, node_name: str) -> Pod:
    """The Bind intercept's state mutation (reference: simulator.go:108-128):
    set nodeName, mark Running."""
    bound = pod.copy()
    bound.spec.node_name = node_name
    bound.status.phase = "Running"
    return bound


def mark_unschedulable(pod: Pod, message: str) -> Pod:
    """The Update intercept (reference: simulator.go:163-185 + scheduler.go error
    path): Pending phase, PodScheduled=False condition, Reason=Unschedulable."""
    failed = pod.copy()
    failed.status.phase = "Pending"
    failed.status.conditions.append(PodCondition(
        type="PodScheduled", status="False", reason="Unschedulable", message=message))
    failed.status.reason = "Unschedulable"
    return failed


class ReferenceBackend:
    """Sequential per-pod loop with reference semantics.

    Mirrors scheduleOne (scheduler.go:431-497): schedule → bind (mutating the
    node aggregates seen by the next pod) or mark unschedulable. The pod order
    is the caller's: the orchestrator reproduces the reference's LIFO feed
    (store.go:223-233).
    """

    name = "reference"

    def __init__(self, provider: str = DEFAULT_PROVIDER,
                 hard_pod_affinity_symmetric_weight: int = 10,
                 registry=None, always_check_all_predicates: bool = False,
                 volume_scheduling_enabled: bool = False, policy=None,
                 extender_transport=None):
        self.provider = provider
        self.hard_pod_affinity_symmetric_weight = hard_pod_affinity_symmetric_weight
        self.registry = registry
        self.always_check_all_predicates = always_check_all_predicates
        # the VolumeScheduling feature gate (off by default, like the
        # reference's utilfeature defaults; scheduler.go:175)
        self.volume_scheduling_enabled = volume_scheduling_enabled
        # policy-as-data (factory.go CreateFromConfig); replaces the provider
        self.policy = policy
        self.extender_transport = extender_transport

    def schedule(self, pods: List[Pod], snapshot: ClusterSnapshot) -> List[Placement]:
        from tpusim.engine.volume import VolumeBinder

        node_info_map = new_node_info_map(snapshot.nodes, snapshot.pods)
        nodes = list(snapshot.nodes)

        # the plugin pod lister is the SCHEDULER CACHE, not the store
        # (factory.go:166 podLister: schedulerCache): assigned pods only —
        # seeded placed pods in snapshot order, then bound pods in bind
        # order (the cache's deterministic stand-in for Go's random map
        # iteration; DEVIATIONS.md #4). "First matching pod" consumers (the
        # ServiceAffinity predicate) depend on this order.
        cluster_pods: List[Pod] = [p for p in snapshot.pods if p.spec.node_name]
        binder = VolumeBinder(snapshot.pvs, snapshot.pvcs,
                              snapshot.storage_classes,
                              enabled=self.volume_scheduling_enabled)

        args = PluginFactoryArgs(
            pod_lister=lambda: list(cluster_pods),
            service_lister=lambda: list(snapshot.services),
            node_info_getter=lambda name: node_info_map.get(name),
            pvc_getter=binder.get_pvc,
            pv_getter=binder.get_pv,
            storage_class_getter=binder.get_class,
            volume_binder=binder,
            volume_scheduling_enabled=self.volume_scheduling_enabled,
            hard_pod_affinity_symmetric_weight=self.hard_pod_affinity_symmetric_weight,
        )
        if self.policy is not None:
            from tpusim.engine.providers import create_from_config

            scheduler = create_from_config(
                self.policy, args, registry=self.registry,
                extender_transport=self.extender_transport)
            # the flag can only be switched ON, never off (CreateFromConfig)
            scheduler.always_check_all_predicates = (
                scheduler.always_check_all_predicates
                or self.always_check_all_predicates)
        else:
            scheduler = create_from_provider(
                self.provider, args, registry=self.registry,
                always_check_all_predicates=self.always_check_all_predicates)

        placements: List[Placement] = []
        for pod in pods:
            try:
                host = scheduler.schedule(pod, nodes, node_info_map)
            except FitError as fit_err:
                placements.append(Placement(pod=mark_unschedulable(pod, fit_err.error()),
                                            reason="Unschedulable",
                                            message=fit_err.error()))
                continue
            except SchedulingError as sched_err:
                placements.append(Placement(pod=mark_unschedulable(pod, str(sched_err)),
                                            reason="Unschedulable",
                                            message=str(sched_err)))
                continue
            if self.volume_scheduling_enabled:
                # scheduleOne assumeAndBindVolumes (scheduler.go:367-398):
                # consume the matched PVs so later pods see the binding
                binder.assume_pod_volumes(pod, host)
            bound = bind_pod(pod, host)
            node_info_map[host].add_pod(bound)
            cluster_pods.append(bound)  # enters the cache view on bind
            placements.append(Placement(pod=bound, node_name=host))
        return placements


def get_backend(name: str, **kwargs):
    if name == "reference":
        return ReferenceBackend(**kwargs)
    if name == "jax":
        from tpusim.jaxe.backend import JaxBackend

        return JaxBackend(**kwargs)
    raise ValueError(f"unknown backend {name!r} (expected 'reference' or 'jax')")


def placement_hash(placements: List[Placement]) -> str:
    """Stable digest of the ordered decision list for parity checking
    (BASELINE.md: 'placement hash')."""
    import hashlib

    h = hashlib.sha256()
    for p in placements:
        h.update(f"{p.pod.name}\x00{p.node_name}\x00{p.reason}\n".encode())
    return h.hexdigest()

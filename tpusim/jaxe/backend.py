"""JaxBackend: the SimulatorBackend implementation running on TPU/XLA.

Exactness contract: placements are
IDENTICAL to ReferenceBackend — verified by randomized differential tests —
across the full DefaultProvider feature set: resources/conditions/pressure,
taints/tolerations, node selectors, node affinity, hostname pins, scalar
resources, controller-avoid annotations, host ports,
services/selector-spreading, and inter-pod (anti)affinity (pod-group presence
state carried on device; state.GroupTables).

Compile-time fallbacks route to the reference backend (fallback="reference")
or raise (fallback="error"): pod-group budget overruns (merged groups >
TPUSIM_MAX_GROUPS, raw signatures > TPUSIM_MAX_RAW_GROUPS, matcher precompute
> TPUSIM_MAX_MATCH_WORK, presence bytes > TPUSIM_MAX_PRESENCE_BYTES — groups
merge by match profile first, so only behaviorally distinct classes count),
unresolvable PVC references on zone-constrained clusters (the reference's
NoVolumeZoneConflict *errors* host-side there), and the host-bound policy
shapes listed in jaxe/policyc.py (extenders only). Volume workloads run
natively on BOTH the fresh and incremental (event-log) paths.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional

import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Pod
from tpusim.backends import (
    Placement,
    ReferenceBackend,
    bind_pod,
    mark_unschedulable,
    placement_hash,
)
from tpusim.engine.generic_scheduler import NO_NODE_AVAILABLE_MSG
from tpusim.engine.providers import (
    CLUSTER_AUTOSCALER_PROVIDER,
    DEFAULT_PROVIDER,
    TD_PROVIDER,
)
from tpusim.jaxe import ensure_x64
from tpusim.jaxe.kernels import (
    EXPLAIN_SENTINEL,
    carry_init,
    config_for,
    explain_part_names,
    pod_columns_to_device,
    pod_columns_to_host,
    schedule_scan,
    schedule_scan_chunked,
    statics_to_device,
)
from tpusim.jaxe.state import NUM_FIXED_BITS, compile_cluster, reason_strings
from tpusim.obs import analytics
from tpusim.obs import provenance
from tpusim.obs import recorder as flight
from tpusim.obs import tracectx

log = logging.getLogger(__name__)


# process-wide fast-path auto-mode state: `disabled` flips the first time a
# self-verification chunk disagrees with the XLA scan or the kernel fails to
# compile/lower (never re-enabled); `verified_sigs` holds the kernel
# signatures (the _build_call variant: shape pads + feature flags) whose
# first large-enough batch verified — each distinct Pallas/Mosaic kernel
# variant earns trust separately (ADVICE r4: a process whose first verified
# batch was group-free must not run the group-featured kernel unverified);
# `transient` counts consecutive runtime (non-compile) failures — transient
# errors like a one-off device OOM do not permanently disable the path, but
# three in a row do.
_FAST_AUTO = {"disabled": False, "verified_sigs": set(), "transient": 0}

_TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "OOM",
                      "UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED")
_MAX_TRANSIENT_FAILURES = 3


def reset_fast_auto() -> None:
    """Reset every process-wide fast-path/victim-kernel trust flag to its
    boot state. Test isolation ONLY: a test that trips the transient path or
    a verify failure would otherwise leak `disabled`/pinned-signature state
    into every later test in the process (ordering could then flip fast-path
    eligibility mid-session). Wired as an autouse fixture in
    tests/conftest.py; production code never calls it."""
    _FAST_AUTO["disabled"] = False
    _FAST_AUTO["verified_sigs"] = set()
    _FAST_AUTO["transient"] = 0
    _VICTIM_AUTO["disabled"] = False
    _VICTIM_AUTO["verified_sigs"] = set()
    _SHARD_AUTO["disabled"] = False
    _SHARD_AUTO["verified_sigs"] = set()
    from tpusim.gang.kernel import _GANG_AUTO  # lazy: gang imports backend
    _GANG_AUTO["disabled"] = False
    _GANG_AUTO["verified_sigs"] = set()
    # disarm any leftover chaos seam (breaker + injector) the same way
    uninstall_chaos()


# plan_fast ineligibility reasons, classified into low-cardinality counter
# keys (the raw strings embed counts/budgets and would explode the label
# space); ordered — first substring match wins
_FALLBACK_KEYS = (
    ("policy static tables unavailable", "policy_tables_missing"),
    ("not compiled", "tables_not_compiled"),
    ("ServiceAffinity lock segments", "sa_segs_budget"),
    ("ServiceAffinity entry labels", "sa_segs_budget"),
    ("ServiceAntiAffinity label domains", "saa_doms_budget"),
    ("ServiceAntiAffinity spread counts", "saa_int32"),
    ("negative", "negative_scores"),
    ("pod groups exceed", "groups_budget"),
    ("zone domains exceed", "zones_budget"),
    ("topology keys exceed", "interpod_budget"),
    ("topology domains exceed", "interpod_budget"),
    ("inter-pod terms exceed", "interpod_budget"),
    ("inter-pod priority counts", "interpod_int32"),
    ("non-integral preferred inter-pod", "interpod_weights"),
    ("MaxPD volume ids", "maxpd_budget"),
    ("scalar resource kinds", "reason_bits_budget"),
    ("priority weights exceed", "score_int32"),
    ("int32", "int32_overflow"),
    ("explain lanes", "explain"),
)


def _fast_fallback_key(why: str) -> str:
    for marker, key in _FALLBACK_KEYS:
        if marker in why:
            return key
    return "other"


def _note_fast_fallback(metrics, why: str) -> None:
    """Surface a plan_fast rejection as observability (ISSUE 4 satellite):
    a labeled counter keyed by blocker class plus a flight-recorder instant
    carrying the full reason string."""
    key = _fast_fallback_key(why)
    metrics.fast_fallback.inc(key)
    flight.note_fast_fallback(key, why)


def plan_signature(plan) -> tuple:
    """The kernel-variant key for AUTO-mode trust: mirrors the _build_call
    cache key's semantic axes (node padding, feature flags, scalar/group
    widths) — a Mosaic miscompile is per compiled variant, so verification
    of one variant must not exempt another."""
    sig = (plan.alloc_cpu.shape[1], plan.most_requested, plan.num_scalars,
           plan.num_groups, plan.n_zone_doms, plan.has_ports,
           plan.has_disk, plan.has_spread, plan.has_vol_zone)
    if plan.has_interpod:
        # the exist-side tables and hard weight are BAKED into the compiled
        # kernel (part of the _build_call cache key): same dims with
        # different constants is a different Mosaic program and must earn
        # trust separately
        sig += (plan.n_topo_keys, plan.n_topo_doms_ip, plan.ta, plan.tb,
                plan.tp, plan.hard_weight, plan.exist_anti_key,
                plan.exist_anti_mask, plan.exist_anti_empty,
                plan.exist_pref_key, plan.exist_pref_w,
                plan.exist_aff_key, plan.exist_aff_mask)
    if plan.has_maxpd:
        # volume type triples and limits are baked into the kernel variant
        sig += (plan.n_vols, plan.vol_type3, plan.maxpd_limits,
                plan.maxpd_enabled)
    if plan.policy is not None:
        # the whole PolicySpec (hashable) is baked into the variant, plus
        # the policy-residue table dims/flags (PolConst rides the
        # _build_call cache key the same way)
        from tpusim.jaxe.fastscan import pol_const_of

        sig += (plan.policy, pol_const_of(plan))
    return sig


def _note_fast_failure(exc: Exception) -> None:
    """Classify a fast-path failure: compile/lowering rejections disable the
    path permanently (re-attempting re-uploads the plan and fails again);
    transient runtime errors keep it enabled until _MAX_TRANSIENT_FAILURES
    consecutive strikes (ADVICE r4)."""
    msg = f"{type(exc).__name__}: {exc}"
    if any(marker in msg for marker in _TRANSIENT_MARKERS):
        _FAST_AUTO["transient"] += 1
        flight.note_auto_transition("discard_transient")
        if _FAST_AUTO["transient"] >= _MAX_TRANSIENT_FAILURES:
            _FAST_AUTO["disabled"] = True
            flight.note_auto_transition("discard_permanent")
            log.warning("pallas fast path: %d consecutive transient "
                        "failures; disabling it for this process",
                        _FAST_AUTO["transient"])
        else:
            log.warning("pallas fast path: transient failure %d/%d (%s); "
                        "will retry on the next batch",
                        _FAST_AUTO["transient"], _MAX_TRANSIENT_FAILURES,
                        msg)
        return
    _FAST_AUTO["disabled"] = True
    flight.note_auto_transition("discard_permanent")
    log.warning("pallas fast path: compile/lowering failure (%s); "
                "disabling it for this process", msg)


def _auto_verify_and_pin(config, compiled, cols, choices, counts,
                         sig: tuple, limit: int = None,
                         statics=None, carry=None) -> bool:
    """AUTO-mode guardrail (shared by run_batch and the what-if fast loop):
    replay the leading pods through the XLA scan and compare bit-for-bit.
    Returns True when the fast results may be used; on disagreement the
    fast path is disabled for the process. Trust is pinned per kernel
    signature, only on a batch of TPUSIM_FAST_VERIFY_MIN+ pods."""
    from tpusim.jaxe.fastscan import verify_against_xla

    m = min(int(os.environ.get("TPUSIM_FAST_VERIFY_PODS", 512)),
            len(np.asarray(cols.req_cpu)))
    if limit is not None:
        # the caller produced fewer rows than the full batch (the
        # preemption hybrid verifies on its first speculation chunk)
        m = min(m, limit)
    if not verify_against_xla(config, compiled, cols, choices, counts, m,
                              statics=statics, carry=carry):
        _FAST_AUTO["disabled"] = True
        flight.note_auto_transition("verify_fail", str(sig))
        log.warning("pallas fast path DISAGREES with the XLA scan on the "
                    "first %d pods; disabling it for this process and "
                    "re-running on the XLA scan", m)
        return False
    flight.note_auto_transition("verify_pass", str(sig))
    min_pin = int(os.environ.get("TPUSIM_FAST_VERIFY_MIN", 64))
    if m >= min_pin:
        _FAST_AUTO["verified_sigs"].add(sig)
        flight.note_auto_transition("pin", str(sig))
        log.info("pallas fast path self-verified on the first %d pods; "
                 "trusting kernel variant %s for this process", m, sig)
    else:
        log.info("pallas fast path verified on %d pods (< %d): keeping "
                 "per-batch verification on", m, min_pin)
    return True


def _fast_path_enabled() -> tuple[bool, bool]:
    """Returns (enabled, auto_mode).

    TPUSIM_FAST=1 forces the Pallas fused-scan fast path (jaxe.fastscan) on
    for eligible workloads (group-free, plus ports/disk-conflict/spreading/
    volume-zone group features within the fast-path budgets), =0 forces it
    off. Unset = AUTO: on
    TPU the fast path is default-ON with first-chunk self-verification —
    before trusting a kernel variant's first fast run, the backend re-runs
    the leading pods through the XLA scan and compares choices bit-for-bit,
    falling back (and disabling the fast path for the process) on any
    disagreement. Off-TPU the kernel would run in the Pallas interpreter —
    far slower than the XLA scan — so non-TPU backends require the explicit
    opt-in with TPUSIM_FAST_INTERPRET=1 (correctness runs).

    A process-wide `disabled` flag (verify disagreement, compile/lowering
    failure, or repeated transient failures) is honored in BOTH modes: a
    persistently failing kernel under forced TPUSIM_FAST=1 must not
    re-attempt (and re-upload the plan) on every batch (ADVICE r4)."""
    env = os.environ.get("TPUSIM_FAST")
    if env == "0":
        return False, False
    if _FAST_AUTO["disabled"]:
        return False, False
    if env == "1":
        if os.environ.get("TPUSIM_FAST_INTERPRET") == "1":
            return True, False
        import jax

        return jax.default_backend() == "tpu", False
    # AUTO (round-3 VERDICT item 2: default-on on TPU, kill-switch kept)
    import jax

    return jax.default_backend() == "tpu", True

# process-wide trust state for the device-side preemption victim-selection
# kernel (jaxe/preempt.py), mirroring _FAST_AUTO: `disabled` flips on the
# first device/host disagreement (never re-enabled); `verified_sigs` holds
# (candidate_bucket, victim_bucket, zero_req) kernel-variant signatures whose
# first device-selected preemption byte-matched the full host oracle
# (selectVictimsOnNode + pickOneNodeForPreemption on cloned NodeInfos) —
# pow2-bucketed shapes mean each compiled variant earns trust separately.
_VICTIM_AUTO = {"disabled": False, "verified_sigs": set()}


def victim_kernel_enabled() -> tuple[bool, bool]:
    """Returns (enabled, auto_mode) for the preemption victim-selection
    kernel.

    TPUSIM_PREEMPT_DEVICE=0 forces the host pipeline, =1 forces the device
    kernel WITHOUT first-use verification (benchmark/debug). Unset = AUTO:
    default-ON on every backend — the kernel is a jitted XLA scan (not
    Pallas), fast on CPU too — with first-preemption-per-variant
    verification against the host oracle; any disagreement disables the
    kernel for the process and the host result is used, so AUTO can never
    change behavior. The `disabled` flag is honored in both modes."""
    env = os.environ.get("TPUSIM_PREEMPT_DEVICE")
    if env == "0":
        return False, False
    if _VICTIM_AUTO["disabled"]:
        return False, False
    if env == "1":
        return True, False
    return True, True


# process-wide trust state for the node-sharded scan route (ISSUE 16),
# mirroring _FAST_AUTO: `disabled` flips the first time a sharded dispatch's
# choices/counts disagree with the single-device replay (never re-enabled);
# `verified_sigs` holds (shard_count, config) pairs whose first batch
# verified — a different shard count or engine config compiles a different
# collective program and earns trust separately.
_SHARD_AUTO = {"disabled": False, "verified_sigs": set()}


def _shard_count() -> int:
    """TPUSIM_SHARDS=k (k > 1) opts the XLA scan into the node-sharded
    shard_map route over a k-device mesh. Unset, 1, 0, or garbage selects
    the single-device route — k=1 must not even build a mesh, so those
    placement chains stay byte-identical to pre-shard builds."""
    try:
        k = int(os.environ.get("TPUSIM_SHARDS", "1"))
    except ValueError:
        return 1
    return k if k > 1 else 1


def _dispatch_sharded(config, mesh, n_shards, statics, carry, xs,
                      use_chunks, scan_chunk, metrics):
    """One node-sharded dispatch: pad the node axis shard-even, place the
    trees per the mesh's node shardings, run the shard_map scan (chunked
    or single), and stamp the tpusim_shard_* telemetry. Returns
    (final_carry, choices, counts, sharded_statics) — the carry/statics
    come back padded + sharded for the analytics reduction to fold."""
    from dataclasses import replace as _dc_replace

    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpusim.jaxe.kernels import sharded_scan_fn
    from tpusim.jaxe.sharding import (
        node_shardings,
        pad_node_axis,
        stage_tree,
    )

    sconfig = _dc_replace(config, shard_axis="node")
    with flight.span("shard:stage") as ssp:
        st, ca, n_real = pad_node_axis(statics, carry, n_shards)
        st_sh, ca_sh = node_shardings(mesh)
        st = stage_tree(st, st_sh)
        ca = stage_tree(ca, ca_sh)
        if ssp:
            ssp.set("shards", n_shards)
            ssp.set("nodes", n_real)
    per = st.alloc_cpu.shape[0] // n_shards
    metrics.shard_count.set(n_shards)
    for s in range(n_shards):
        metrics.shard_node_occupancy.set(
            str(s), max(0, min(n_real - s * per, per)))
    # estimated collective payload: each pod step moves ~12 psum/pmax/pmin
    # scalars plus an n_shards-wide tie-count all_gather, 8 bytes each, on
    # every shard (the analytics/gang collectives are separate dispatches)
    n_pods = int(np.asarray(xs.req_cpu).shape[0])
    metrics.shard_collective_bytes.set(
        float(n_pods) * (12 + n_shards) * 8 * n_shards)
    rep = NamedSharding(mesh, P())
    with flight.span("shard:scan", "device") as sp:
        if use_chunks:
            final_carry, choices, counts, _ = schedule_scan_chunked(
                config, ca, st, xs, scan_chunk,
                scan_donated=sharded_scan_fn(sconfig, mesh, donate=True),
                put=lambda rows: stage_tree(rows, rep))
        else:
            final_carry, choices, counts, _ = sharded_scan_fn(
                sconfig, mesh)(ca, st, stage_tree(xs, rep))
        if sp:
            sp.set("shards", n_shards)
            sp.set("pods", n_pods)
    return final_carry, choices, counts, st


_MOST_REQUESTED_PROVIDERS = {CLUSTER_AUTOSCALER_PROVIDER, TD_PROVIDER}
_KNOWN_PROVIDERS = {DEFAULT_PROVIDER} | _MOST_REQUESTED_PROVIDERS


# process-wide chaos seam (ISSUE 3): when installed, every JaxBackend
# dispatch flows through the circuit breaker (closed → open on repeated
# device faults → half-open re-probe → closed) and the DeviceInjector
# scripts per-dispatch exceptions/corruptions. Upgrades the _FAST_AUTO
# three-strikes-and-permanently-out policy into a RECOVERING state
# machine: a flaky device degrades to the host pipeline and comes back,
# and under chaos no placement is ever emitted unverified (verify="all")
# or un-re-probed (half-open).
_CHAOS = {"injector": None, "breaker": None, "verify": "all"}


def install_chaos(device_plan):
    """Arm the device-fault layer of a chaos plan
    (tpusim.chaos.plan.DeviceFaultPlan). Returns the CircuitBreaker so
    callers can assert on its transition audit trail."""
    from tpusim.chaos.breaker import CircuitBreaker
    from tpusim.chaos.engine import DeviceInjector

    device_plan.validate()
    breaker = CircuitBreaker("device", device_plan.failure_threshold,
                             device_plan.cooldown)
    _CHAOS.update(injector=DeviceInjector(device_plan.faults),
                  breaker=breaker, verify=device_plan.verify)
    return breaker


def uninstall_chaos() -> None:
    _CHAOS.update(injector=None, breaker=None, verify="all")


def format_fit_error(num_nodes: int, counts: np.ndarray, strings: List[str]) -> str:
    """Byte-identical FitError.Error() (generic_scheduler.go:71-90)."""
    reason_strs = sorted(f"{int(c)} {strings[i]}"
                         for i, c in enumerate(counts) if c > 0)
    return (NO_NODE_AVAILABLE_MSG.format(num_nodes)
            + ": " + ", ".join(reason_strs) + ".")


def decode_placements(pods: List[Pod], choices: np.ndarray, counts: np.ndarray,
                      names: List[str], strings: List[str],
                      prebound: Optional[List[Placement]] = None
                      ) -> tuple[List[Placement], int]:
    """Device results -> Placement list (shared by JaxBackend and run_what_if).

    prebound: already-constructed Placements for the scheduled pods, in pod
    order — the pipelined fold-back (stream/runtime._fold_binds) binds each
    placed pod once to feed the host IncrementalCluster, and handing those
    objects in here avoids a second bind_pod copy per placement."""
    placements: List[Placement] = []
    bound_iter = iter(prebound) if prebound is not None else None
    scheduled = 0
    for j, pod in enumerate(pods):
        c = int(choices[j])
        if c >= 0:
            scheduled += 1
            placements.append(next(bound_iter) if bound_iter is not None
                              else Placement(pod=bind_pod(pod, names[c]),
                                             node_name=names[c]))
        else:
            msg = format_fit_error(len(names), counts[j], strings)
            placements.append(Placement(pod=mark_unschedulable(pod, msg),
                                        reason="Unschedulable", message=msg))
    return placements, scheduled


class JaxBackend:
    name = "jax"

    def __init__(self, provider: str = DEFAULT_PROVIDER, fallback: str = "reference",
                 hard_pod_affinity_symmetric_weight: int = 10,
                 policy=None, compiled_policy=None, extender_transport=None):
        """policy: an engine.policy.Policy compiled to static gating + weights
        (jaxe.policyc) — replaces the provider's predicate/priority sets like
        factory.go CreateFromConfig; host-bound policy features (extenders,
        ServiceAffinity, ...) route through the fallback. compiled_policy: a
        jaxe.policyc.CompiledPolicy for `policy`, if the caller already
        compiled it. extender_transport: the in-process extender seam handed
        to the reference fallback (policy extenders are host-bound)."""
        if provider not in _KNOWN_PROVIDERS:
            raise KeyError(f"plugin {provider!r} has not been registered")
        if fallback not in ("reference", "error"):
            raise ValueError("fallback must be 'reference' or 'error'")
        if not 1 <= hard_pod_affinity_symmetric_weight <= 100:
            # factory.go:1024-1026 — the host backend rejects this range in
            # _create_from_keys; the device backend must match
            raise ValueError("invalid hardPodAffinitySymmetricWeight: "
                             f"{hard_pod_affinity_symmetric_weight}, must be "
                             "in the range 1-100")
        self.provider = provider
        self.fallback = fallback
        self.hard_pod_affinity_symmetric_weight = hard_pod_affinity_symmetric_weight
        self.policy = policy
        self.extender_transport = extender_transport
        if policy is not None and compiled_policy is None:
            # compile (and validate) at build time, like CreateFromConfig
            from tpusim.jaxe.policyc import compile_policy

            compiled_policy = compile_policy(policy)
        self._compiled_policy = compiled_policy

    def _reference(self, pods: List[Pod],
                   snapshot: ClusterSnapshot) -> List[Placement]:
        placements = ReferenceBackend(
            provider=self.provider, policy=self.policy,
            extender_transport=self.extender_transport,
            hard_pod_affinity_symmetric_weight=self.hard_pod_affinity_symmetric_weight,
        ).schedule(pods, snapshot)
        # host-route decisions carry provenance too (failures-only: the
        # reference path computes no per-part score lanes); FitError text
        # is the host original by construction
        provenance.capture(placements, "reference")
        return placements

    def schedule(self, pods: List[Pod], snapshot: ClusterSnapshot,
                 precompiled=None) -> List[Placement]:
        """Device dispatch behind the chaos circuit breaker (when armed via
        install_chaos; a no-op wrapper otherwise). The contract under
        chaos: a denied or faulted dispatch routes the batch through the
        host pipeline (byte-identical placements), a half-open probe — and
        every dispatch under verify="all" — is host-verified before its
        placements are emitted, so a flaky device can never surface an
        unverified result, and a recovered device is re-trusted after one
        verified probe."""
        breaker = _CHAOS["breaker"]
        if breaker is None:
            return self._schedule_on_device(pods, snapshot, precompiled)
        if not pods:
            return []
        from tpusim.chaos.engine import DeviceFault

        if not breaker.allow():
            flight.note_route("breaker_fallback", len(pods))
            return self._reference(pods, snapshot)
        probing = breaker.probing
        try:
            placements = self._schedule_on_device(pods, snapshot, precompiled)
        except DeviceFault as exc:
            breaker.record_failure(f"{type(exc).__name__}: {exc}")
            flight.note_route("breaker_fallback", len(pods))
            return self._reference(pods, snapshot)
        if probing or _CHAOS["verify"] == "all":
            expected = self._reference(pods, snapshot)
            if placement_hash(placements) != placement_hash(expected):
                # silent corruption: in-range but wrong placements — only
                # the host parity digest catches it
                breaker.record_failure("device/host placement divergence")
                flight.note_route("breaker_fallback", len(pods))
                return expected
        breaker.record_success()
        return placements

    def _schedule_on_device(self, pods: List[Pod], snapshot: ClusterSnapshot,
                            precompiled=None) -> List[Placement]:
        """precompiled: an optional (CompiledCluster, PodColumns) pair for
        `pods` against `snapshot` — the incremental event-log path
        (jaxe.delta.IncrementalCluster.compile) hands its cached state in
        here instead of recompiling."""
        if not pods:
            return []
        if not snapshot.nodes:
            msg = "no nodes available to schedule pods"
            placements = [Placement(pod=mark_unschedulable(p, msg),
                                    reason="Unschedulable", message=msg)
                          for p in pods]
            provenance.capture(placements, "backend")
            return placements
        # a wedged accelerator tunnel must degrade to CPU, not hang the
        # first device op (or the AUTO fast-path gate's default_backend())
        from time import perf_counter

        from tpusim.framework.metrics import register, since_in_microseconds
        from tpusim.jaxe import ensure_responsive_platform

        metrics = register()
        ensure_responsive_platform()

        cp = self._compiled_policy
        from tpusim.engine.predicates import (
            POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
        )

        need_noexec = (cp is not None and cp.spec.pred_keys is not None
                       and POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED
                       in cp.spec.pred_keys)
        need_saa = cp is not None and (bool(cp.spec.saa_weights)
                                       or cp.spec.sa_enabled)
        def _timed_compile():
            compile_start = perf_counter()
            with flight.span("compile_cluster") as csp:
                out = compile_cluster(snapshot, pods, need_noexec=need_noexec,
                                      need_saa=need_saa)
                if csp:
                    csp.set("pods", len(pods))
                    csp.set("nodes", len(snapshot.nodes))
            compile_us = since_in_microseconds(compile_start)
            metrics.backend_compile_latency.observe(compile_us)
            analytics.note_compile(
                "backend", f"nodes={len(snapshot.nodes)}", compile_us)
            return out

        compiled, cols = precompiled or _timed_compile()
        if (need_noexec and not compiled.has_noexec_table) \
                or (need_saa and not compiled.has_saa_table):
            # a precompiled (event-log/incremental) state built without the
            # policy-only tables: recompile fresh for this rare combination
            compiled, cols = _timed_compile()
        unsupported = list(compiled.unsupported)
        if cp is not None:
            unsupported.extend(cp.unsupported)
        if unsupported:
            detail = "; ".join(sorted(set(unsupported))[:5])
            if self.fallback == "error":
                raise NotImplementedError(
                    f"jax backend does not yet carry state for: {detail}")
            log.warning("jax backend falling back to reference for: %s", detail)
            flight.note_route("reference_fallback", len(pods))
            return self._reference(pods, snapshot)

        hard_weight = self.hard_pod_affinity_symmetric_weight
        if cp is not None and cp.hard_weight is not None:
            hard_weight = cp.hard_weight
        num_bits = NUM_FIXED_BITS + len(compiled.scalar_names)
        config = config_for(
            [compiled],
            most_requested=self.provider in _MOST_REQUESTED_PROVIDERS,
            num_reason_bits=num_bits,
            hard_weight=hard_weight)
        ptabs = None
        if cp is not None:
            from dataclasses import replace as _dc_replace

            from tpusim.jaxe.policyc import build_policy_tables

            config = _dc_replace(config, policy=cp.spec)
            # one host-side table build feeds BOTH device routes: plan_fast
            # bakes these into the Pallas plan, the XLA branch grafts them
            # onto the trivial statics rows below (also fills cols.img_id /
            # cols.sa_self_id in place), so the two engines cannot drift on
            # their inputs
            ptabs = build_policy_tables(cp, snapshot, pods, compiled, cols)
            if cp.saa_entries:
                config = _dc_replace(config, n_saa_doms=ptabs.n_saa_doms)

        # decision provenance (ISSUE 13): an installed explain log that
        # asked for a score breakdown compiles the top-k lanes into the
        # scan. explain_k is a jit static, so provenance-off programs (the
        # default) are byte-identical to pre-provenance ones.
        explain_k = provenance.requested_top_k()
        if explain_k > 0:
            from dataclasses import replace as _explain_replace

            config = _explain_replace(
                config,
                explain_k=min(explain_k, len(compiled.statics.names)))

        ensure_x64()
        # fast-path decision BEFORE any device upload: when the Pallas plan
        # engages, the statics/carry/pod-column HBM transfers below would be
        # pure wasted latency on exactly the hot path the feature accelerates
        fplan = None
        fast_verify = False
        fast_sig = None
        fast_on, auto_mode = _fast_path_enabled()
        if fast_on and config.explain_k > 0:
            # the score-breakdown lanes are an XLA-scan feature: the Pallas
            # kernel carries failure provenance natively (reason counts) but
            # not per-part top-k scores (DEVIATIONS.md)
            fast_on = False
            _note_fast_fallback(
                metrics, "explain lanes (top-k score breakdown) route "
                "through the XLA scan")
        if fast_on and analytics.get() is not None:
            # the analytics reduction folds the scan's final carry, which
            # the Pallas kernel never materializes (it emits choices/counts
            # only) — same precedent as the explain lanes above
            fast_on = False
            _note_fast_fallback(
                metrics, "cluster analytics rides the XLA scan's final "
                "carry")
        if (fast_on and auto_mode and not _FAST_AUTO["verified_sigs"]
                and len(pods) < int(os.environ.get(
                    "TPUSIM_FAST_VERIFY_MIN", 64))):
            # no variant is trusted yet, so this small batch would be
            # deferred after planning anyway — skip the O(nodes+pods)
            # gcd reduction entirely (the pre-signature fast exit)
            fast_on = False
            flight.note_auto_transition("defer")
            log.info("pallas fast path deferred: %d pods is below "
                     "the self-verification threshold; using the "
                     "XLA scan", len(pods))
        if fast_on:
            from tpusim.jaxe.fastscan import plan_fast

            fplan, why = plan_fast(config, compiled, cols, ptabs=ptabs)
            if fplan is None:
                _note_fast_fallback(metrics, why)
                log.info("pallas fast path ineligible (%s); using the "
                         "XLA scan", why)
            else:
                fast_sig = plan_signature(fplan)
                fast_verify = (auto_mode and fast_sig
                               not in _FAST_AUTO["verified_sigs"])
            if fplan is not None and fast_verify and len(pods) < int(
                    os.environ.get("TPUSIM_FAST_VERIFY_MIN", 64)):
                # AUTO mode, variant not yet trusted: a batch too small to
                # pin trust (< TPUSIM_FAST_VERIFY_MIN) would run the kernel
                # AND a full XLA replay — strictly slower than plain XLA.
                # Small batches gain nothing from the fast path anyway;
                # route them straight to the XLA scan.
                fplan = None
                fast_verify = False
                flight.note_auto_transition("defer")
                log.info("pallas fast path deferred: %d pods is below "
                         "the self-verification threshold; using the "
                         "XLA scan", len(pods))
        def _xla_statics():
            if cp is None:
                return statics_to_device(compiled)
            # overwrite the trivial custom-plugin rows with the policy's
            # per-node tables (ordering by the compiled node index); the
            # trivial PolicyTables shapes match statics_to_host exactly, so
            # the unconditional replace is byte-identical for policies
            # without the corresponding feature
            from tpusim.jaxe.kernels import _tree_to_device, statics_to_host

            return _tree_to_device(statics_to_host(compiled)._replace(
                label_ok=ptabs.label_ok, label_prio=ptabs.label_prio,
                image_score=ptabs.image_score, saa_dom=ptabs.saa_dom,
                sa_pin=ptabs.sa_pin, sa_val=ptabs.sa_val))

        def _xla_carry():
            carry = carry_init(compiled)
            if cp is not None and cp.spec.sa_enabled:
                carry = carry._replace(sa_lock=ptabs.sa_lock_init)
            return carry

        statics = None if fplan is not None else _xla_statics()
        # Batches beyond TPUSIM_SCAN_CHUNK pods run through the
        # double-buffered chunked scan: pod columns stay host-side and stream
        # to HBM chunk by chunk, bit-identical to the single dispatch
        # (SURVEY.md §7 hard part 6 — 1M-pod batches).
        scan_chunk = int(os.environ.get("TPUSIM_SCAN_CHUNK", 131072))
        use_chunks = (fplan is None and config.explain_k == 0
                      and scan_chunk > 0 and len(pods) > scan_chunk)
        if fplan is None:
            carry = _xla_carry()
            xs = (pod_columns_to_host(cols) if use_chunks
                  else pod_columns_to_device(cols))
        # On TPU the per-pod filter→score→select→bind pipeline is one fused
        # device program, so the whole batch dispatch lands in the algorithm
        # histogram (the per-phase split of metrics.go has no device analog);
        # e2e additionally covers host-side result materialization.
        # chaos seam: scripted dispatch faults raise here (outside the fast
        # path's own try/except — an injected device death is not a Mosaic
        # lowering failure and must reach the circuit breaker, not flip
        # _FAST_AUTO); scripted corruptions apply to the results below
        _corrupt_kind = None
        if _CHAOS["injector"] is not None:
            _corrupt_kind = _CHAOS["injector"].begin_dispatch()
        dispatch_start = perf_counter()
        dsp = flight.span("device_dispatch", "device")

        def _discard_fast_path():
            # pay the uploads the fast path deferred and rebuild the
            # XLA-scan inputs (set via nonlocal) with a fresh dispatch
            # clock; whether the path stays disabled for the process is the
            # caller's call (_note_fast_failure / _auto_verify_and_pin)
            nonlocal fplan, statics, carry, use_chunks, xs, dispatch_start
            fplan = None
            statics = _xla_statics()
            carry = _xla_carry()
            use_chunks = (config.explain_k == 0
                          and scan_chunk > 0 and len(pods) > scan_chunk)
            xs = (pod_columns_to_host(cols) if use_chunks
                  else pod_columns_to_device(cols))
            dispatch_start = perf_counter()

        if fplan is not None:
            from tpusim.jaxe.fastscan import fast_scan

            try:
                with flight.profiled("tpusim:fast_scan"):
                    choices, counts, _adv = fast_scan(fplan)
            except Exception as exc:
                # A Mosaic lowering/compile rejection on this backend must
                # degrade to the XLA scan, not crash the process: an abrupt
                # exit mid-device-context has wedged the axon tunnel before
                # (round-4 capture, BASELINE.md). _note_fast_failure
                # decides whether the failure disables the path for the
                # process (compile/lowering) or allows retries (transient).
                log.warning("pallas fast path failed on this backend "
                            "(%s: %s); falling back to the XLA scan",
                            type(exc).__name__, exc)
                _note_fast_failure(exc)
                _discard_fast_path()
            else:
                _FAST_AUTO["transient"] = 0
                if fast_verify and not _auto_verify_and_pin(
                        config, compiled, cols, choices, counts, fast_sig,
                        statics=_xla_statics(), carry=_xla_carry()):
                    # the kernel lowered but miscomputed: the guardrail
                    # already disabled it process-wide; rerun on XLA
                    _discard_fast_path()
                elif auto_mode and not fast_verify:
                    # already-pinned variant ran without re-verification
                    flight.note_auto_transition("trust", str(fast_sig))
        # node-sharded route decision (ISSUE 16): TPUSIM_SHARDS=k > 1 runs
        # the same fused scan as a shard_map over a k-device node mesh —
        # bit-identical placements via cross-shard collectives, so every
        # ineligibility is a classified fallback to the single-device scan,
        # never a behavior change
        n_shards = _shard_count()
        shard_mesh = None
        shard_statics = None
        if fplan is None and n_shards > 1 and not _SHARD_AUTO["disabled"]:
            import jax

            from tpusim.jaxe.kernels import shard_route_eligible

            ok, why = shard_route_eligible(config)
            if ok and len(jax.devices()) < n_shards:
                ok, why = False, "device_count"
            if not ok:
                metrics.shard_fallback.inc(why)
                flight.note_fast_fallback(
                    "shard_" + why,
                    f"TPUSIM_SHARDS={n_shards} batch routed single-device")
                log.info("sharded route ineligible (%s); using the "
                         "single-device scan", why)
            else:
                from tpusim.jaxe.sharding import make_mesh

                shard_mesh = make_mesh(n_shards, snap=1)
        explain_lanes = None
        final_carry = None  # bound-and-dropped unless analytics reads it
        if fplan is None:  # fast path off, ineligible, or discarded above
            with flight.profiled("tpusim:schedule_scan"):
                if shard_mesh is not None:
                    (final_carry, choices, counts,
                     shard_statics) = _dispatch_sharded(
                         config, shard_mesh, n_shards, statics, carry,
                         xs, use_chunks, scan_chunk, metrics)
                elif use_chunks:
                    final_carry, choices, counts, _ = schedule_scan_chunked(
                        config, carry, statics, xs, scan_chunk)
                elif config.explain_k > 0:
                    (final_carry, choices, counts, _,
                     explain_lanes) = schedule_scan(config, carry,
                                                    statics, xs)
                else:
                    (final_carry, choices, counts,
                     _) = schedule_scan(config, carry, statics, xs)
        choices = np.asarray(choices)
        counts = np.asarray(counts)
        if shard_mesh is not None:
            # verify-then-trust, the same seam as the fast path: the first
            # batch per (shard count, config) replays its leading pods
            # through the single-device scan bit-for-bit; a disagreement
            # disables the sharded route for the process and this batch
            # reruns single-device (TPUSIM_SHARD_VERIFY=0 skips, bench only)
            shard_sig = (n_shards, config)
            if os.environ.get("TPUSIM_SHARD_VERIFY") == "0":
                pass
            elif shard_sig in _SHARD_AUTO["verified_sigs"]:
                flight.note_auto_transition("shard_trust", str(n_shards))
            else:
                from tpusim.jaxe.fastscan import verify_against_xla

                if verify_against_xla(config, compiled, cols, choices,
                                      counts, statics=statics,
                                      carry=_xla_carry()):
                    _SHARD_AUTO["verified_sigs"].add(shard_sig)
                    flight.note_auto_transition("shard_pin", str(n_shards))
                else:
                    _SHARD_AUTO["disabled"] = True
                    metrics.shard_count.set(0)
                    flight.note_auto_transition("shard_verify_fail",
                                                str(n_shards))
                    log.warning(
                        "sharded scan DISAGREES with the single-device "
                        "scan on the leading pods (shards=%d); disabling "
                        "the sharded route for this process and re-running "
                        "single-device", n_shards)
                    shard_mesh = None
                    shard_statics = None
                    with flight.profiled("tpusim:schedule_scan"):
                        if use_chunks:
                            (final_carry, choices, counts,
                             _) = schedule_scan_chunked(
                                 config, _xla_carry(), statics, xs,
                                 scan_chunk)
                        else:
                            (final_carry, choices, counts,
                             _) = schedule_scan(config, _xla_carry(),
                                                statics, xs)
                    choices = np.asarray(choices)
                    counts = np.asarray(counts)
        if _CHAOS["injector"] is not None:
            if _corrupt_kind is not None:
                from tpusim.chaos.engine import DeviceInjector

                choices, counts = DeviceInjector.corrupt(_corrupt_kind,
                                                         choices, counts)
            # structural validation always runs under chaos: out-of-range
            # choices and NaN counts never reach decode_placements
            from tpusim.chaos.engine import DeviceOutputError

            n_nodes = len(compiled.statics.names)
            if choices.size and (int(choices.max()) >= n_nodes
                                 or int(choices.min()) < -1):
                raise DeviceOutputError(
                    f"device choice out of range [-1, {n_nodes})")
            if np.isnan(np.asarray(counts, dtype=np.float64)).any():
                raise DeviceOutputError("NaN in device reason counts")
        if fplan is not None:
            # the interpreter only engages on the explicit TPUSIM_FAST=1
            # opt-in (see _fast_path_enabled)
            route = ("fastscan_interpret"
                     if os.environ.get("TPUSIM_FAST") == "1"
                     and os.environ.get("TPUSIM_FAST_INTERPRET") == "1"
                     else "fastscan")
        elif shard_mesh is not None:
            route = "xla_sharded_chunked" if use_chunks else "xla_sharded"
        else:
            route = "xla_chunked" if use_chunks else "xla_scan"
        flight.note_route(route, len(pods))
        if dsp:
            dsp.set("route", route)
            dsp.set("pods", len(pods))
            if shard_mesh is not None:
                dsp.set("shards", n_shards)
            if fast_sig is not None:
                dsp.set("sig", str(fast_sig))
            dsp.end()
        # trace-id exemplar (ISSUE 20): a dispatch-latency spike on the
        # dashboard resolves to the exact device-dispatch trace
        _ctx = tracectx.current()
        metrics.backend_dispatch_latency.observe(
            since_in_microseconds(dispatch_start),
            exemplar=_ctx.trace_id if _ctx is not None else None)
        metrics.scheduling_algorithm_latency.observe(
            since_in_microseconds(dispatch_start))

        strings = reason_strings(compiled.scalar_names)
        with flight.span("decode_placements"):
            placements, _ = decode_placements(pods, choices, counts,
                                              compiled.statics.names, strings)
        prov = provenance.get_log()
        if prov is not None:
            topk = None
            if explain_lanes is not None:
                top_idx, top_scores, top_parts = explain_lanes
                p = len(pods)
                topk = {"idx": np.asarray(top_idx)[:p],
                        "scores": np.asarray(top_scores)[:p],
                        "parts": np.asarray(top_parts)[:p],
                        "names": compiled.statics.names,
                        "part_names": explain_part_names(config),
                        "sentinel": EXPLAIN_SENTINEL}
            prov.capture_batch(placements, "backend", topk=topk)
        if final_carry is not None:
            # one None-check inside; the reduction folds the POST-bind
            # carry this batch produced against the staged statics — on the
            # sharded route both trees are padded + node-sharded, so the
            # reduction runs the two-level cross-shard merge
            analytics.capture(
                shard_statics if shard_mesh is not None else statics,
                final_carry, len(compiled.statics.names), "backend",
                names=compiled.statics.names, mesh=shard_mesh)
        # e2e additionally covers host-side result materialization
        metrics.e2e_scheduling_latency.observe(
            since_in_microseconds(dispatch_start))
        return placements

"""Preemption on the jax backend: a host-device hybrid.

Reference: the Preempt pipeline (core/generic_scheduler.go:205-1000) driven
from scheduleOne's error arm (scheduler.go:449-455). Victim selection is
inherently pod-identity-bound (remove lower-priority pods one by one, reprieve
in priority order, PDB-aware) — state the device deliberately does not carry
(the scan holds per-node aggregates + group presence, not per-pod rows). The
TPU-native split is therefore:

  device — the fused filter→score→select→bind scan schedules every pod that
           fits (tpusim/jaxe/kernels.py); a pod that fails leaves the carry
           untouched and does not advance the round-robin counter, so the
           decisions AFTER a failed pod stay valid.
  host   — only when a pod fails with the PodPriority gate on does the exact
           engine pipeline (GenericScheduler.preempt — the same code the
           reference backend runs) pick a node + victims against a host mirror
           of the cluster.

A successful preemption mutates state (victims deleted), which invalidates
the device's decisions for every later pod — so the scan re-dispatches from
the failed pod. The IncrementalCluster event path (tpusim/jaxe/delta.py)
keeps compiled columns in sync: binds stream in as ADDED events, victims as
DELETED events, so a re-dispatch recompiles only what changed (the
watch-fabric analog powering preemption). Re-dispatch batches are padded to
power-of-two buckets with provably-infeasible rows (req_cpu = 2^61 exceeds
any allocatable), bounding XLA recompiles to O(log P) per run; an infeasible
row can never bind or advance the rr counter, so padding is semantics-free.

A cheap host gate skips the preemption attempt entirely when no placed pod
has lower priority than the failed pod (selectVictimsOnNode can then never
produce a fitting node), so equal-priority workloads pay no host cost beyond
the mirror bookkeeping.
"""

from __future__ import annotations

import logging
from collections import Counter
from typing import List

import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Pod, PodCondition, ResourceType
from tpusim.engine.generic_scheduler import (
    ERR_NO_NODES_AVAILABLE,
    FitError,
    SchedulingError,
)
from tpusim.engine.providers import DEFAULT_PROVIDER
from tpusim.engine.util import get_pod_priority
from tpusim.framework.report import Status
from tpusim.framework.store import ADDED
from tpusim.framework.store import DELETED as EV_DELETED
from tpusim.jaxe import ensure_x64
from tpusim.jaxe.backend import (
    _MOST_REQUESTED_PROVIDERS,
    format_fit_error,
)
from tpusim.jaxe.delta import IncrementalCluster
from tpusim.jaxe.kernels import (
    PodX,
    carry_init,
    pad_infeasible_rows,
    config_for,
    pod_columns_to_host,
    schedule_scan,
    schedule_wavefront,
    statics_to_device,
)
from tpusim.jaxe.state import NUM_FIXED_BITS, reason_strings

log = logging.getLogger(__name__)

def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def run_with_preemption(pods: List[Pod], snapshot: ClusterSnapshot,
                        provider: str = DEFAULT_PROVIDER, batch_size: int = 0,
                        hard_pod_affinity_symmetric_weight: int = 10,
                        incremental: IncrementalCluster = None) -> Status:
    """Run `pods` (podspec order; the LIFO feed reversal happens here, like
    the reference's store.go:223-233 queue) with the PodPriority gate on.
    Returns the final Status with successful/failed/preempted buckets matching
    the reference backend's ClusterCapacity run.

    incremental: an IncrementalCluster already equivalent to `snapshot` (e.g.
    from an event-log replay) — reused instead of compiling a fresh one."""
    # deferred import: simulator imports this module's sibling lazily too
    from tpusim.simulator import ClusterCapacity, SchedulerServerConfig

    def host_config():
        return SchedulerServerConfig(
            algorithm_provider=provider,
            hard_pod_affinity_symmetric_weight=hard_pod_affinity_symmetric_weight,
            enable_pod_priority=True)

    # the host mirror: the same orchestrator the reference backend runs, fed
    # manually — binds via the Bind seam, failures via the Update seam, and
    # preemption via the shared attempt_preemption arm
    cc = ClusterCapacity(host_config(), new_pods=[],
                         scheduled_pods=snapshot.pods, nodes=snapshot.nodes,
                         services=snapshot.services, pvs=snapshot.pvs,
                         pvcs=snapshot.pvcs,
                         storage_classes=snapshot.storage_classes)
    feed = list(reversed(pods))
    if not feed:
        cc.status.stop_reason = cc.STOP_REASONS["run"]
        cc.close()
        return cc.status
    if not snapshot.nodes:
        # generic_scheduler raises ERR_NO_NODES_AVAILABLE — the plain
        # SchedulingError arm, which never enters the preemption pipeline
        for pod in feed:
            cc.resource_store.add(ResourceType.PODS, pod)
            cc.update(pod, PodCondition(
                type="PodScheduled", status="False", reason="Unschedulable",
                message=str(ERR_NO_NODES_AVAILABLE)))
        cc.status.stop_reason = cc.STOP_REASONS["failed"]
        cc.close()
        return cc.status

    inc = incremental if incremental is not None else IncrementalCluster(snapshot)
    # priority histogram of placed pods — the preemption-possible gate
    placed_priorities: Counter = Counter(
        get_pod_priority(p) for p in snapshot.pods if p.spec.node_name)
    attempts: dict = {}   # pod key -> preemption attempts (budget 1, like
    #                       _schedule_one's preempt_budget)
    remaining = feed
    full_size = len(feed)
    last_outcome = "run"
    metrics = cc.metrics
    first_dispatch = True
    rr_start = 0

    from time import perf_counter

    from tpusim.framework.metrics import since_in_microseconds

    while True:
        compiled, cols = inc.compile(remaining)
        if compiled.unsupported:
            if not first_dispatch:
                raise RuntimeError(
                    "jax preemption: compile fallback after binds were made "
                    f"({sorted(set(compiled.unsupported))[:3]})")
            log.warning("jax backend (preemption) falling back to reference "
                        "for: %s", "; ".join(sorted(set(compiled.unsupported))[:5]))
            ref = ClusterCapacity(host_config(), new_pods=pods,
                                  scheduled_pods=snapshot.pods,
                                  nodes=snapshot.nodes,
                                  services=snapshot.services, pvs=snapshot.pvs,
                                  pvcs=snapshot.pvcs,
                                  storage_classes=snapshot.storage_classes)
            ref.run()
            return ref.status

        num_bits = NUM_FIXED_BITS + len(compiled.scalar_names)
        config = config_for(
            [compiled],
            most_requested=provider in _MOST_REQUESTED_PROVIDERS,
            num_reason_bits=num_bits,
            hard_weight=hard_pod_affinity_symmetric_weight)
        ensure_x64()
        # lastNodeIndex persists across the whole run (generic_scheduler.go:97)
        # — re-dispatches resume the rr counter at the preemption point
        carry = carry_init(compiled)._replace(rr=np.int64(rr_start))
        statics = statics_to_device(compiled)
        xs_host = pod_columns_to_host(cols)
        if not first_dispatch:
            # bucket re-dispatch shapes so XLA recompiles O(log P) times
            bucket = min(_next_pow2(len(remaining)), full_size)
            xs_host = pad_infeasible_rows(xs_host, bucket - len(remaining))
        first_dispatch = False
        import jax.numpy as jnp

        xs = PodX(*(jnp.asarray(a) for a in xs_host))

        dispatch_start = perf_counter()
        if batch_size > 0:
            _, choices, counts, advanced = schedule_wavefront(
                config, carry, statics, xs, batch_size)
        else:
            _, choices, counts, advanced = schedule_scan(config, carry,
                                                         statics, xs)
        choices = np.asarray(choices)[:len(remaining)]
        counts = np.asarray(counts)[:len(remaining)]
        advanced = np.asarray(advanced)[:len(remaining)]
        metrics.scheduling_algorithm_latency.observe(
            since_in_microseconds(dispatch_start))

        strings = reason_strings(compiled.scalar_names)
        names = compiled.statics.names

        redispatch = False
        for j, pod in enumerate(remaining):
            cc.resource_store.add(ResourceType.PODS, pod)  # nextPod's store add
            c = int(choices[j])
            if c >= 0:
                cc.bind(pod, names[c])
                bound, _ = cc.resource_store.get(ResourceType.PODS, pod.key())
                inc.apply(ADDED, bound)
                placed_priorities[get_pod_priority(bound)] += 1
                last_outcome = "bound"
                continue

            # failure: the scan left the carry untouched, so later decisions
            # stay valid unless a preemption below mutates state
            pod_priority = get_pod_priority(pod)
            can_preempt = (
                cc.config.enable_pod_priority
                and attempts.get(pod.key(), 0) < 1
                and any(count > 0 and pri < pod_priority
                        for pri, count in placed_priorities.items()))
            if not can_preempt:
                cc.update(pod, PodCondition(
                    type="PodScheduled", status="False",
                    reason="Unschedulable",
                    message=format_fit_error(len(names), counts[j], strings)))
                last_outcome = "failed"
                continue

            # host arm: per-node failure reasons (the device ships only the
            # aggregate histogram), then the exact Preempt pipeline — both
            # against the cache's generation-checked snapshot, like the host
            # engine's g.cachedNodeInfoMap
            node_infos = cc.refresh_node_info_snapshot()
            try:
                filtered, failed = cc.scheduler.find_nodes_that_fit(
                    pod, cc.nodes, node_infos)
            except SchedulingError as exc:
                cc.update(pod, PodCondition(
                    type="PodScheduled", status="False",
                    reason="Unschedulable", message=str(exc)))
                last_outcome = "failed"
                continue
            if filtered:
                # device said infeasible, host disagrees — a parity bug; keep
                # the run coherent by trusting the host engine
                log.error("device/host disagreement for pod %s: host found %d "
                          "feasible nodes; using host placement", pod.key(),
                          len(filtered))
                cc.scheduler.last_node_index = rr_start + int(np.sum(advanced[:j]))
                host = cc.scheduler.schedule(pod, cc.nodes, node_infos)
                rr_start = cc.scheduler.last_node_index
                cc.bind(pod, host)
                bound, _ = cc.resource_store.get(ResourceType.PODS, pod.key())
                inc.apply(ADDED, bound)
                placed_priorities[get_pod_priority(bound)] += 1
                last_outcome = "bound"
                remaining = remaining[j + 1:]
                redispatch = bool(remaining)
                break
            fit_err = FitError(pod, len(cc.nodes), failed)
            node, victims = cc.attempt_preemption(pod, fit_err)
            if node is None:
                cc.update(pod, PodCondition(
                    type="PodScheduled", status="False",
                    reason="Unschedulable", message=fit_err.error()))
                last_outcome = "failed"
                continue
            for victim in victims:
                inc.apply(EV_DELETED, victim)
                placed_priorities[get_pod_priority(victim)] -= 1
            attempts[pod.key()] = attempts.get(pod.key(), 0) + 1
            rr_start += int(np.sum(advanced[:j]))
            # scheduleOne retries the nominated pod immediately
            # (simulator _schedule_one preempt_budget arm); every later
            # decision was computed against pre-preemption state
            remaining = remaining[j:]
            redispatch = True
            break
        if not redispatch:
            break

    cc.status.stop_reason = cc.STOP_REASONS[last_outcome]
    cc.close()
    return cc.status

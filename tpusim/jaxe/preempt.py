"""Preemption on the jax backend: a host-device hybrid.

Reference: the Preempt pipeline (core/generic_scheduler.go:205-1000) driven
from scheduleOne's error arm (scheduler.go:449-455). Victim selection is
inherently pod-identity-bound (remove lower-priority pods one by one, reprieve
in priority order, PDB-aware) — state the device deliberately does not carry
(the scan holds per-node aggregates + group presence, not per-pod rows). The
TPU-native split is therefore:

  device — the fused filter→score→select→bind scan schedules every pod that
           fits (tpusim/jaxe/kernels.py); a pod that fails leaves the carry
           untouched and does not advance the round-robin counter, so the
           decisions AFTER a failed pod stay valid.
  host   — only when a pod fails with the PodPriority gate on does the exact
           engine pipeline (GenericScheduler.preempt — the same code the
           reference backend runs) pick a node + victims against a host mirror
           of the cluster.

A successful preemption mutates state (victims deleted), which invalidates
the device's decisions for every later pod — so the scan must restart from
the failed pod. Restarts are made cheap two ways:

  1. **Chunked speculation.** The batch is compiled ONCE; the device scans
     adaptively growing power-of-two chunks (TPUSIM_PREEMPT_CHUNK0, doubling
     to TPUSIM_PREEMPT_CHUNK_MAX) instead of all remaining pods at once.
     Decisions after a preemption point are discarded, so a bounded chunk
     caps the wasted speculation at one chunk per preemption — previously a
     full O(remaining) re-scan (and an O(remaining) host recompile) per
     preemption made config-6-style saturated workloads quadratic. The
     chunk size resets after every preemption (preemptions cluster once the
     cluster saturates) and doubles while the stream stays clean, so
     preemption-free stretches approach single-dispatch throughput.
  2. **Dynamic-only re-arm.** The IncrementalCluster event path
     (tpusim/jaxe/delta.py) keeps columns in sync: binds stream in as ADDED
     events, victims as DELETED events. After a preemption the carry is
     rebuilt from `IncrementalCluster.refresh_dynamic` — a handful of array
     copies — and the compiled statics/tables/pod columns are reused as-is.
     Only structural churn (a victim or bound pod carrying volumes dirties
     the group tables) falls back to a full compile of the remaining feed.

Chunks are padded to power-of-two buckets with provably-infeasible rows
(req_cpu = 2^61 exceeds any allocatable), bounding XLA recompiles to
O(log chunk_max) per run; an infeasible row can never bind or advance the
rr counter, so padding is semantics-free.

A cheap host gate skips the preemption attempt entirely when no placed pod
has lower priority than the failed pod (selectVictimsOnNode can then never
produce a fitting node), so equal-priority workloads pay no host cost beyond
the mirror bookkeeping.
"""

from __future__ import annotations

import logging
import os
from collections import Counter
from typing import List

import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Pod, PodCondition, ResourceType
from tpusim.engine.generic_scheduler import (
    ERR_NO_NODES_AVAILABLE,
    FitError,
    SchedulingError,
)
from tpusim.engine.providers import DEFAULT_PROVIDER
from tpusim.engine.resources import get_resource_request, request_memo
from tpusim.engine.util import get_pod_priority
from tpusim.framework.report import Status
from tpusim.framework.store import ADDED
from tpusim.framework.store import DELETED as EV_DELETED
from tpusim.jaxe import ensure_x64
from tpusim.jaxe.backend import (
    _MOST_REQUESTED_PROVIDERS,
    _VICTIM_AUTO,
    format_fit_error,
    victim_kernel_enabled,
)
from tpusim.jaxe.delta import IncrementalCluster
from tpusim.jaxe.kernels import (
    PodX,
    carry_init,
    pad_infeasible_rows,
    config_for,
    pod_columns_to_host,
    preempt_select,
    schedule_scan,
    statics_to_device,
)
from tpusim.jaxe.policyc import classify_preemption_class
from tpusim.jaxe.state import NUM_FIXED_BITS, reason_strings, victim_order_columns
from tpusim.obs import recorder as flight

log = logging.getLogger(__name__)

# per-process counters for how each preemption's victim selection ran:
#   "device"          trusted kernel pick committed directly
#   "device_verified" kernel pick byte-checked against the full host oracle
#                     on its kernel variant's first use (host objects
#                     committed — AUTO mode can never change behavior)
#   "host"            host pipeline (general class, scalar/volume-gated pod,
#                     kernel disabled, or kernel declined the case)
#   "fallback"        kernel disagreed with the oracle: disabled for the
#                     process, host result used
# Read by tests and bench.py (stamped into the config-6 record); reset with
# reset_preempt_class_stats().
PREEMPT_CLASS_STATS: Counter = Counter()


def reset_preempt_class_stats() -> None:
    PREEMPT_CLASS_STATS.clear()


def _note_victim_path(path: str) -> None:
    """One preemption's victim-selection path: bumps the in-module Counter
    (read by tests/bench) and the tpusim_backend_victim_path_total metric
    family + recorder instant in one place."""
    PREEMPT_CLASS_STATS[path] += 1
    flight.note_victim_path(path)

def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class _PreemptBound:
    """Vectorized necessary-fit bound over [priority, node] request aggregates
    — the device-engine analog of victim selection's masked aggregate search.

    selectVictimsOnNode (core/generic_scheduler.go:583-665) strips every
    lower-priority pod from a candidate node and runs podFitsOnNode;
    PodFitsResources (predicates.go:706-776) is in that predicate set, so a
    node where the pod's request still exceeds allocatable after removing ALL
    lower-priority usage can never yield a fitting victim set. This tracker
    keeps per-priority-band per-node aggregates of the same
    get_resource_request accounting NodeInfo uses, and evaluates that bound
    for every node in one numpy pass, so the host pipeline only clones and
    reprieves on the handful of nodes that can actually fit the pod.

    The bound checks pod count + cpu/mem/gpu/ephemeral exactly as
    pod_fits_resources does (including the all-zero-request early-out) and
    deliberately ignores scalar resources and meta.ignored_extended_resources
    — omitted checks only make the bound more permissive, so a pruned node is
    PROVABLY unfit and the filtered pipeline's outcome is identical."""

    def __init__(self, compiled, placed_pods: List[Pod]):
        st = compiled.statics
        self._node_index = dict(compiled.node_index)
        n = len(st.names)
        self._alloc = (st.alloc_cpu.copy(), st.alloc_mem.copy(),
                       st.alloc_gpu.copy(), st.alloc_eph.copy())
        self._allowed = st.allowed_pods.copy()
        # priority -> [cpu, mem, gpu, eph, count] per-node arrays
        self._bands: dict = {}
        self._n = n
        for pod in placed_pods:
            if pod.spec.node_name:
                self.update(pod, +1)

    def update(self, pod: Pod, sign: int) -> None:
        i = self._node_index.get(pod.spec.node_name)
        if i is None:
            return
        prio = get_pod_priority(pod)
        band = self._bands.get(prio)
        if band is None:
            band = [np.zeros(self._n, np.int64) for _ in range(5)]
            self._bands[prio] = band
        req = get_resource_request(pod)
        band[0][i] += sign * req.milli_cpu
        band[1][i] += sign * req.memory
        band[2][i] += sign * req.nvidia_gpu
        band[3][i] += sign * req.ephemeral_storage
        band[4][i] += sign

    def candidates(self, pod: Pod):
        """Set of node names where the stripped-node resource bound passes,
        or None when every node passes (skip filtering)."""
        pp = get_pod_priority(pod)
        remain = [np.zeros(self._n, np.int64) for _ in range(5)]
        for prio, band in self._bands.items():
            if prio >= pp:   # only lower-priority pods are strippable
                for acc, col in zip(remain, band):
                    acc += col
        req = get_resource_request(pod)
        ok = remain[4] + 1 <= self._allowed
        if (req.milli_cpu or req.memory or req.nvidia_gpu
                or req.ephemeral_storage or req.scalar):
            for k, want in enumerate((req.milli_cpu, req.memory,
                                      req.nvidia_gpu, req.ephemeral_storage)):
                ok &= want + remain[k] <= self._alloc[k]
        if ok.all():
            return None
        names = self._node_index
        mask = ok
        return {name for name, i in names.items() if mask[i]}


class _VictimTable:
    """Columnar mirror of every placed pod, maintained alongside the host
    cache so victim selection can run on device (kernels.preempt_select).

    Row order is the parity anchor: rows are appended in placement-event
    order (snapshot seeds via state.victim_order_columns, then every bind),
    and removals only clear the alive bit — so the per-node subsequence of
    alive rows equals NodeInfo.pods (append on add, order-preserving `del`
    on remove), and a stable sort by descending priority reproduces
    sort_by_priority_desc's victim ordering exactly."""

    def __init__(self, compiled, placed_pods: List[Pod]):
        self._node_index = dict(compiled.node_index)
        n = len(compiled.statics.names)
        node_i, prio, req, objs = victim_order_columns(placed_pods,
                                                       self._node_index)
        self.size = len(objs)
        cap = max(256, _next_pow2(self.size + 1))
        self.node_i = np.zeros(cap, np.int32)
        self.node_i[:self.size] = node_i
        self.prio = np.zeros(cap, np.int64)
        self.prio[:self.size] = prio
        self.req = np.zeros((cap, 4), np.int64)   # cpu/mem/gpu/eph
        self.req[:self.size] = req
        self.alive = np.zeros(cap, bool)
        self.alive[:self.size] = True
        self.objs: List = list(objs) + [None] * (cap - self.size)
        self._row = {p.key(): i for i, p in enumerate(objs)}
        # per-node totals over alive rows — the un-stripped NodeInfo
        # requested/pod-count aggregates
        self.tot = np.zeros((n, 4), np.int64)
        np.add.at(self.tot, node_i, req)
        self.tot_n = np.bincount(node_i, minlength=n).astype(np.int64)

    def _grow(self) -> None:
        cap = len(self.alive) * 2
        for name in ("node_i", "prio", "alive"):
            old = getattr(self, name)
            new = np.zeros(cap, old.dtype)
            new[:self.size] = old[:self.size]
            setattr(self, name, new)
        new_req = np.zeros((cap, 4), np.int64)
        new_req[:self.size] = self.req[:self.size]
        self.req = new_req
        self.objs.extend([None] * (cap - len(self.objs)))

    def add(self, pod: Pod) -> None:
        i = self._node_index.get(pod.spec.node_name)
        if i is None:
            return
        if self.size == len(self.alive):
            self._grow()
        r = self.size
        self.size = r + 1
        pr = get_resource_request(pod)
        self.node_i[r] = i
        self.prio[r] = get_pod_priority(pod)
        self.req[r] = (pr.milli_cpu, pr.memory, pr.nvidia_gpu,
                       pr.ephemeral_storage)
        self.alive[r] = True
        self.objs[r] = pod
        self._row[pod.key()] = r
        self.tot[i] += self.req[r]
        self.tot_n[i] += 1

    def remove(self, pod: Pod) -> None:
        r = self._row.pop(pod.key(), None)
        if r is None:
            return
        self.alive[r] = False
        self.objs[r] = None
        i = self.node_i[r]
        self.tot[i] -= self.req[r]
        self.tot_n[i] -= 1


def _device_select_victims(vtable: _VictimTable, compiled, cols, row,
                           pod: Pod):
    """One failed pod through the device victim-selection pipeline:
    candidate lanes (static-predicate mask + stripped-node resource fit, the
    exact complement of _UNRESOLVABLE given the arithmetic class), victim
    slots (priority-desc lower-priority residents per lane), then the
    preempt_select kernel for the reprieve scan + pickOneNode reductions.

    Returns (winner_node_index, victims_in_reprieve_order, kernel_sig) on a
    device pick, or (None, why, None) when the host arm must run (no
    candidates, or the kernel's result contradicts the scan's infeasibility
    verdict)."""
    st, tb = compiled.statics, compiled.tables
    n_nodes = len(st.names)
    pp = get_pod_priority(pod)
    preq = get_resource_request(pod)
    zero_req = (preq.milli_cpu == 0 and preq.memory == 0
                and preq.nvidia_gpu == 0 and preq.ephemeral_storage == 0
                and not preq.scalar)

    # static-predicate mask == nodes whose only failure can be resources:
    # in the arithmetic class every registered predicate is either node-
    # static (condition/unschedulable bits, hostname pin, selector+required
    # affinity, taints, pressure) or PodFitsResources, and a static failure
    # is _UNRESOLVABLE while a resource failure is not — so this mask IS
    # nodesWherePreemptionMightHelp ∩ {stripped-chain statics pass}
    ok = ((st.cond_fail_bits == 0)
          & tb.host_ok[cols.host_id[row]]
          & tb.selector_ok[cols.sel_id[row]]
          & tb.taint_ok[cols.tol_id[row]]
          & ~st.disk_pressure)
    if cols.best_effort[row]:
        ok = ok & ~st.mem_pressure

    # strip every lower-priority pod, then podFitsOnNode's resource half on
    # the stripped node (the _fits_sans_nominated gate of selectVictims)
    size = vtable.size
    lower = vtable.alive[:size] & (vtable.prio[:size] < pp)
    vrows = np.nonzero(lower)[0]
    node_of = vtable.node_i[:size]
    lower_sum = np.zeros((n_nodes, 4), np.int64)
    np.add.at(lower_sum, node_of[vrows], vtable.req[:size][vrows])
    lower_n = np.bincount(node_of[vrows], minlength=n_nodes)
    n_base = vtable.tot_n - lower_n
    used_base = vtable.tot - lower_sum
    fit = ok & (n_base + 1 <= st.allowed_pods)
    if not zero_req:
        fit = (fit
               & (used_base[:, 0] + preq.milli_cpu <= st.alloc_cpu)
               & (used_base[:, 1] + preq.memory <= st.alloc_mem)
               & (used_base[:, 2] + preq.nvidia_gpu <= st.alloc_gpu)
               & (used_base[:, 3] + preq.ephemeral_storage <= st.alloc_eph))
    cand = np.nonzero(fit)[0]
    c_real = int(cand.size)
    if c_real == 0:
        return None, "no stripped-fit candidate nodes", None

    # victims on candidate lanes, stable-sorted by descending priority
    # (row order within equal priority = NodeInfo.pods order)
    rows = vrows[fit[node_of[vrows]]]
    if rows.size == 0:
        # a candidate with zero strippable pods fits as-is — the scan said
        # it doesn't; surface through the host disagreement arm
        return None, "candidate fits without victims (scan disagreement)", None
    rows = rows[np.argsort(-vtable.prio[:size][rows], kind="stable")]
    lane_of_node = np.full(n_nodes, -1, np.int64)
    lane_of_node[cand] = np.arange(c_real)
    lane = lane_of_node[node_of[rows]]
    g = np.argsort(lane, kind="stable")   # group by lane, keep prio order
    rows_g, lane_g = rows[g], lane[g]
    counts = np.bincount(lane_g, minlength=c_real)
    if int(counts.min()) == 0:
        return None, "candidate fits without victims (scan disagreement)", None
    starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
    pos_in = np.arange(rows_g.size) - starts[lane_g]
    v_real = int(counts.max())

    c_pad = _next_pow2(c_real)
    v_pad = _next_pow2(v_real)
    sig = (c_pad, v_pad, zero_req)

    def lane_arr(vals, base=0):
        out = np.full(c_pad, base, np.int64)
        out[:c_real] = vals
        return out

    lane_valid = np.zeros(c_pad, bool)
    lane_valid[:c_real] = True
    v_prio = np.zeros((c_pad, v_pad), np.int64)
    v_req = np.zeros((c_pad, v_pad, 4), np.int64)
    v_valid = np.zeros((c_pad, v_pad), bool)
    v_row = np.full((c_pad, v_pad), -1, np.int64)
    v_prio[lane_g, pos_in] = vtable.prio[:size][rows_g]
    v_req[lane_g, pos_in] = vtable.req[:size][rows_g]
    v_valid[lane_g, pos_in] = True
    v_row[lane_g, pos_in] = rows_g

    winner, empty_winner, victim_mask, _num = preempt_select(
        bool(zero_req), lane_valid, lane_arr(cand),
        lane_arr(st.alloc_cpu[cand]), lane_arr(st.alloc_mem[cand]),
        lane_arr(st.alloc_gpu[cand]), lane_arr(st.alloc_eph[cand]),
        lane_arr(st.allowed_pods[cand]),
        lane_arr(n_base[cand]),
        lane_arr(used_base[cand, 0] + preq.milli_cpu),
        lane_arr(used_base[cand, 1] + preq.memory),
        lane_arr(used_base[cand, 2] + preq.nvidia_gpu),
        lane_arr(used_base[cand, 3] + preq.ephemeral_storage),
        v_prio, v_req[:, :, 0], v_req[:, :, 1], v_req[:, :, 2],
        v_req[:, :, 3], v_valid)
    big = 1 << 62
    if int(empty_winner) < big:
        return None, "kernel found a no-victim candidate (scan disagreement)", None
    win = int(winner)
    if win >= big:
        # cannot happen for a lane that passed the stripped fit (the scan
        # terminates with a valid, possibly-empty victim set); treat any
        # occurrence as a disagreement and let the host arm decide
        return None, "kernel produced no winner", None
    lane_w = int(lane_of_node[win])
    mask = np.asarray(victim_mask)[lane_w]
    slot_rows = v_row[lane_w][mask & (v_row[lane_w] >= 0)]
    victims = [vtable.objs[int(r)] for r in slot_rows]
    return win, victims, sig


def _device_preempt(cc, vtable: _VictimTable, compiled, cols, row, pod: Pod,
                    bound, by_name, auto_mode: bool):
    """Run one preemption attempt's victim selection on device, with the
    AUTO-mode first-use verification against the full host oracle.

    Returns (status, payload):
      ("skip", why)                   kernel not applicable — run the host arm
      ("committed", (node, victims))  preemption committed through
                                      Simulator.commit_preemption
      ("nopreempt", message)          the verifying host oracle found no
                                      preemption; the None outcome was
                                      committed and `message` is the FitError
                                      text for the pod's condition
    """
    from time import perf_counter

    from tpusim.framework.metrics import since_in_microseconds

    metrics = cc.metrics
    start = perf_counter()
    win, payload, sig = _device_select_victims(vtable, compiled, cols, row,
                                               pod)
    if win is None:
        if "disagreement" in payload:
            log.error("device victim selection for pod %s: %s; deferring to "
                      "the host pipeline", pod.key(), payload)
        return "skip", payload
    name = compiled.statics.names[win]
    if auto_mode and sig not in _VICTIM_AUTO["verified_sigs"]:
        # first preemption on this kernel variant: run the FULL host
        # pipeline alongside and compare (node, ordered victim keys)
        node_infos = cc.refresh_node_info_snapshot()
        try:
            filtered, failed = cc.scheduler.find_nodes_that_fit(
                pod, cc.nodes, node_infos)
        except SchedulingError:
            return "skip", "host oracle errored during verification"
        if filtered:
            # scan-level disagreement — the host arm owns that safety net
            return "skip", "host found feasible nodes"
        fit_err = FitError(pod, len(cc.nodes), failed)
        cand = bound.candidates(pod) if bound is not None else None
        metrics.preemption_attempts.inc()
        host_node, host_victims, host_to_clear = cc.scheduler.preempt(
            pod, cc.nodes, node_infos, fit_err,
            candidate_filter=(cand.__contains__
                              if cand is not None else None))
        metrics.preemption_evaluation.observe(since_in_microseconds(start))
        agree = (host_node is not None and host_node.name == name
                 and [v.key() for v in host_victims]
                 == [v.key() for v in payload])
        if agree:
            _VICTIM_AUTO["verified_sigs"].add(sig)
            _note_victim_path("device_verified")
            log.info("preempt-victim kernel verified against the host "
                     "oracle (variant %s); trusting it for this process",
                     sig)
        else:
            _VICTIM_AUTO["disabled"] = True
            _note_victim_path("fallback")
            log.error(
                "preempt-victim kernel DISAGREES with the host oracle for "
                "pod %s (device: %s + %d victims; host: %s + %d victims); "
                "disabling it for this process and using the host result",
                pod.key(), name, len(payload),
                host_node.name if host_node is not None else None,
                len(host_victims))
        node, victims = cc.commit_preemption(pod, host_node, host_victims,
                                             host_to_clear)
        if node is None:
            return "nopreempt", fit_err.error()
        return "committed", (node, victims)
    # trusted: commit the kernel's pick through the same side-effect path
    # the host pipeline uses (store deletes, nominations, events)
    metrics.preemption_attempts.inc()
    to_clear = cc.scheduler._get_lower_priority_nominated_pods(pod, name)
    metrics.preemption_evaluation.observe(since_in_microseconds(start))
    _note_victim_path("device")
    node, victims = cc.commit_preemption(pod, by_name[name], payload,
                                         to_clear)
    return "committed", (node, victims)


def _mesh_place(mesh, carry, statics=None):
    """Place the hybrid's scan state on a ("snap", "node") mesh: node columns
    sharded over "node", pad rows permanently infeasible (sharding.py sentinel
    bit). statics=None is the post-preemption re-arm — a fresh carry padded to
    match the already-placed statics."""
    from tpusim.jaxe.sharding import (
        node_shardings,
        pad_carry_node_axis,
        pad_node_axis,
        stage_tree,
    )

    st_spec, ca_spec = node_shardings(mesh)
    if statics is None:
        carry = pad_carry_node_axis(carry, mesh.shape["node"])
    else:
        statics, carry, _ = pad_node_axis(statics, carry, mesh.shape["node"])
        statics = stage_tree(statics, st_spec)
    return statics, stage_tree(carry, ca_spec)


def run_with_preemption(pods: List[Pod], snapshot: ClusterSnapshot,
                        provider: str = DEFAULT_PROVIDER,
                        hard_pod_affinity_symmetric_weight: int = 10,
                        incremental: IncrementalCluster = None,
                        mesh=None) -> Status:
    """Run `pods` (podspec order; the LIFO feed reversal happens here, like
    the reference's store.go:223-233 queue) with the PodPriority gate on.
    Returns the final Status with successful/failed/preempted buckets matching
    the reference backend's ClusterCapacity run.

    incremental: an IncrementalCluster already equivalent to `snapshot` (e.g.
    from an event-log replay) — reused instead of compiling a fresh one.

    mesh: an optional ("snap", "node") jax.sharding.Mesh (sharding.make_mesh);
    the speculation chunks dispatch with node columns sharded over the "node"
    axis (pod rows replicated), and the carry re-arm after every preemption
    lands back on the mesh. Placements must stay byte-identical to the
    single-device hybrid — host arms (victim selection, binds, report) never
    see the mesh. Forces the XLA scan (the Pallas plan is single-device)."""
    # deferred import: simulator imports this module's sibling lazily too
    from tpusim.simulator import ClusterCapacity, SchedulerServerConfig

    def host_config():
        return SchedulerServerConfig(
            algorithm_provider=provider,
            hard_pod_affinity_symmetric_weight=hard_pod_affinity_symmetric_weight,
            enable_pod_priority=True)

    # the host mirror: the same orchestrator the reference backend runs, fed
    # manually — binds via the Bind seam, failures via the Update seam, and
    # preemption via the shared attempt_preemption arm
    cc = ClusterCapacity(host_config(), new_pods=[],
                         scheduled_pods=snapshot.pods, nodes=snapshot.nodes,
                         services=snapshot.services, pvs=snapshot.pvs,
                         pvcs=snapshot.pvcs,
                         storage_classes=snapshot.storage_classes)
    feed = list(reversed(pods))
    if not feed:
        cc.status.stop_reason = cc.STOP_REASONS["run"]
        cc.close()
        return cc.status
    if not snapshot.nodes:
        # generic_scheduler raises ERR_NO_NODES_AVAILABLE — the plain
        # SchedulingError arm, which never enters the preemption pipeline
        for pod in feed:
            cc.resource_store.add(ResourceType.PODS, pod)
            cc.update(pod, PodCondition(
                type="PodScheduled", status="False", reason="Unschedulable",
                message=str(ERR_NO_NODES_AVAILABLE)))
        cc.status.stop_reason = cc.STOP_REASONS["failed"]
        cc.close()
        return cc.status

    inc = incremental if incremental is not None else IncrementalCluster(snapshot)
    by_name = {n.name: n for n in cc.nodes}
    vtable = None         # _VictimTable, built at first compile
    run_class = None      # victim-selection class, logged once per run
    # priority histogram of placed pods — the preemption-possible gate
    placed_priorities: Counter = Counter(
        get_pod_priority(p) for p in snapshot.pods if p.spec.node_name)
    attempts: dict = {}   # pod key -> preemption attempts (budget 1, like
    #                       _schedule_one's preempt_budget)
    last_outcome = "run"
    metrics = cc.metrics
    first_compile = True
    rr_start = 0          # lastNodeIndex persists across the whole run
    #                       (generic_scheduler.go:97); restarts resume it
    pos = 0               # next unprocessed pod in `feed`

    chunk0 = max(1, int(os.environ.get("TPUSIM_PREEMPT_CHUNK0", "128")))
    chunk_max = max(chunk0,
                    int(os.environ.get("TPUSIM_PREEMPT_CHUNK_MAX", "8192")))

    # Pallas fast path for the speculation chunks (staged round-5 design):
    # the same kernel the plain batch path runs, driven with explicit
    # carry-in/out over pow2 buckets; after a preemption the carry re-arms
    # from refresh_dynamic's original-unit aggregates divided by the plan's
    # gcds (exact — placed pods' requests joined the gcd fold). Host arms
    # (victim selection, binds, report) are untouched: placements stay
    # byte-identical to the XLA hybrid, pinned by the differential suites.
    from tpusim.jaxe.backend import (
        _FAST_AUTO,
        _auto_verify_and_pin,
        _fast_path_enabled,
        _note_fast_failure,
        plan_signature,
    )
    from tpusim.jaxe.fastscan import fast_scan, init_carry, plan_fast, rearm_carry

    # placed-pod values for the gcd fold: initial snapshot placements plus
    # every pod bound during the run (appended at both bind arms below — a
    # superset is safe: the gcd over a superset still divides every victim
    # adjustment, so victims are never removed from this list either)
    placed_for_gcd = [p for p in snapshot.pods if p.spec.node_name]

    from time import perf_counter

    from tpusim.framework.metrics import since_in_microseconds

    import jax.numpy as jnp

    # pod specs are immutable for the duration of the run (only status and
    # node_name change), so request recomputation — hot in victim selection's
    # clone/strip/reprieve churn — is memoized for the whole hybrid loop
    with request_memo():
        while pos < len(feed):
            # (re)compile feed[pos:] against the current picture; reached once up
            # front and again only after structural churn (volume-carrying binds
            # or victims dirty the group tables — refresh_dynamic covers the rest)
            compile_start = perf_counter()
            with flight.span("compile_cluster") as csp:
                compiled, cols = inc.compile(feed[pos:])
                if csp:
                    csp.set("pods", len(feed) - pos)
            metrics.backend_compile_latency.observe(
                since_in_microseconds(compile_start))
            if compiled.unsupported:
                if not first_compile:
                    raise RuntimeError(
                        "jax preemption: compile fallback after binds were made "
                        f"({sorted(set(compiled.unsupported))[:3]})")
                log.warning("jax backend (preemption) falling back to reference "
                            "for: %s", "; ".join(sorted(set(compiled.unsupported))[:5]))
                ref = ClusterCapacity(host_config(), new_pods=pods,
                                      scheduled_pods=snapshot.pods,
                                      nodes=snapshot.nodes,
                                      services=snapshot.services, pvs=snapshot.pvs,
                                      pvcs=snapshot.pvcs,
                                      storage_classes=snapshot.storage_classes)
                ref.run()
                return ref.status
            if first_compile:
                # the bound only prunes nodes the resource-fit check would
                # reject; shipped providers carry it via GeneralPredicates
                # (which subsumes PodFitsResources, predicates.go:1059-1123),
                # policies may register PodFitsResources directly — a set
                # with neither skips pruning to stay outcome-identical
                preds = cc.scheduler.predicates
                bound = (_PreemptBound(compiled, snapshot.pods)
                         if "GeneralPredicates" in preds
                         or "PodFitsResources" in preds else None)
                vtable = _VictimTable(compiled, snapshot.pods)
            first_compile = False

            num_bits = NUM_FIXED_BITS + len(compiled.scalar_names)
            config = config_for(
                [compiled],
                most_requested=provider in _MOST_REQUESTED_PROVIDERS,
                num_reason_bits=num_bits,
                hard_weight=hard_pod_affinity_symmetric_weight)
            ensure_x64()
            # workload feature hints for the arithmetic reprieve fast path
            # (generic_scheduler._make_arithmetic_reprieve): compiled flags
            # cover new AND placed pods, so an absent feature's reprieve
            # predicate is constant-true for the whole run
            cc.scheduler.reprieve_feature_hints = {
                "has_ports": config.has_ports,
                "has_disk_conflict": config.has_disk_conflict,
                "has_maxpd": config.has_maxpd,
                "has_interpod": config.has_interpod,
            }
            # victim-selection class for this compile: the key/flag
            # classification (shared with policy compilation), cross-checked
            # against the scheduler's own reprieve-chain seam, plus the live
            # PDB gate (criterion 2 is only a no-op with no PDBs registered)
            vclass, vclass_why = classify_preemption_class(
                frozenset(cc.scheduler.predicates),
                cc.scheduler.reprieve_feature_hints,
                has_extenders=bool(cc.scheduler.extenders))
            if vclass == "arithmetic" and cc.scheduler.pdb_lister():
                vclass, vclass_why = ("general",
                                      "pod disruption budgets registered")
            if (vclass == "arithmetic"
                    and cc.scheduler.preemption_reprieve_class()
                    != "arithmetic"):
                vclass, vclass_why = ("general", "reprieve chain kept a "
                                      "pod-set-dependent predicate")
            if run_class != vclass:
                log.info("preemption victim-selection class: %s%s", vclass,
                         f" ({vclass_why})" if vclass_why else "")
                run_class = vclass
            strings = reason_strings(compiled.scalar_names)
            names = compiled.statics.names
            base = pos            # plan/column row i holds feed[base + i]

            # fast-path decision BEFORE the statics upload (same rule as
            # backend.schedule): when the kernel engages, the XLA-scan
            # inputs are never materialized
            fplan = fcarry = fsig = None
            fverify = False
            fast_on, auto_mode = _fast_path_enabled()
            if mesh is not None:
                fast_on = False  # Pallas plan is single-device; mesh -> XLA
            if fast_on:
                fplan, why = plan_fast(config, compiled, cols,
                                       placed_pods=placed_for_gcd)
                if fplan is None:
                    log.info("preemption fast path ineligible (%s); using "
                             "the XLA scan", why)
                else:
                    fsig = plan_signature(fplan)
                    if (auto_mode
                            and fsig not in _FAST_AUTO["verified_sigs"]
                            and not (pos == 0 and rr_start == 0)):
                        # verification replays from carry_init (rr=0): an
                        # unverified variant can only earn trust on the
                        # run's very first chunk — later compiles of an
                        # untrusted variant stay on the XLA scan
                        log.info("preemption fast path deferred: kernel "
                                 "variant unverified and the run is past "
                                 "its first chunk")
                        fplan = fsig = None
                    else:
                        fcarry = init_carry(fplan, rr=rr_start)
                        fverify = (auto_mode and fsig
                                   not in _FAST_AUTO["verified_sigs"])
            statics = xs_all = carry = None
            if fplan is None:
                statics = statics_to_device(compiled)
                xs_all = pod_columns_to_host(cols)
                carry = carry_init(compiled)._replace(rr=np.int64(rr_start))
                if mesh is not None:
                    statics, carry = _mesh_place(mesh, carry, statics)
            flight.note_route("fastscan" if fplan is not None else "xla_scan",
                              len(feed) - pos)
            chunk = chunk0

            while pos < len(feed):
                take = min(chunk, len(feed) - pos)
                off = pos - base
                dispatch_start = perf_counter()
                dsp = flight.span("device_dispatch", "device")
                # pow2 buckets bound recompiles to O(log chunk_max) on both
                # engines: arbitrary tail lengths after a preemption would
                # otherwise each trace a fresh program (infeasible pad rows
                # never bind or advance rr)
                bucket = _next_pow2(take)
                if fplan is not None:
                    try:
                        with flight.profiled("tpusim:fast_scan"):
                            choices, counts, advanced, fc_out = fast_scan(
                                fplan, chunk=bucket, start=off,
                                stop=off + take, carry_in=fcarry,
                                return_carry=True, fixed_chunk=True)
                    except Exception as exc:
                        # degrade without crashing mid-device-context; the
                        # outer loop recompiles feed[pos:] and re-decides
                        # the engine (disabled after compile/lowering or
                        # repeated transient failures)
                        log.warning("preemption fast path failed (%s: %s); "
                                    "re-running on the XLA scan",
                                    type(exc).__name__, exc)
                        _note_fast_failure(exc)
                        if dsp:
                            dsp.set("error", type(exc).__name__)
                            dsp.end()
                        break
                    _FAST_AUTO["transient"] = 0
                    if fverify:
                        # ONCE, on the run's first chunk only (the plan
                        # gate guarantees pos==0, rr_start==0 there):
                        # verify_against_xla replays the LEADING pods from
                        # carry_init, which matches no later chunk's
                        # chained-carry state — comparing those would be
                        # pods-vs-different-pods
                        fverify = False
                        if not _auto_verify_and_pin(
                                config, compiled, cols, choices, counts,
                                fsig, limit=take):
                            if dsp:
                                dsp.end()
                            break
                    carry_out = fc_out
                else:
                    sl = PodX(*(a[off:off + take] for a in xs_all))
                    sl = pad_infeasible_rows(sl, bucket - take)
                    xs = PodX(*(jnp.asarray(a) for a in sl))
                    if mesh is not None:
                        import jax
                        from jax.sharding import NamedSharding, PartitionSpec
                        rep = NamedSharding(mesh, PartitionSpec())
                        xs = jax.tree.map(
                            lambda a: jax.device_put(a, rep), xs)
                        with mesh, flight.profiled("tpusim:schedule_scan"):
                            carry_out, choices, counts, advanced = \
                                schedule_scan(config, carry, statics, xs)
                    else:
                        with flight.profiled("tpusim:schedule_scan"):
                            carry_out, choices, counts, advanced = \
                                schedule_scan(config, carry, statics, xs)
                choices = np.asarray(choices)[:take]
                counts = np.asarray(counts)[:take]
                advanced = np.asarray(advanced)[:take]
                if dsp:
                    dsp.set("engine", "fastscan" if fplan is not None
                            else "xla_scan")
                    dsp.set("chunk", bucket)
                    dsp.set("take", take)
                    dsp.end()
                metrics.backend_dispatch_latency.observe(
                    since_in_microseconds(dispatch_start))
                metrics.scheduling_algorithm_latency.observe(
                    since_in_microseconds(dispatch_start))

                mutated = False
                for j in range(take):
                    pod = feed[pos + j]
                    cc.resource_store.add(ResourceType.PODS, pod)  # nextPod's add
                    c = int(choices[j])
                    if c >= 0:
                        cc.bind(pod, names[c])
                        placed, _ = cc.resource_store.get(ResourceType.PODS,
                                                          pod.key())
                        inc.apply(ADDED, placed)
                        placed_for_gcd.append(placed)
                        placed_priorities[get_pod_priority(placed)] += 1
                        if bound is not None:
                            bound.update(placed, +1)
                        vtable.add(placed)
                        last_outcome = "bound"
                        continue

                    # failure: the scan left the carry untouched, so later
                    # decisions stay valid unless a preemption below mutates state
                    pod_priority = get_pod_priority(pod)
                    can_preempt = (
                        cc.config.enable_pod_priority
                        and attempts.get(pod.key(), 0) < 1
                        and any(count > 0 and pri < pod_priority
                                for pri, count in placed_priorities.items()))
                    if not can_preempt:
                        cc.update(pod, PodCondition(
                            type="PodScheduled", status="False",
                            reason="Unschedulable",
                            message=format_fit_error(len(names), counts[j],
                                                     strings)))
                        last_outcome = "failed"
                        continue

                    rr_here = rr_start + int(np.sum(advanced[:j]))
                    # device arm: the arithmetic-reprieve class runs victim
                    # selection on device (kernels.preempt_select) — the pod
                    # is additionally gated on no scalar requests (victim
                    # scalar columns are not carried) and no volumes (keeps
                    # the candidate mask == nodesWherePreemptionMightHelp)
                    dev_status = None
                    dev_payload = None
                    if vclass == "arithmetic":
                        vk_on, vk_auto = victim_kernel_enabled()
                        preq_pod = get_resource_request(pod)
                        if (vk_on and not preq_pod.scalar
                                and not pod.spec.volumes):
                            dev_status, dev_payload = _device_preempt(
                                cc, vtable, compiled, cols, off + j, pod,
                                bound, by_name, vk_auto)
                            if dev_status == "skip":
                                dev_status = None
                    if dev_status == "committed":
                        node, victims = dev_payload
                    elif dev_status == "nopreempt":
                        cc.update(pod, PodCondition(
                            type="PodScheduled", status="False",
                            reason="Unschedulable", message=dev_payload))
                        last_outcome = "failed"
                        continue
                    else:
                        # host arm: per-node failure reasons (the device ships
                        # only the aggregate histogram), then the exact Preempt
                        # pipeline — both against the cache's generation-checked
                        # snapshot, like the host engine's g.cachedNodeInfoMap
                        node_infos = cc.refresh_node_info_snapshot()
                        try:
                            filtered, failed = cc.scheduler.find_nodes_that_fit(
                                pod, cc.nodes, node_infos)
                        except SchedulingError as exc:
                            cc.update(pod, PodCondition(
                                type="PodScheduled", status="False",
                                reason="Unschedulable", message=str(exc)))
                            last_outcome = "failed"
                            continue
                        if filtered:
                            # device said infeasible, host disagrees — a parity
                            # bug; keep the run coherent by trusting the host
                            log.error("device/host disagreement for pod %s: host "
                                      "found %d feasible nodes; using host placement",
                                      pod.key(), len(filtered))
                            cc.scheduler.last_node_index = rr_here
                            host = cc.scheduler.schedule(pod, cc.nodes, node_infos)
                            rr_start = cc.scheduler.last_node_index
                            cc.bind(pod, host)
                            placed, _ = cc.resource_store.get(ResourceType.PODS,
                                                              pod.key())
                            inc.apply(ADDED, placed)
                            placed_for_gcd.append(placed)
                            placed_priorities[get_pod_priority(placed)] += 1
                            if bound is not None:
                                bound.update(placed, +1)
                            vtable.add(placed)
                            last_outcome = "bound"
                            pos += j + 1
                            mutated = True
                            break
                        fit_err = FitError(pod, len(cc.nodes), failed)
                        cand = (bound.candidates(pod)
                                if bound is not None else None)
                        _note_victim_path("host")
                        node, victims = cc.attempt_preemption(
                            pod, fit_err,
                            candidate_filter=(cand.__contains__
                                              if cand is not None else None))
                        if node is None:
                            cc.update(pod, PodCondition(
                                type="PodScheduled", status="False",
                                reason="Unschedulable", message=fit_err.error()))
                            last_outcome = "failed"
                            continue
                    for victim in victims:
                        inc.apply(EV_DELETED, victim)
                        placed_priorities[get_pod_priority(victim)] -= 1
                        if bound is not None:
                            bound.update(victim, -1)
                        vtable.remove(victim)
                    attempts[pod.key()] = attempts.get(pod.key(), 0) + 1
                    # scheduleOne retries the nominated pod immediately
                    # (simulator _schedule_one preempt_budget arm); every later
                    # decision was computed against pre-preemption state
                    pos += j
                    rr_start = rr_here
                    mutated = True
                    break

                if not mutated:
                    pos += take
                    if fplan is not None:
                        fcarry = carry_out
                    else:
                        carry = carry_out
                    rr_start += int(np.sum(advanced))
                    chunk = min(chunk * 2, chunk_max)
                    continue
                if pos >= len(feed):
                    break
                # state changed: re-arm the carry from the incremental picture;
                # statics/tables/pod columns are reused when the group structure
                # is clean, else fall out to a full recompile of feed[pos:]
                refreshed = inc.refresh_dynamic(compiled)
                if refreshed is None:
                    break
                compiled = refreshed
                if fplan is not None:
                    # original-unit aggregates -> plan units via the stored
                    # gcds (exact by the placed-pod fold; verified anyway)
                    fcarry = rearm_carry(fplan, compiled, rr_start)
                    if fcarry is None:
                        log.info("preemption fast path: refreshed state "
                                 "not expressible in plan units; "
                                 "recompiling")
                        break
                else:
                    carry = carry_init(compiled)._replace(
                        rr=np.int64(rr_start))
                    if mesh is not None:
                        _, carry = _mesh_place(mesh, carry)
                chunk = chunk0

    if PREEMPT_CLASS_STATS:
        log.info("preemption victim-selection paths (process cumulative): %s",
                 dict(PREEMPT_CLASS_STATS))
    cc.status.stop_reason = cc.STOP_REASONS[last_outcome]
    cc.close()
    return cc.status

"""The JAX/TPU backend: columnar cluster state + batched scheduling kernels.

Design (SURVEY.md §7, BASELINE.json north star): the reference's per-pod
Filter/Score loop over a 16-worker goroutine fan-out becomes

  1. a host-side COMPILE step — pods' symbolic features (node selectors,
     tolerations, node-affinity terms, controller refs, hostname pins) are
     interned into signature classes and evaluated against the STATIC node
     attributes (labels, taints, conditions — immutable during a simulation)
     into dense [signature, node] tables, using the parity engine's own
     matching functions so semantics match by construction. This subsumes the
     reference's equivalence cache (core/equivalence_cache.go): instead of
     memoizing per-pod predicate results behind an equivalence hash, every
     class×node result is materialized once, up front, vectorized.

  2. a DEVICE scan — `lax.scan` over the pod axis carrying only numeric
     aggregates (requested/nonzero resources, pod counts, the round-robin
     counter). Each step fuses predicate masks + reason codes, priority
     scores, weighted sum, tie-break selection, and the bind scatter-add into
     one compiled program. Exact integer semantics via int64 (x64 mode).

Integer/float precision: scores use int64 (Go int); BalancedResourceAllocation
uses float64 exactly like Go. Memory quantities are byte-exact int64.
"""

import logging as _logging
import os

import jax

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    # Honor an env-level CPU-only pin before ANY backend init. Axon-style TPU
    # plugins force-append themselves over JAX_PLATFORMS, so the env var alone
    # does not stop jax.devices() from initializing (and blocking on) the TPU
    # tunnel; the config knob set pre-init does. Exact-match only: a priority
    # list like "tpu,cpu" means "prefer the accelerator" and must pass
    # through untouched. No-op if backends are already up (a host app that
    # imported jax first keeps its own platform choice).
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as exc:
        _logging.getLogger(__name__).warning(
            "could not honor JAX_PLATFORMS=cpu via jax config: %s", exc)

_cache_dir = os.environ.get("TPUSIM_COMPILE_CACHE", "")
if _cache_dir:
    # Persistent XLA compilation cache (opt-in): the what-if path compiles a
    # fresh vmap(snapshots)×scan(pods) program per shape (~2min at the
    # BASELINE.json config-5 shape) — cache it on disk so every later process
    # pays a cache hit instead. Keyed by HLO + compile options, so a shape
    # change recompiles naturally.
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as exc:
        _logging.getLogger(__name__).warning(
            "TPUSIM_COMPILE_CACHE=%s requested but the persistent compile "
            "cache could not be enabled: %s", _cache_dir, exc)

_probe_checked = False


def ensure_responsive_platform(timeout: float = 0.0) -> None:
    """Probe the accelerator in a SUBPROCESS before the first in-process
    device op; pin jax to CPU when it does not answer.

    The axon TPU tunnel can wedge such that the first device op blocks
    forever with the GIL held (BASELINE.md round-2..4 postmortems) — an
    interactive CLI must degrade to the host platform instead of hanging.
    Skipped when: TPUSIM_PROBE=0, an explicit platform pin is active
    (JAX_PLATFORMS=cpu / --platform / tests' conftest), or a probe passed
    within the last 10 minutes (stamp file — repeat CLI invocations on a
    healthy tunnel pay the ~13s probe once)."""
    global _probe_checked
    if _probe_checked or os.environ.get("TPUSIM_PROBE") == "0":
        return
    _probe_checked = True
    try:
        from jax._src import xla_bridge as _xb

        if _xb._backends:
            # already initialized: the init-hang this guard exists for is
            # behind us, re-pinning platforms would be a no-op, and a probe
            # SUBPROCESS would open a second concurrent tunnel client —
            # itself a suspected wedge trigger (BASELINE.md round-4)
            return
    except Exception:  # pragma: no cover - private-API drift
        pass
    try:
        plats = str(jax.config.jax_platforms or "").split(",")
        if plats[0].strip().lower() == "cpu":
            # the FIRST entry wins platform selection: "cpu" / "cpu,axon"
            # never touches the tunnel, but "axon,cpu" (what the axon
            # plugin force-installs) absolutely does
            return
    except AttributeError:  # pragma: no cover - very old jax
        pass
    import subprocess
    import sys
    import tempfile
    import time

    # per-uid names: on a shared host another user's stale stamp would be
    # unreadable/unwritable and must not affect (or crash) this process
    uid = getattr(os, "getuid", lambda: 0)()
    stamp = os.path.join(tempfile.gettempdir(), f"tpusim_probe_ok.{uid}")
    stamp_bad = os.path.join(tempfile.gettempdir(), f"tpusim_probe_bad.{uid}")
    log = _logging.getLogger(__name__)

    def _pin_cpu(why: str) -> None:
        log.warning(
            "%s; running on the CPU backend (set TPUSIM_PROBE=0 or "
            "--platform to override)", why)
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception as exc:  # backends already initialized
            log.warning("could not pin jax to cpu: %s", exc)

    try:
        if time.time() - os.path.getmtime(stamp) < 600:
            return
    except OSError:
        pass
    try:
        # a recent failed probe: don't make every process re-pay the full
        # probe timeout against a tunnel known to be wedged
        if time.time() - os.path.getmtime(stamp_bad) < 120:
            _pin_cpu("accelerator probe failed <120s ago (wedged tunnel?)")
            return
    except OSError:
        pass
    if not timeout:
        timeout = float(os.environ.get("TPUSIM_PROBE_TIMEOUT", "40"))
    def _touch(path: str) -> None:
        # stamp upkeep must never fail the probe verdict or the caller
        try:
            with open(path, "w"):
                pass
        except OSError:
            pass

    try:
        subprocess.run(
            [sys.executable, "-c",
             "import jax\nimport jax.numpy as jnp\n"
             "assert int(jnp.ones((8, 8)).sum()) == 64"],
            timeout=timeout, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    except Exception:
        _touch(stamp_bad)
        _pin_cpu(f"accelerator probe did not answer within {timeout:.0f}s "
                 "(wedged tunnel?)")
    else:
        _touch(stamp)
        try:
            os.remove(stamp_bad)
        except OSError:
            pass


_x64_enabled = False


def ensure_x64() -> None:
    """Enable 64-bit JAX types (process-global) before building device state.

    Go semantics are 64-bit; placement parity requires byte-exact memory sums
    and int64 score arithmetic. On TPU, int64 is emulated 32-bit-pairwise — the
    fast path can later narrow where ranges allow. Called explicitly from the
    backend entry points instead of at import so that importing tpusim never
    flips global JAX config for a host application.
    """
    global _x64_enabled
    if not _x64_enabled:
        jax.config.update("jax_enable_x64", True)
        _x64_enabled = True

"""The JAX/TPU backend: columnar cluster state + batched scheduling kernels.

Design (SURVEY.md §7, BASELINE.json north star): the reference's per-pod
Filter/Score loop over a 16-worker goroutine fan-out becomes

  1. a host-side COMPILE step — pods' symbolic features (node selectors,
     tolerations, node-affinity terms, controller refs, hostname pins) are
     interned into signature classes and evaluated against the STATIC node
     attributes (labels, taints, conditions — immutable during a simulation)
     into dense [signature, node] tables, using the parity engine's own
     matching functions so semantics match by construction. This subsumes the
     reference's equivalence cache (core/equivalence_cache.go): instead of
     memoizing per-pod predicate results behind an equivalence hash, every
     class×node result is materialized once, up front, vectorized.

  2. a DEVICE scan — `lax.scan` over the pod axis carrying only numeric
     aggregates (requested/nonzero resources, pod counts, the round-robin
     counter). Each step fuses predicate masks + reason codes, priority
     scores, weighted sum, tie-break selection, and the bind scatter-add into
     one compiled program. Exact integer semantics via int64 (x64 mode).

Integer/float precision: scores use int64 (Go int); BalancedResourceAllocation
uses float64 exactly like Go. Memory quantities are byte-exact int64.
"""

import logging as _logging
import os

import jax

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    # Honor an env-level CPU-only pin before ANY backend init. Axon-style TPU
    # plugins force-append themselves over JAX_PLATFORMS, so the env var alone
    # does not stop jax.devices() from initializing (and blocking on) the TPU
    # tunnel; the config knob set pre-init does. Exact-match only: a priority
    # list like "tpu,cpu" means "prefer the accelerator" and must pass
    # through untouched. No-op if backends are already up (a host app that
    # imported jax first keeps its own platform choice).
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as exc:
        _logging.getLogger(__name__).warning(
            "could not honor JAX_PLATFORMS=cpu via jax config: %s", exc)

_cache_dir = os.environ.get("TPUSIM_COMPILE_CACHE", "")
if _cache_dir:
    # Persistent XLA compilation cache (opt-in): the what-if path compiles a
    # fresh vmap(snapshots)×scan(pods) program per shape (~2min at the
    # BASELINE.json config-5 shape) — cache it on disk so every later process
    # pays a cache hit instead. Keyed by HLO + compile options, so a shape
    # change recompiles naturally.
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as exc:
        _logging.getLogger(__name__).warning(
            "TPUSIM_COMPILE_CACHE=%s requested but the persistent compile "
            "cache could not be enabled: %s", _cache_dir, exc)

_x64_enabled = False


def ensure_x64() -> None:
    """Enable 64-bit JAX types (process-global) before building device state.

    Go semantics are 64-bit; placement parity requires byte-exact memory sums
    and int64 score arithmetic. On TPU, int64 is emulated 32-bit-pairwise — the
    fast path can later narrow where ranges allow. Called explicitly from the
    backend entry points instead of at import so that importing tpusim never
    flips global JAX config for a host application.
    """
    global _x64_enabled
    if not _x64_enabled:
        jax.config.update("jax_enable_x64", True)
        _x64_enabled = True

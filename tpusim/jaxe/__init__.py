"""The JAX/TPU backend: columnar cluster state + batched scheduling kernels.

Design (SURVEY.md §7, BASELINE.json north star): the reference's per-pod
Filter/Score loop over a 16-worker goroutine fan-out becomes

  1. a host-side COMPILE step — pods' symbolic features (node selectors,
     tolerations, node-affinity terms, controller refs, hostname pins) are
     interned into signature classes and evaluated against the STATIC node
     attributes (labels, taints, conditions — immutable during a simulation)
     into dense [signature, node] tables, using the parity engine's own
     matching functions so semantics match by construction. This subsumes the
     reference's equivalence cache (core/equivalence_cache.go): instead of
     memoizing per-pod predicate results behind an equivalence hash, every
     class×node result is materialized once, up front, vectorized.

  2. a DEVICE scan — `lax.scan` over the pod axis carrying only numeric
     aggregates (requested/nonzero resources, pod counts, the round-robin
     counter). Each step fuses predicate masks + reason codes, priority
     scores, weighted sum, tie-break selection, and the bind scatter-add into
     one compiled program. Exact integer semantics via int64 (x64 mode).

Integer/float precision: scores use int64 (Go int); BalancedResourceAllocation
uses float64 exactly like Go. Memory quantities are byte-exact int64.
"""

import jax

_x64_enabled = False


def ensure_x64() -> None:
    """Enable 64-bit JAX types (process-global) before building device state.

    Go semantics are 64-bit; placement parity requires byte-exact memory sums
    and int64 score arithmetic. On TPU, int64 is emulated 32-bit-pairwise — the
    fast path can later narrow where ranges allow. Called explicitly from the
    backend entry points instead of at import so that importing tpusim never
    flips global JAX config for a host application.
    """
    global _x64_enabled
    if not _x64_enabled:
        jax.config.update("jax_enable_x64", True)
        _x64_enabled = True

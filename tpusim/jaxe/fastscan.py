"""Pallas TPU fast path for the exact sequential scan.

The production `schedule_scan` is a `lax.scan` whose per-pod step is one XLA
while-loop iteration; on TPU each iteration pays loop/dispatch overhead that
dwarfs the [N]-wide arithmetic (measured ~94us/pod at 10k nodes vs ~5us of
compute). This module runs the same step as a single Pallas kernel with a
grid over the pod axis: the carry ([N]-sized node state) lives in VMEM
output blocks that persist across grid steps, pod scalars and pregathered
signature-table rows stream in via the grid pipeline, and each step is pure
VPU work — no per-pod dispatch.

Semantics are IDENTICAL to the XLA path for eligible workloads (differential
tests drive both); ineligible workloads fall back to `schedule_scan`.

Eligibility (checked by `plan_fast`, reasons returned):
  * pod-group features run natively through a [Gpad, Npad] presence carry
    (round 4): host ports, NoDiskConflict, services/SelectorSpreadPriority
    (incl. the zone blend), and NoVolumeZoneConflict — per-pod group rows
    stream through SMEM and all group state is accessed via statically
    -unrolled loops over Gpad with (g == gid)-masked row ops (no dynamic
    indexing; Mosaic-safe). Bounded by TPUSIM_FAST_MAX_GROUPS (32) merged
    groups / TPUSIM_FAST_MAX_ZONES (16) zone domains, and the spread
    blend's int32 product bound;
  * inter-pod (anti)affinity runs natively (round 5): own terms via per
    -pod match rows + D scalar segment reductions over the presence carry,
    the existing-pods side via a [Gpad*K, Dpad] presence_dom carry with
    per-(group, term) constants baked into the kernel variant, and
    InterPodAffinityPriority in exact int32 — bounded by
    TPUSIM_FAST_MAX_TOPO_KEYS (4), _MAX_TOPO_DOMS (64), _MAX_TERMS (4)
    and an int32 weight-mass bound;
  * MaxPD volume counts run natively (round 5): the [N, V] used-volume
    union as a [Vpad, Npad] bit carry with baked type triples/limits,
    bounded by TPUSIM_FAST_MAX_VOLS (32);
  * POLICIES compile into the kernel in full (rounds 5-6): the PolicySpec
    (predicate subset incl. individually-named GeneralPredicates parts,
    priority weights, per-type MaxPD enables, hard weight) is baked into
    the kernel variant like the interpod constants, and the round-6
    residue classes all run natively — label-presence predicate rows and
    the NoExecute-only taint table as static mask stages at their
    ordering slots, NodeLabel/LabelPreference priorities as a pre
    -weighted score row, ImageLocality through the signature-table
    streaming path, ServiceAntiAffinity via per-pod first-service rows
    over the presence carry, ServiceAffinity predicates via pin/value
    label rows plus first-matching-pod lock slots riding the misc carry
    lanes (bounded by TPUSIM_FAST_MAX_SA_SEGS, default 16), and
    alwaysCheckAllPredicates count-mode by keeping every stage's failure
    bits live through the full chain. Only extenders stay host-bound
    (they call out to HTTP processes — no device analog);
  * every resource quantity reduces exactly to int32: values are divided by
    the per-axis gcd (exact — fractions and fit comparisons are
    unit-invariant) and the reduced values must stay under 2^29, with the
    BalancedResourceAllocation product bound 10*max_cpu*max_mem < 2^31
    (Mosaic has no 64-bit integers, so the kernel is int32 throughout;
    DEVIATIONS.md #16's exactness contract is preserved because the reduced
    arithmetic never overflows);
  * scalar (extended) resources ARE eligible: each scalar axis gcd-reduces
    independently like cpu/mem (PodFitsResources treats every scalar as one
    more fit column, predicates.go:706-776), and its failure bit rides at
    NUM_FIXED_BITS+s — at most PAD_SENTINEL_BIT-NUM_FIXED_BITS (=6) scalar
    kinds fit the int32 reason word; more falls back to the XLA scan.

Reference mapping (same as kernels._evaluate for this subset):
  CheckNodeCondition/Unschedulable -> cond_fail_bits stage
  GeneralPredicates (resources, hostname, selector+affinity) -> stage 2
    (predicates.go:1059-1123, :659-776, :780-865)
  PodToleratesNodeTaints (predicates.go:1465-1493) -> stage 3
  CheckNodeMemory/DiskPressure (predicates.go:1502-1541) -> stages 4-5
  Least/MostRequested, BalancedResourceAllocation, NodeAffinity,
  TaintToleration normalizes, NodePreferAvoidPods -> int32 score sum
  selectHost round-robin tie-break (generic_scheduler.go:183-198) -> masked
    argmax + rank-k tie pick carried through the VMEM rr cell
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from functools import lru_cache
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports fail on some non-TPU builds; interpret mode needs none
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
    _SMEM = pltpu.SMEM
except Exception:  # pragma: no cover - exercised only on exotic builds
    pltpu = None
    _VMEM = _SMEM = None

from tpusim.engine.predicates import (
    CHECK_NODE_DISK_PRESSURE_PRED,
    CHECK_NODE_LABEL_PRESENCE_PRED,
    CHECK_NODE_MEMORY_PRESSURE_PRED,
    CHECK_NODE_UNSCHEDULABLE_PRED,
    CHECK_SERVICE_AFFINITY_PRED,
    CHECK_VOLUME_BINDING_PRED,
    GENERAL_PRED,
    HOSTNAME_PRED,
    MATCH_INTERPOD_AFFINITY_PRED,
    MATCH_NODE_SELECTOR_PRED,
    MAX_AZURE_DISK_VOLUME_COUNT_PRED,
    MAX_EBS_VOLUME_COUNT_PRED,
    MAX_GCE_PD_VOLUME_COUNT_PRED,
    NO_DISK_CONFLICT_PRED,
    NO_VOLUME_ZONE_CONFLICT_PRED,
    POD_FITS_HOST_PORTS_PRED,
    POD_FITS_RESOURCES_PRED,
    POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
    POD_TOLERATES_NODE_TAINTS_PRED,
)
from tpusim.jaxe.state import NUM_FIXED_BITS, CompiledCluster, PodColumns
from tpusim.jaxe.kernels import (
    AVOID_PODS_WEIGHT,
    MAX_PRIORITY,
    EngineConfig,
)
from tpusim.jaxe.state import (
    BIT_AFFINITY_NOT_MATCH,
    BIT_MAX_VOLUME_COUNT,
    BIT_AFFINITY_RULES,
    BIT_ANTI_AFFINITY_RULES,
    BIT_DISK_CONFLICT,
    BIT_DISK_PRESSURE,
    BIT_EXISTING_ANTI_AFFINITY,
    BIT_HOSTNAME_MISMATCH,
    BIT_HOST_PORTS,
    BIT_INSUFFICIENT_CPU,
    BIT_INSUFFICIENT_EPHEMERAL,
    BIT_INSUFFICIENT_GPU,
    BIT_INSUFFICIENT_MEMORY,
    BIT_INSUFFICIENT_PODS,
    BIT_MEMORY_PRESSURE,
    BIT_NODE_LABEL_PRESENCE,
    BIT_NODE_SELECTOR_MISMATCH,
    BIT_NODE_UNSCHEDULABLE,
    BIT_SERVICE_AFFINITY,
    BIT_TAINTS_NOT_TOLERATED,
    BIT_VOLUME_ZONE_CONFLICT,
)

INT_LIMIT = 1 << 29          # per-value bound after gcd reduction
GHOST_REQ = 1 << 30          # > any reduced allocatable: never feasible
PAD_SENTINEL_BIT = 30        # cond bit for padded nodes; >= last scalar bit
LANES = 128
SUBLANES = 8                 # scalar-axis row padding (TPU sublane tile)


@dataclass
class FastPlan:
    """int32 device-ready arrays; node axis padded to a multiple of 128."""

    num_nodes: int           # real nodes (pad rows follow)
    num_pods: int
    most_requested: bool
    num_scalars: int         # scalar-resource kinds (0 = no scalar args)
    # statics [1, Npad]
    alloc_cpu: np.ndarray
    alloc_mem: np.ndarray
    alloc_gpu: np.ndarray
    alloc_eph: np.ndarray
    allowed: np.ndarray
    cond_bits: np.ndarray
    mem_pressure: np.ndarray
    disk_pressure: np.ndarray
    # signature tables [S, Npad]
    selector_ok: np.ndarray
    taint_ok: np.ndarray
    intolerable: np.ndarray
    aff_count: np.ndarray
    avoid_score: np.ndarray
    host_ok: np.ndarray
    # initial carry [1, Npad]
    used_cpu: np.ndarray
    used_mem: np.ndarray
    used_gpu: np.ndarray
    used_eph: np.ndarray
    nonzero_cpu: np.ndarray
    nonzero_mem: np.ndarray
    pod_count: np.ndarray
    # pod columns [P]
    req_cpu: np.ndarray
    req_mem: np.ndarray
    req_gpu: np.ndarray
    req_eph: np.ndarray
    nz_cpu: np.ndarray
    nz_mem: np.ndarray
    zero_request: np.ndarray
    best_effort: np.ndarray
    sel_id: np.ndarray
    tol_id: np.ndarray
    aff_id: np.ndarray
    avoid_id: np.ndarray
    host_id: np.ndarray
    # scalar resources (present when num_scalars > 0)
    alloc_scalar: Optional[np.ndarray] = None   # [Srows, Npad]
    used_scalar: Optional[np.ndarray] = None    # [Srows, Npad] init carry
    req_scalar: Optional[np.ndarray] = None     # [P, S]; chunks pad to LANES
    # pod-group features (num_groups == 0 -> group-free kernel). Presence is
    # a [Gpad, Npad] int32 carry; per-pod group rows are SMEM-streamed
    # scalars read in a statically-unrolled loop over Gpad (no dynamic
    # indexing — Mosaic-safe), and the bind updates presence via
    # (g == gid) masked whole-row adds.
    num_groups: int = 0          # Gpad (sublane-padded merged group count)
    has_ports: bool = False
    has_disk: bool = False
    has_spread: bool = False
    has_vol_zone: bool = False
    presence: Optional[np.ndarray] = None    # [Gpad, Npad] int32 init carry
    gid: Optional[np.ndarray] = None         # [P] int32 merged group id
    port_row: Optional[np.ndarray] = None    # [P, Gpad] int32 0/1 conflicts
    disk_row: Optional[np.ndarray] = None    # [P, Gpad] int32 0/1 conflicts
    ss_row: Optional[np.ndarray] = None      # [P, Gpad] int32 0/1 spread set
    zone_ok_tbl: Optional[np.ndarray] = None  # [G, Npad] int32 0/1 by gid
    zone_onehot: Optional[np.ndarray] = None  # [Zpad, Npad] int32; row 0 =
    #                                           the unlabeled dom-0 sentinel
    n_zone_doms: int = 0         # Zpad (sublane-padded)
    # per-axis gcds the int32 reduction divided by — the preemption hybrid
    # re-arms its carry from refreshed ORIGINAL-unit aggregates by dividing
    # through these (exact when every placed pod's request was folded into
    # the gcd via plan_fast's placed_pods; rearm_carry verifies anyway)
    gcds: Tuple[int, int, int, int] = (1, 1, 1, 1)   # cpu, mem, gpu, eph
    scalar_gcds: Tuple[int, ...] = ()
    # statically-gateable policy (round 5): the PolicySpec itself (hashable)
    # is baked into the kernel variant — stage gating + score weights
    policy: Optional[object] = None
    # inter-pod (anti)affinity (round 5). Own required/preferred terms run
    # through per-pod match rows + domain segment sums recomputed from the
    # presence carry (dc_at == broadcast-back of the per-domain sums of
    # mcount — identical to the XLA path's _seg_rows + take_along_axis);
    # the existing-pods side (their anti-affinity / preferred terms vs ME)
    # rides a [Gpad*K, Dpad] presence_dom carry with the per-(group, term)
    # keys, weights, and validity masks baked into the kernel as static
    # constants, so per-pod operands reduce to pure match bits.
    has_interpod: bool = False
    n_topo_keys: int = 0           # K (rows of topo_dom; Gpad*K presence_dom rows)
    n_topo_doms_ip: int = 0        # REAL domain count incl. the invalid-0
    #                                bucket (the unroll bound; presence_dom's
    #                                lane axis is padded to 128 separately)
    ta: int = 0                    # own required-affinity term slots
    tb: int = 0                    # own required-anti-affinity term slots
    tp: int = 0                    # own preferred term slots
    hard_weight: int = 10
    topo_rows: Optional[np.ndarray] = None       # [Kpad8, Npad] int32 dom ids
    presence_dom: Optional[np.ndarray] = None    # [Gpad*K, Dpad] int32 init
    ipod: Optional[np.ndarray] = None            # [P, Wip] per-pod packed row
    # static exist-side tables (baked into the kernel; part of its cache key)
    exist_anti_key: Tuple[int, ...] = ()     # [G*Tb] topo-key per term
    exist_anti_mask: Tuple[int, ...] = ()    # [G*Tb] valid & ~empty
    exist_anti_empty: Tuple[int, ...] = ()   # [G*Tb] valid & empty (fail_all)
    exist_pref_key: Tuple[int, ...] = ()     # [G*Tp]
    exist_pref_w: Tuple[int, ...] = ()       # [G*Tp] signed int weights
    exist_aff_key: Tuple[int, ...] = ()      # [G*Ta]
    exist_aff_mask: Tuple[int, ...] = ()     # [G*Ta] valid & ~empty
    # Max{EBS,GCEPD,AzureDisk}VolumeCount (round 5): the [N, V] per-node
    # used-volume union becomes a [Vpad8, Npad] 0/1 carry; per-pod volume
    # masks ride a [Gpad?, Vpad] group table gathered by group id (maxpd
    # needs no presence), and the per-volume type triples + per-type
    # limits are baked into the kernel variant.
    has_maxpd: bool = False
    maxpd_enabled: Tuple[bool, bool, bool] = (True, True, True)
    n_vols: int = 0                          # V real volume ids
    used_vols: Optional[np.ndarray] = None   # [Vpad8, Npad] init carry
    vol_tbl: Optional[np.ndarray] = None     # [G, Vpad] mask by group id
    vol_type3: Tuple[int, ...] = ()          # [V*3] type bits (EBS,GCE,AZ)
    maxpd_limits: Tuple[int, int, int] = (0, 0, 0)
    # full policy residue (round 6): label-presence mask rows, the pre
    # -weighted NodeLabel/LabelPreference priority row, the ImageLocality
    # signature table, the NoExecute-only taint table, ServiceAntiAffinity
    # label domains, and the ServiceAffinity pin/value/lock tables. All
    # int32, node axis padded to Npad; ServiceAffinity locks ride the misc
    # carry lanes 1..Fd (first-matching-pod node index, -1 unlocked, -2
    # permanently unpinned).
    label_tbl: Optional[np.ndarray] = None      # [Lpad8, Npad] 0/1 pass
    label_prio_row: Optional[np.ndarray] = None  # [1, Npad] pre-weighted
    image_tbl: Optional[np.ndarray] = None      # [Si, Npad] by img_id
    img_id: Optional[np.ndarray] = None         # [P] int32
    noexec_tbl: Optional[np.ndarray] = None     # [Ctol, Npad] by tol_id
    saa_row: Optional[np.ndarray] = None        # [P, Gpad] first-service row
    saa_dom_tbl: Optional[np.ndarray] = None    # [Epad8, Npad] label doms
    n_saa_doms: int = 0                         # unroll bound (incl. dom 0)
    sa_sig: Optional[np.ndarray] = None         # [P] first-service sig id
    sa_pin_row: Optional[np.ndarray] = None     # [P, La8] own selector pins
    sa_match_row: Optional[np.ndarray] = None   # [P, Fd8] bind match bits
    sa_val_tbl: Optional[np.ndarray] = None     # [Lapad8, Npad] label values
    sa_lock_init: Optional[np.ndarray] = None   # [Fd] int32 lock seeds
    sa_la: int = 0                              # real concatenated SA labels


@dataclass
class FastCarry:
    """Device/host carry state threaded through fast_scan calls: the seven
    [1, Npad] node rows, the rr misc row, and the optional scalar / group
    -presence rows. Arrays may be numpy (fresh/re-armed) or jax device
    arrays (chained from a previous call's carry_out)."""

    rows: list               # [used_c, used_m, used_g, used_e, nz_c, nz_m, pc]
    misc: object             # [1, LANES] int32; rr at [0, 0]
    scal: Optional[object] = None    # [Srows, Npad] int32
    pres: Optional[object] = None    # [Gpad, Npad] int32
    pd: Optional[object] = None      # [Gpad*K, Dpad] int32 (interpod)
    uv: Optional[object] = None      # [Vpad8, Npad] 0/1 int32 (maxpd)


def init_carry(plan: FastPlan, rr: int = 0) -> FastCarry:
    """The carry at the plan's initial cluster state."""
    misc = np.zeros((1, LANES), dtype=np.int32)
    misc[0, 0] = rr
    if plan.sa_lock_init is not None:
        # ServiceAffinity first-matching-pod locks ride misc lanes 1..Fd
        misc[0, 1:1 + len(plan.sa_lock_init)] = plan.sa_lock_init
    return FastCarry(
        rows=[plan.used_cpu, plan.used_mem, plan.used_gpu, plan.used_eph,
              plan.nonzero_cpu, plan.nonzero_mem, plan.pod_count],
        misc=misc,
        scal=plan.used_scalar if plan.num_scalars else None,
        pres=plan.presence if plan.num_groups else None,
        pd=plan.presence_dom if plan.has_interpod else None,
        uv=plan.used_vols if plan.has_maxpd else None)


def rearm_carry(plan: FastPlan, compiled, rr: int) -> Optional[FastCarry]:
    """Rebuild the carry from a refreshed CompiledCluster's ORIGINAL-unit
    dynamic aggregates (IncrementalCluster.refresh_dynamic after preemption
    churn: binds streamed in as ADDED, victims as DELETED). Every value must
    divide exactly by the plan's per-axis gcd and stay inside the int32
    budget — guaranteed when plan_fast folded all placed pods' requests into
    the gcds, verified here regardless. Returns None when the refreshed
    state can't be expressed in plan units (caller re-plans or falls back).
    """
    if plan.sa_lock_init is not None:
        # ServiceAffinity locks are pod-assignment history the refreshed
        # cluster tables cannot reproduce; SA policies never reach the
        # preemption hybrid (policyc forces preemption_class "general"),
        # so this is a defensive fallback, not a hot path
        return None
    d = compiled.dynamic
    n = plan.num_nodes
    npad = plan.alloc_cpu.shape[1]

    def reduce_row(agg, g):
        a = np.asarray(agg, dtype=np.int64)
        if g > 1:
            if (a % g).any():
                return None
            a = a // g
        if a.size and int(a.max(initial=0)) >= INT_LIMIT:
            return None
        out = np.zeros((1, npad), dtype=np.int32)
        out[0, :n] = a.astype(np.int32)
        return out

    gc, gm, gg, ge = plan.gcds
    rows = [reduce_row(d.used_cpu, gc), reduce_row(d.used_mem, gm),
            reduce_row(d.used_gpu, gg), reduce_row(d.used_eph, ge),
            reduce_row(d.nonzero_cpu, gc), reduce_row(d.nonzero_mem, gm),
            reduce_row(d.pod_count, 1)]
    if any(r is None for r in rows):
        return None
    scal = None
    if plan.num_scalars:
        srows = plan.used_scalar.shape[0]
        scal = np.zeros((srows, npad), dtype=np.int32)
        us = np.asarray(d.used_scalar, dtype=np.int64)
        for si, g in enumerate(plan.scalar_gcds):
            col = us[:, si]
            if g > 1:
                if (col % g).any():
                    return None
                col = col // g
            if col.size and int(col.max(initial=0)) >= INT_LIMIT:
                return None
            scal[si, :n] = col.astype(np.int32)
    pres = pd = None
    if plan.num_groups:
        gt = compiled.groups
        if gt.presence.shape[0] > plan.num_groups:
            return None  # group universe grew: the plan's rows are stale
        pres = np.zeros((plan.num_groups, npad), dtype=np.int32)
        pres[:gt.presence.shape[0], :n] = gt.presence.astype(np.int32)
        if plan.has_interpod:
            if gt.topo_dom.shape[0] != plan.n_topo_keys:
                return None  # topology-key universe changed
            pd = embed_presence_dom(gt.presence, gt.topo_dom,
                                    plan.n_topo_doms_ip, plan.num_groups,
                                    plan.presence_dom.shape[1])
    uv = None
    if plan.has_maxpd:
        gt = compiled.groups
        if gt.vol_mask.shape[1] != plan.n_vols:
            return None  # volume-id universe changed
        # valid because refresh_dynamic only succeeds with CLEAN group
        # tables: a volume-carrying bind or victim dirties them and forces
        # the full recompile path instead
        uv = np.zeros_like(plan.used_vols)
        uv[:plan.n_vols, :plan.num_nodes] = \
            gt.used_vols_init.T.astype(np.int32)
    misc = np.zeros((1, LANES), dtype=np.int32)
    misc[0, 0] = rr
    return FastCarry(rows=rows, misc=misc, scal=scal, pres=pres, pd=pd,
                     uv=uv)


class IpLayout:
    """Static offsets into the per-pod packed interpod row (int32 lanes).

    Own-term data (my group's required affinity / anti-affinity / preferred
    terms): match bits vs every group, topo-key ids, and flag bits. Exist
    -side data (other groups' terms evaluated against ME): pure match bits
    — their keys, weights, and validity masks are compile-time constants
    baked into the kernel."""

    def __init__(self, ta: int, tb: int, tp: int, gpad: int):
        off = 0

        def take(n):
            nonlocal off
            at = off
            off += n
            return at

        self.aff_match = take(ta * gpad)    # [t*gpad+g]
        self.aff_key = take(ta)
        self.aff_valid = take(ta)
        self.aff_empty = take(ta)
        self.aff_host = take(ta)
        self.aff_self = take(ta)
        self.aff_unpl = take(ta)
        self.aff_err = take(1)
        self.anti_match = take(tb * gpad)
        self.anti_key = take(tb)
        self.anti_valid = take(tb)
        self.anti_host = take(tb)
        self.anti_err = take(1)
        self.pref_match = take(tp * gpad)
        self.pref_key = take(tp)
        self.pref_w = take(tp)              # signed int weights
        self.ex_anti = take(gpad * tb)      # [g*tb+t] term matches ME
        self.ex_pref = take(gpad * tp)
        self.ex_aff = take(gpad * ta)
        self.width = max(-(-off // LANES) * LANES, LANES)


def _gcd_reduce(arrays) -> Tuple[int, list]:
    """gcd over every value in `arrays`; returns (g, arrays // g)."""
    g = 0
    for a in arrays:
        for v in np.unique(np.asarray(a, dtype=np.int64)):
            g = math.gcd(g, int(v))
    if g <= 1:
        return max(g, 1), [np.asarray(a, dtype=np.int64) for a in arrays]
    return g, [np.asarray(a, dtype=np.int64) // g for a in arrays]


def embed_presence_dom(presence, topo_dom, d_doms: int, gpad: int,
                      dpad: int) -> np.ndarray:
    """[G, K, D] presence_dom -> the kernel's [Gpad*K, Dpad] row-interleaved
    carry layout (row g*K + k): ONE definition shared by plan_fast and
    rearm_carry so the embedding can never diverge between the initial
    plan and a post-preemption re-arm."""
    from tpusim.jaxe.kernels import _presence_dom_init

    pd3 = _presence_dom_init(presence, topo_dom, d_doms)
    g, k_keys, _ = pd3.shape
    out = np.zeros((gpad * k_keys, dpad), dtype=np.int32)
    out[:g * k_keys, :d_doms] = pd3.reshape(g * k_keys, d_doms)
    return out


def placed_pod_values(placed_pods, scalar_names) -> dict:
    """Per-pod request values of already-placed pods, by axis — folded into
    plan_fast's gcds so a preemption victim's deletion keeps every refreshed
    aggregate an exact multiple of the reduction unit (the staged round-5
    design: victim-adjusted sums then divide exactly)."""
    from tpusim.engine.resources import (
        get_nonzero_pod_request,
        get_resource_request,
    )

    vals = {"cpu": [], "mem": [], "gpu": [], "eph": [],
            "scalar": [[] for _ in scalar_names]}
    idx = {name: i for i, name in enumerate(scalar_names)}
    for pod in placed_pods:
        req = get_resource_request(pod)
        nz = get_nonzero_pod_request(pod)
        vals["cpu"] += [req.milli_cpu, nz.milli_cpu]
        vals["mem"] += [req.memory, nz.memory]
        vals["gpu"].append(req.nvidia_gpu)
        vals["eph"].append(req.ephemeral_storage)
        for name, v in (req.scalar or {}).items():
            if name in idx:
                vals["scalar"][idx[name]].append(v)
    return {"cpu": np.asarray(vals["cpu"], dtype=np.int64),
            "mem": np.asarray(vals["mem"], dtype=np.int64),
            "gpu": np.asarray(vals["gpu"], dtype=np.int64),
            "eph": np.asarray(vals["eph"], dtype=np.int64),
            "scalar": [np.asarray(col, dtype=np.int64)
                       for col in vals["scalar"]]}


def plan_fast(config: EngineConfig, compiled: CompiledCluster,
              cols: PodColumns, placed_pods=None, ptabs=None
              ) -> Tuple[Optional[FastPlan], str]:
    """Build the int32 plan, or (None, reason) when ineligible.

    placed_pods: pods already bound in the snapshot (preemption callers) —
    their per-pod request/nonzero values join the gcd reduction so victim
    deletions keep refreshed aggregates expressible in plan units.

    ptabs: policyc.PolicyTables with the host-built residue-class arrays
    (label rows, label priorities, image scores, ServiceAntiAffinity
    domains, ServiceAffinity pins/values/locks). Required whenever the
    policy uses any residue class; callers without them (the preemption
    hybrid compiles policy-free configs) simply stay on the XLA scan."""
    ps = config.policy
    pol_label = ps is not None and bool(ps.label_rows)
    pol_prio = ps is not None and ps.has_label_prio
    pol_image = ps is not None and bool(ps.w_image)
    pol_saa = ps is not None and bool(ps.saa_weights)
    pol_sa = ps is not None and (ps.sa_enabled or bool(ps.sa_slots))
    pol_noexec = (ps is not None and ps.pred_keys is not None
                  and POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED
                  in ps.pred_keys)
    pol_any = (pol_label or pol_prio or pol_image or pol_saa or pol_sa
               or pol_noexec)
    if pol_any:
        # every residue class compiles into the kernel (round 6) — the
        # remaining rejections are table availability and unroll budgets,
        # never the feature itself
        if ptabs is None:
            return None, ("policy static tables unavailable (caller did "
                          "not supply them)")
        if pol_noexec and not compiled.has_noexec_table:
            return None, "NoExecute taint table not compiled"
        if (pol_sa or pol_saa) and not compiled.has_saa_table:
            return None, "ServiceAffinity signature tables not compiled"
        if pol_sa:
            fd_real = int(compiled.groups.saa_rows.shape[0])
            la_real = int(sum(ps.sa_segs))
            max_sa = int(os.environ.get("TPUSIM_FAST_MAX_SA_SEGS", 16))
            # lock slots ride misc carry lanes 1..Fd (lane 0 is rr)
            if fd_real > min(max_sa, LANES - 1):
                return None, (f"{fd_real} ServiceAffinity lock segments "
                              f"exceed the fast-path budget "
                              f"({min(max_sa, LANES - 1)}; "
                              "TPUSIM_FAST_MAX_SA_SEGS)")
            if la_real > max_sa:
                return None, (f"{la_real} ServiceAffinity entry labels "
                              f"exceed the fast-path budget ({max_sa}; "
                              "TPUSIM_FAST_MAX_SA_SEGS)")
        if pol_saa:
            max_sz = int(os.environ.get("TPUSIM_FAST_MAX_ZONES", 16))
            if config.n_saa_doms > max_sz:
                return None, (f"{config.n_saa_doms} ServiceAntiAffinity "
                              f"label domains exceed the fast-path budget "
                              f"({max_sz}; TPUSIM_FAST_MAX_ZONES)")
    # maxpd carries a [N, V] per-node volume-id union — beyond the kernel's
    # presence model; every other pod-group feature (ports, disk conflicts,
    # spreading, volume zones, and — round 5 — inter-pod (anti)affinity)
    # runs via the [Gpad, Npad] presence carry (+ the [Gpad*K, Dpad]
    # presence_dom carry for interpod's existing-pods side) when the group
    # count fits the unrolled-loop budget
    if config.has_maxpd:
        n_vols_real = int(compiled.groups.vol_mask.shape[1])
        max_v = int(os.environ.get("TPUSIM_FAST_MAX_VOLS", 32))
        if n_vols_real > max_v:
            return None, (f"{n_vols_real} MaxPD volume ids exceed the "
                          f"fast-path budget ({max_v}; "
                          "TPUSIM_FAST_MAX_VOLS)")
    gt = compiled.groups
    group_bound = (config.has_ports or config.has_services
                   or config.has_disk_conflict or config.has_vol_zone
                   or config.has_interpod or config.has_maxpd or pol_saa)
    # presence is only read by ports/disk/spread/interpod/SAA; a vol-zone
    # -only workload streams per-pod zone rows (gathered by group id from an
    # HBM table) and needs neither the presence carry nor the unrolled
    # budget. SAA reads presence but (on service-less clusters) must not
    # force the bind to UPDATE it — the kernel's presence write mirrors the
    # XLA gate (ports|services|disk|interpod) separately.
    needs_presence = (config.has_ports or config.has_services
                      or config.has_disk_conflict or config.has_interpod
                      or pol_saa)
    num_g = int(gt.presence.shape[0]) if group_bound else 0
    if needs_presence:
        max_g = int(os.environ.get("TPUSIM_FAST_MAX_GROUPS", 32))
        if num_g > max_g:
            return None, (f"{num_g} pod groups exceed the fast-path "
                          f"unrolled-loop budget ({max_g}; "
                          "TPUSIM_FAST_MAX_GROUPS)")
        if config.has_services:
            max_z = int(os.environ.get("TPUSIM_FAST_MAX_ZONES", 16))
            if config.n_zone_doms > max_z:
                return None, (f"{config.n_zone_doms} zone domains exceed "
                              f"the fast-path budget ({max_z})")
    ip_dims = None
    if config.has_interpod:
        k_keys = int(gt.topo_dom.shape[0])
        d_doms = int(config.n_topo_doms)
        ta = int(gt.aff_valid.shape[1])
        tb = int(gt.anti_valid.shape[1])
        tp = int(gt.pref_w.shape[1])
        max_k = int(os.environ.get("TPUSIM_FAST_MAX_TOPO_KEYS", 4))
        max_d = int(os.environ.get("TPUSIM_FAST_MAX_TOPO_DOMS", 64))
        max_t = int(os.environ.get("TPUSIM_FAST_MAX_TERMS", 4))
        if k_keys > max_k:
            return None, (f"{k_keys} topology keys exceed the fast-path "
                          f"budget ({max_k}; TPUSIM_FAST_MAX_TOPO_KEYS)")
        if d_doms > max_d:
            return None, (f"{d_doms} topology domains exceed the fast-path "
                          f"budget ({max_d}; TPUSIM_FAST_MAX_TOPO_DOMS)")
        if max(ta, tb, tp) > max_t:
            return None, (f"{max(ta, tb, tp)} inter-pod terms exceed the "
                          f"fast-path budget ({max_t}; "
                          "TPUSIM_FAST_MAX_TERMS)")
        if not np.all(gt.pref_w == np.round(gt.pref_w)):
            return None, "non-integral preferred inter-pod weights"
        # InterPodAffinityPriority counts stay int32: bound |counts| by the
        # total weight mass times the largest possible pod population
        total_pods = int(gt.presence.sum()) + len(np.asarray(cols.req_cpu))
        w_own = int(np.abs(gt.pref_w).sum(axis=1).max(initial=0))
        w_exist = int(np.abs(gt.pref_w).sum()) + config.hard_weight * int(
            (gt.aff_valid & ~gt.aff_empty).sum())
        bound_counts = (w_own + w_exist) * max(total_pods, 1)
        w_ip_eff = 1 if ps is None else max(ps.w_interpod, 1)
        if MAX_PRIORITY * 2 * w_ip_eff * bound_counts >= (1 << 31):
            return None, ("inter-pod priority counts exceed int32 "
                          f"(weight mass {w_own + w_exist} x "
                          f"{total_pods} pods)")
        ip_dims = (k_keys, d_doms, ta, tb, tp)
    n_scal = len(compiled.scalar_names)
    if NUM_FIXED_BITS + n_scal > PAD_SENTINEL_BIT:
        return None, (f"{n_scal} scalar resource kinds exceed the int32 "
                      f"reason-bit budget "
                      f"({PAD_SENTINEL_BIT - NUM_FIXED_BITS})")
    s, t, d = compiled.statics, compiled.tables, compiled.dynamic

    placed = (placed_pod_values(placed_pods, compiled.scalar_names)
              if placed_pods else None)

    def axis(key):
        # extra per-placed-pod values join the gcd but are discarded after
        # (only the gcd itself matters for them)
        return [placed[key]] if placed is not None else []

    g_cpu, (ac, rc, nzc, uc, nzuc, *_) = _gcd_reduce(
        [s.alloc_cpu, cols.req_cpu, cols.nz_cpu, d.used_cpu, d.nonzero_cpu]
        + axis("cpu"))
    g_mem, (am, rm, nzm, um, nzum, *_) = _gcd_reduce(
        [s.alloc_mem, cols.req_mem, cols.nz_mem, d.used_mem, d.nonzero_mem]
        + axis("mem"))
    g_gpu, (ag, rg, ug, *_) = _gcd_reduce(
        [s.alloc_gpu, cols.req_gpu, d.used_gpu] + axis("gpu"))
    g_eph, (ae, re_, ue, *_) = _gcd_reduce(
        [s.alloc_eph, cols.req_eph, d.used_eph] + axis("eph"))
    # each scalar axis reduces independently (fit comparisons never mix axes)
    scal_cols = []
    scal_gcds = []
    if n_scal:
        ascal = np.asarray(s.alloc_scalar, dtype=np.int64).reshape(-1, n_scal)
        rscal = np.asarray(cols.req_scalar, dtype=np.int64).reshape(-1, n_scal)
        uscal = np.asarray(d.used_scalar, dtype=np.int64).reshape(-1, n_scal)
        for si in range(n_scal):
            extra = [placed["scalar"][si]] if placed is not None else []
            g_s, (a_s, r_s, u_s, *_) = _gcd_reduce(
                [ascal[:, si], rscal[:, si], uscal[:, si]] + extra)
            scal_cols.append((a_s, r_s, u_s))
            scal_gcds.append(g_s)

    checks = [("cpu", (ac, rc, nzc, uc, nzuc)),
              ("memory", (am, rm, nzm, um, nzum)),
              ("gpu", (ag, rg, ug)), ("ephemeral", (ae, re_, ue))]
    checks += [(compiled.scalar_names[si], scal_cols[si])
               for si in range(n_scal)]
    for name, arrs in checks:
        for a in arrs:
            if a.size and int(a.max(initial=0)) >= INT_LIMIT:
                return None, f"{name} values exceed int32 after gcd reduction"
    # BalancedResourceAllocation products must fit int32 including the
    # nonzero totals (which can exceed allocatable; bounded by allowed_pods
    # extra defaulted requests per node)
    allowed_max = int(np.max(s.allowed_pods, initial=0))
    bound_c = int(ac.max(initial=0)) + allowed_max * int(
        max(nzc.max(initial=0), nzuc.max(initial=0), 0))
    bound_m = int(am.max(initial=0)) + allowed_max * int(
        max(nzm.max(initial=0), nzum.max(initial=0), 0))
    if 10 * bound_c * bound_m >= (1 << 31):
        return None, "balanced-allocation product exceeds int32"
    if ps is not None:
        # the weighted sum of 0..MAX_PRIORITY components must stay int32
        # (each component is bounded by MAX_PRIORITY after its normalize;
        # avoid rides its own table check below via the policy weight).
        # Residue-class score rows join the mass: label priorities and
        # image scores are pre-computed host-side, SAA contributes another
        # 0..MAX_PRIORITY component per entry.
        w_total = (ps.w_least + ps.w_most + ps.w_balanced + ps.w_node_aff
                   + ps.w_taint + ps.w_spread + ps.w_interpod
                   + sum(ps.saa_weights))
        pol_mass = 0
        if pol_prio:
            lp64 = np.asarray(ptabs.label_prio, dtype=np.int64)
            if lp64.size and int(lp64.min(initial=0)) < 0:
                # the kernel's argmax uses -1 as the infeasible sentinel
                # (matching the XLA _select); negative scores would
                # collide with it
                return None, ("negative label priority scores exceed the "
                              "fast-path score model")
            pol_mass += int(lp64.max(initial=0))
        if pol_image:
            im64 = np.asarray(ptabs.image_score, dtype=np.int64)
            if ps.w_image < 0 or (im64.size
                                  and int(im64.min(initial=0)) < 0):
                return None, ("negative image-locality scores exceed the "
                              "fast-path score model")
            pol_mass += ps.w_image * int(im64.max(initial=0))
        if pol_saa and ps.saa_weights and min(ps.saa_weights) < 0:
            return None, ("negative ServiceAntiAffinity weights exceed "
                          "the fast-path score model")
        if pol_saa:
            # the SAA normalize multiplies MAX_PRIORITY by the feasible
            # matched-pod total before the divide
            total_pods_saa = (int(gt.presence.sum())
                              + len(np.asarray(cols.req_cpu)))
            if MAX_PRIORITY * max(total_pods_saa, 1) >= (1 << 31):
                return None, ("ServiceAntiAffinity spread counts exceed "
                              f"int32 ({total_pods_saa} pods)")
        if w_total * MAX_PRIORITY + pol_mass >= (1 << 30):
            return None, "policy priority weights exceed the int32 budget"
        if ps.w_balanced and 10 * ps.w_balanced * bound_c * bound_m \
                >= (1 << 31):
            return None, "weighted balanced-allocation exceeds int32"
    w_avoid_eff = AVOID_PODS_WEIGHT if ps is None else ps.w_avoid
    for name, table in (("affinity", t.affinity_count),
                        ("intolerable", t.intolerable),
                        ("avoid", t.avoid_score)):
        if table.size and MAX_PRIORITY * int(np.max(np.abs(table))) * max(
                w_avoid_eff if name == "avoid" else 1, 1) >= (1 << 31):
            return None, f"{name} table exceeds int32"

    n = len(np.asarray(s.alloc_cpu))
    npad = -(-max(n, 1) // LANES) * LANES

    gpad = zpad = 0
    if needs_presence:
        gpad = max(-(-num_g // SUBLANES) * SUBLANES, SUBLANES)
    if group_bound:
        # SelectorSpreadPriority's zone blend multiplies per-node by
        # per-zone counts: bound both from the seeded presence plus the
        # worst case every remaining slot fills with matched pods, and
        # require the blend products to fit int32 (exactness contract)
        if config.has_services:
            col_tot = gt.presence.sum(axis=0).astype(np.int64)  # [N]
            allowed_pods_max = int(np.max(s.allowed_pods, initial=0))
            bound_node = int(col_tot.max(initial=0)) + allowed_pods_max
            zd = np.asarray(gt.zone_dom, dtype=np.int64)
            bound_zone = 1
            for dom in np.unique(zd):
                if dom == 0:
                    # the unlabeled dom-0 sentinel never enters a zone
                    # product (the kernel loops z >= 1; the XLA path zeroes
                    # zcnt[0]) — including it would spuriously reject
                    # zone-label-free clusters at scale
                    continue
                in_dom = zd == dom
                bound_zone = max(bound_zone,
                                 int(col_tot[in_dom].sum())
                                 + int(in_dom.sum()) * allowed_pods_max)
            w_spread_eff = 1 if ps is None else max(ps.w_spread, 1)
            if 3 * MAX_PRIORITY * w_spread_eff * bound_node * bound_zone \
                    >= (1 << 31):
                return None, ("spread zone-blend products exceed int32 "
                              f"(node bound {bound_node} x zone bound "
                              f"{bound_zone})")
            zpad = max(-(-config.n_zone_doms // SUBLANES) * SUBLANES,
                       SUBLANES)

    def node_row(a, fill=0):
        a = np.asarray(a, dtype=np.int64).astype(np.int32)
        out = np.full((1, npad), fill, dtype=np.int32)
        out[0, :n] = a
        return out

    def table_rows(a, fill=0):
        a = np.asarray(a)
        rows = max(a.shape[0], 1)
        out = np.full((rows, npad), fill, dtype=np.int32)
        if a.size:
            out[:a.shape[0], :n] = a.astype(np.int32)
        return out

    cond = node_row(np.asarray(s.cond_fail_bits, dtype=np.int64)
                    .astype(np.int32))
    cond[0, n:] = np.int32(1 << PAD_SENTINEL_BIT)

    def pods(a):
        return np.asarray(a, dtype=np.int64).astype(np.int32)

    alloc_scalar = used_scalar = req_scalar = None
    if n_scal:
        srows = -(-n_scal // SUBLANES) * SUBLANES
        alloc_scalar = np.zeros((srows, npad), dtype=np.int32)
        used_scalar = np.zeros((srows, npad), dtype=np.int32)
        p_count = rscal.shape[0]
        req_scalar = np.zeros((p_count, n_scal), dtype=np.int32)
        for si, (a_s, r_s, u_s) in enumerate(scal_cols):
            alloc_scalar[si, :n] = a_s.astype(np.int32)
            used_scalar[si, :n] = u_s.astype(np.int32)
            req_scalar[:, si] = r_s.astype(np.int32)

    presence = gid = port_row = disk_row = ss_row = None
    zone_ok_tbl = zone_onehot = None
    if group_bound:
        gid = pods(cols.group_id)
    if needs_presence:
        presence = np.zeros((gpad, npad), dtype=np.int32)
        presence[:num_g, :n] = gt.presence.astype(np.int32)

        def per_pod_grow(row_of_group):
            # row_of_group [G, G] -> per-pod [P, Gpad] int32 0/1
            out = np.zeros((len(gid), gpad), dtype=np.int32)
            out[:, :num_g] = row_of_group[gid]
            return out

        if config.has_ports:
            # conflict of MY port set vs each group's port set
            # (kernels._evaluate: port_conflict[port_sig[g]][port_sig])
            port_row = per_pod_grow(
                gt.port_conflict[gt.port_sig][:, gt.port_sig]
                .astype(np.int32))
        if config.has_disk_conflict:
            disk_row = per_pod_grow(
                gt.disk_conflict[gt.disk_sig][:, gt.disk_sig]
                .astype(np.int32))
        if config.has_services:
            ss_row = per_pod_grow(
                gt.ss_rows[gt.ss_sig].astype(np.int32))
            zone_onehot = np.zeros((zpad, npad), dtype=np.int32)
            zd = np.asarray(gt.zone_dom, dtype=np.int64)
            for z in range(config.n_zone_doms):
                zone_onehot[z, :n] = (zd == z).astype(np.int32)
    if config.has_vol_zone:
        zone_ok_tbl = table_rows(gt.zone_ok, fill=0)

    used_vols = vol_tbl = None
    n_vols = 0
    vol_type3 = ()
    mp_limits = (0, 0, 0)
    mp_enabled = (True, True, True)
    if config.has_maxpd and ps is not None and ps.pred_keys is not None:
        mp_enabled = (MAX_EBS_VOLUME_COUNT_PRED in ps.pred_keys,
                      MAX_GCE_PD_VOLUME_COUNT_PRED in ps.pred_keys,
                      MAX_AZURE_DISK_VOLUME_COUNT_PRED in ps.pred_keys)
    if config.has_maxpd and any(mp_enabled):
        n_vols = n_vols_real
        vpad8 = max(-(-n_vols // SUBLANES) * SUBLANES, SUBLANES)
        vpad_l = max(-(-n_vols // LANES) * LANES, LANES)
        used_vols = np.zeros((vpad8, npad), dtype=np.int32)
        used_vols[:n_vols, :n] = gt.used_vols_init.T.astype(np.int32)
        vol_tbl = np.zeros((max(num_g, 1), vpad_l), dtype=np.int32)
        vol_tbl[:num_g, :n_vols] = gt.vol_mask.astype(np.int32)
        vol_type3 = tuple(int(v) for v in
                          np.asarray(gt.vol_type, dtype=np.int64).flatten())
        mp_limits = tuple(int(x) for x in config.maxpd_limits)

    topo_rows = presence_dom = ip_tbl = None
    ip_static = {}
    k_keys = d_doms_real = ta = tb = tp = 0
    if config.has_interpod:
        k_keys, d_doms, ta, tb, tp = ip_dims
        d_doms_real = d_doms
        kpad8 = max(-(-k_keys // SUBLANES) * SUBLANES, SUBLANES)
        dpad = max(-(-d_doms // LANES) * LANES, LANES)
        topo_rows = np.zeros((kpad8, npad), dtype=np.int32)
        topo_rows[:k_keys, :n] = gt.topo_dom.astype(np.int32)
        # pad rows and pad nodes keep domain 0 ("label missing": never
        # matches, and pad nodes are infeasible everywhere anyway)
        presence_dom = embed_presence_dom(gt.presence, gt.topo_dom, d_doms,
                                          gpad, dpad)
        # every per-pod interpod operand is a pure function of the pod's
        # GROUP, so the packed rows live in a [Gpad, Wip] table gathered by
        # group id per chunk on device — no O(P) host materialization
        L = IpLayout(ta, tb, tp, gpad)
        ip_tbl = np.zeros((gpad, L.width), dtype=np.int32)
        gi = np.arange(num_g)
        tm = gt.term_match.astype(np.int32)            # [Td, G]

        def put(offset, arr):
            a = np.asarray(arr).reshape(num_g, -1).astype(np.int32)
            ip_tbl[:num_g, offset:offset + a.shape[1]] = a

        def pad_groups(a3):
            # [G, T, G] match tensor -> [G, T, Gpad]
            out = np.zeros((num_g, a3.shape[1], gpad), np.int32)
            out[:, :, :num_g] = a3
            return out

        put(L.aff_match, pad_groups(tm[gt.aff_term[gi]]))
        put(L.aff_key, gt.aff_key[gi])
        put(L.aff_valid, gt.aff_valid[gi])
        put(L.aff_empty, gt.aff_empty[gi])
        put(L.aff_host, gt.aff_hostname[gi])
        put(L.aff_self, gt.aff_self[gi])
        put(L.aff_unpl, gt.aff_unplaced[gi])
        put(L.aff_err, gt.aff_err[gi])
        put(L.anti_match, pad_groups(tm[gt.anti_term[gi]]))
        put(L.anti_key, gt.anti_key[gi])
        put(L.anti_valid, gt.anti_valid[gi])
        put(L.anti_host, gt.anti_hostname[gi])
        put(L.anti_err, gt.anti_err[gi])
        put(L.pref_match, pad_groups(tm[gt.pref_term[gi]]))
        put(L.pref_key, gt.pref_key[gi])
        put(L.pref_w, np.round(gt.pref_w[gi]).astype(np.int64))
        # exist side: does group g2's term t match ME — transpose of the
        # same factored tables, padded on the OUTER group axis
        def exist_bits(term_ids, t_):
            # [G_me, Gpad * t_]: bit (g2, t) = term_match[term_ids[g2, t], me]
            a = tm[term_ids][:, :, gi]                 # [G, T, G_me]
            out = np.zeros((num_g, gpad, t_), np.int32)
            out[:, :num_g] = a.transpose(2, 0, 1)
            return out

        put(L.ex_anti, exist_bits(gt.anti_term, tb))
        put(L.ex_pref, exist_bits(gt.pref_term, tp))
        put(L.ex_aff, exist_bits(gt.aff_term, ta))

        def bake(a, t_, dtype=np.int64):
            out = np.zeros((gpad, t_), dtype=dtype)
            out[:num_g] = a
            return tuple(int(v) for v in out.flatten())

        ip_static = dict(
            exist_anti_key=bake(gt.anti_key, tb),
            exist_anti_mask=bake(gt.anti_valid & ~gt.anti_empty, tb),
            exist_anti_empty=bake(gt.anti_valid & gt.anti_empty, tb),
            exist_pref_key=bake(gt.pref_key, tp),
            exist_pref_w=bake(np.round(gt.pref_w).astype(np.int64), tp),
            exist_aff_key=bake(gt.aff_key, ta),
            exist_aff_mask=bake(gt.aff_valid & ~gt.aff_empty, ta),
        )

    label_tbl = label_prio_row = image_tbl = img_id_col = noexec_tbl = None
    saa_row = saa_dom_tbl = None
    sa_sig = sa_pin_row = sa_match_row = sa_val_tbl = sa_lock_init = None
    n_saa_doms_p = 0
    sa_la = 0
    if pol_any:
        gid_all = pods(cols.group_id)
        if pol_label:
            # whatif axis-unification may pad the shared table wider than
            # this scenario's policy needs — slice to the spec's own rows
            lr = np.asarray(ptabs.label_ok)[:len(ps.label_rows)]
            lpad8 = max(-(-lr.shape[0] // SUBLANES) * SUBLANES, SUBLANES)
            label_tbl = np.zeros((lpad8, npad), dtype=np.int32)
            label_tbl[:lr.shape[0], :n] = lr.astype(np.int32)
        if pol_prio:
            label_prio_row = node_row(ptabs.label_prio)
        if pol_image:
            image_tbl = table_rows(ptabs.image_score)
            img_id_col = pods(cols.img_id)
        if pol_noexec:
            noexec_tbl = table_rows(t.taint_ok_noexec)
        if pol_saa:
            n_saa_doms_p = int(config.n_saa_doms)
            saa_row = np.zeros((len(gid_all), gpad), dtype=np.int32)
            saa_row[:, :num_g] = \
                gt.saa_rows[gt.saa_sig[gid_all]].astype(np.int32)
            ne = len(ps.saa_weights)
            dom_rows_p = np.asarray(ptabs.saa_dom)[:ne]
            epad8 = max(-(-ne // SUBLANES) * SUBLANES, SUBLANES)
            saa_dom_tbl = np.zeros((epad8, npad), dtype=np.int32)
            saa_dom_tbl[:ne, :n] = dom_rows_p.astype(np.int32)
        if pol_sa:
            sa_la = int(sum(ps.sa_segs))
            fd_real = int(gt.saa_rows.shape[0])
            fd8 = max(-(-fd_real // SUBLANES) * SUBLANES, SUBLANES)
            la8 = max(-(-max(sa_la, 1) // SUBLANES) * SUBLANES, SUBLANES)
            sa_sig = pods(gt.saa_sig[gid_all])
            pin = np.asarray(ptabs.sa_pin)[pods(cols.sa_self_id)][:, :sa_la]
            sa_pin_row = np.zeros((len(gid_all), la8), dtype=np.int32)
            sa_pin_row[:, :sa_la] = pin.astype(np.int32)
            sa_match_row = np.zeros((len(gid_all), fd8), dtype=np.int32)
            sa_match_row[:, :fd_real] = \
                gt.saa_rows[:, gid_all].T.astype(np.int32)
            lapad8 = max(-(-max(sa_la, 1) // SUBLANES) * SUBLANES, SUBLANES)
            sa_val_tbl = np.zeros((lapad8, npad), dtype=np.int32)
            sa_val_tbl[:sa_la, :n] = \
                np.asarray(ptabs.sa_val)[:sa_la].astype(np.int32)
            sa_lock_init = np.asarray(ptabs.sa_lock_init,
                                      dtype=np.int32)[:fd_real]

    plan = FastPlan(
        num_nodes=n, num_pods=len(np.asarray(cols.req_cpu)),
        most_requested=config.most_requested, num_scalars=n_scal,
        alloc_scalar=alloc_scalar, used_scalar=used_scalar,
        req_scalar=req_scalar,
        alloc_cpu=node_row(ac), alloc_mem=node_row(am),
        alloc_gpu=node_row(ag), alloc_eph=node_row(ae),
        allowed=node_row(s.allowed_pods), cond_bits=cond,
        mem_pressure=node_row(np.asarray(s.mem_pressure, dtype=np.int64)),
        disk_pressure=node_row(np.asarray(s.disk_pressure, dtype=np.int64)),
        selector_ok=table_rows(t.selector_ok),
        taint_ok=table_rows(t.taint_ok),
        intolerable=table_rows(t.intolerable),
        aff_count=table_rows(t.affinity_count),
        avoid_score=table_rows(t.avoid_score),
        host_ok=table_rows(t.host_ok),
        used_cpu=node_row(uc), used_mem=node_row(um),
        used_gpu=node_row(ug), used_eph=node_row(ue),
        nonzero_cpu=node_row(nzuc), nonzero_mem=node_row(nzum),
        pod_count=node_row(d.pod_count),
        req_cpu=pods(rc), req_mem=pods(rm), req_gpu=pods(rg),
        req_eph=pods(re_),
        nz_cpu=pods(nzc), nz_mem=pods(nzm),
        zero_request=pods(np.asarray(cols.zero_request, dtype=np.int64)),
        best_effort=pods(np.asarray(cols.best_effort, dtype=np.int64)),
        sel_id=pods(cols.sel_id), tol_id=pods(cols.tol_id),
        aff_id=pods(cols.aff_id), avoid_id=pods(cols.avoid_id),
        host_id=pods(cols.host_id),
        num_groups=gpad, has_ports=config.has_ports,
        has_disk=config.has_disk_conflict,
        has_spread=config.has_services, has_vol_zone=config.has_vol_zone,
        presence=presence, gid=gid, port_row=port_row, disk_row=disk_row,
        ss_row=ss_row, zone_ok_tbl=zone_ok_tbl, zone_onehot=zone_onehot,
        n_zone_doms=zpad if config.has_services else 0,
        gcds=(g_cpu, g_mem, g_gpu, g_eph), scalar_gcds=tuple(scal_gcds),
        has_interpod=config.has_interpod, n_topo_keys=k_keys,
        n_topo_doms_ip=d_doms_real, ta=ta, tb=tb, tp=tp,
        hard_weight=config.hard_weight, topo_rows=topo_rows,
        presence_dom=presence_dom, ipod=ip_tbl, **ip_static,
        has_maxpd=config.has_maxpd and any(mp_enabled),
        maxpd_enabled=mp_enabled, n_vols=n_vols, used_vols=used_vols,
        vol_tbl=vol_tbl, vol_type3=vol_type3, maxpd_limits=mp_limits,
        policy=ps,
        label_tbl=label_tbl, label_prio_row=label_prio_row,
        image_tbl=image_tbl, img_id=img_id_col, noexec_tbl=noexec_tbl,
        saa_row=saa_row, saa_dom_tbl=saa_dom_tbl, n_saa_doms=n_saa_doms_p,
        sa_sig=sa_sig, sa_pin_row=sa_pin_row, sa_match_row=sa_match_row,
        sa_val_tbl=sa_val_tbl, sa_lock_init=sa_lock_init, sa_la=sa_la,
    )
    return plan, ""


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class IpConst:
    """Compile-time interpod constants baked into one kernel variant (and
    therefore part of the _build_call cache key): dimensions plus the
    exist-side per-(group, term) key/weight/mask tables — per-pod operands
    then carry only match bits."""

    k_keys: int
    kpad8: int              # sublane-padded rows of the static topo block
    d_doms: int             # REAL domain count (the unroll bound)
    dpad: int               # lane-padded presence_dom width
    ta: int
    tb: int
    tp: int
    hard_weight: int
    wip: int
    exist_anti_key: Tuple[int, ...]
    exist_anti_mask: Tuple[int, ...]
    exist_anti_empty: Tuple[int, ...]
    exist_pref_key: Tuple[int, ...]
    exist_pref_w: Tuple[int, ...]
    exist_aff_key: Tuple[int, ...]
    exist_aff_mask: Tuple[int, ...]


def ip_const_of(plan: FastPlan) -> Optional[IpConst]:
    if not plan.has_interpod:
        return None
    return IpConst(
        k_keys=plan.n_topo_keys, kpad8=plan.topo_rows.shape[0],
        d_doms=plan.n_topo_doms_ip, dpad=plan.presence_dom.shape[1],
        ta=plan.ta, tb=plan.tb, tp=plan.tp, hard_weight=plan.hard_weight,
        wip=plan.ipod.shape[1],
        exist_anti_key=plan.exist_anti_key,
        exist_anti_mask=plan.exist_anti_mask,
        exist_anti_empty=plan.exist_anti_empty,
        exist_pref_key=plan.exist_pref_key,
        exist_pref_w=plan.exist_pref_w,
        exist_aff_key=plan.exist_aff_key,
        exist_aff_mask=plan.exist_aff_mask)


@dataclass(frozen=True)
class MpConst:
    """Compile-time MaxPD constants baked into one kernel variant: volume
    count/padding, per-volume type triples, and per-type limits."""

    n_vols: int
    vpad8: int       # sublane-padded carry rows
    vpad_l: int      # lane-padded per-pod mask row width
    vol_type3: Tuple[int, ...]              # [V*3] (EBS, GCE, AzureDisk)
    limits: Tuple[int, int, int]
    enabled3: Tuple[bool, bool, bool] = (True, True, True)


def mp_const_of(plan: FastPlan) -> Optional[MpConst]:
    if not plan.has_maxpd:
        return None
    return MpConst(n_vols=plan.n_vols, vpad8=plan.used_vols.shape[0],
                   vpad_l=plan.vol_tbl.shape[1], vol_type3=plan.vol_type3,
                   limits=plan.maxpd_limits, enabled3=plan.maxpd_enabled)


@dataclass(frozen=True)
class PolConst:
    """Compile-time policy-residue dimensions baked into one kernel variant
    (round 6). The PolicySpec itself already rides the _build_call cache
    key; these are the cluster-dependent axis sizes the spec alone cannot
    name: label-row padding, ServiceAntiAffinity domain rows/unroll bound,
    and the ServiceAffinity label/lock-slot widths."""

    lpad8: int = 0        # label-presence mask rows (0 = no label input)
    epad8: int = 0        # ServiceAntiAffinity dom-row padding
    n_saa_doms: int = 0   # SAA label-domain unroll bound (incl. bucket 0)
    la: int = 0           # real concatenated ServiceAffinity entry labels
    la8: int = 0          # SMEM pin-row width
    fd: int = 0           # ServiceAffinity lock slots (misc lanes 1..fd)
    fd8: int = 0          # SMEM match-row width
    lapad8: int = 0       # sa_val table rows
    has_label: bool = False
    has_prio: bool = False
    has_image: bool = False
    has_noexec: bool = False
    has_saa: bool = False
    has_sa: bool = False


def pol_const_of(plan: FastPlan) -> Optional[PolConst]:
    if plan.policy is None:
        return None
    has_label = plan.label_tbl is not None
    has_prio = plan.label_prio_row is not None
    has_image = plan.image_tbl is not None
    has_noexec = plan.noexec_tbl is not None
    has_saa = plan.saa_dom_tbl is not None
    has_sa = plan.sa_val_tbl is not None
    if not (has_label or has_prio or has_image or has_noexec or has_saa
            or has_sa):
        return None
    return PolConst(
        lpad8=plan.label_tbl.shape[0] if has_label else 0,
        epad8=plan.saa_dom_tbl.shape[0] if has_saa else 0,
        n_saa_doms=plan.n_saa_doms,
        la=plan.sa_la,
        la8=plan.sa_pin_row.shape[1] if has_sa else 0,
        fd=len(plan.sa_lock_init) if has_sa else 0,
        fd8=plan.sa_match_row.shape[1] if has_sa else 0,
        lapad8=plan.sa_val_tbl.shape[0] if has_sa else 0,
        has_label=has_label, has_prio=has_prio, has_image=has_image,
        has_noexec=has_noexec, has_saa=has_saa, has_sa=has_sa)


def _make_kernel(most_requested: bool, num_bits: int, num_scalars: int,
                 group: int, gpad: int = 0, zpad: int = 0,
                 has_ports: bool = False, has_disk: bool = False,
                 has_spread: bool = False, has_vol_zone: bool = False,
                 ip: Optional[IpConst] = None,
                 mp: Optional[MpConst] = None,
                 pol: Optional[PolConst] = None, ps=None):
    """Kernel body for one grid step of `group` consecutive pods.

    Mosaic requires the sublane (second-to-last) block dim to be a multiple
    of 8 or the whole array axis, so per-pod operands stream in blocks of
    `group`=SUBLANES pods and the kernel statically unrolls the sequential
    per-pod step `group` times (carry reads re-load the output refs, so pod
    j sees pod j-1's bind). Binds are masked whole-row vector updates — a
    one-hot (1,Npad) `pick` row — rather than dynamic-lane scalar stores,
    which Mosaic does not lower.

    gpad > 0 enables the pod-group path: a [Gpad, Npad] presence carry, per
    -pod SMEM group rows (conflict/spread flags vs each group), and all
    group state access via statically-unrolled loops over Gpad with
    (g == gid)-masked row ops — no dynamic indexing anywhere."""
    group_bound = gpad > 0
    # the bind only UPDATES the presence carry when the XLA make_step does
    # (ports|services|disk|interpod); an SAA-only plan reads presence
    # frozen at its seeded state, exactly like the host path
    pres_update = has_ports or has_disk or has_spread or (ip is not None)

    # policy gating + weights (kernels._evaluate's on()/part_on and the
    # weighted-sum table, generic_scheduler.go:631-639) — all static, so
    # gated-off stages and zero-weight components generate no code
    en = None if ps is None else ps.pred_keys

    def on(name):
        return en is None or name in en

    def part(name):
        return en is not None and name in en

    from tpusim.jaxe.kernels import policy_weights

    (w_least, w_most, w_balanced, w_node_aff, w_taint, w_avoid, w_spread,
     w_interpod) = policy_weights(ps, most_requested)

    aca = ps is not None and ps.always_check_all

    def kernel(*refs):
        (rc_r, rm_r, rg_r, re_r, nzc_r, nzm_r, zr_r, be_r,
         sel_r, tol_r, intol_r, aff_r, av_r, host_r,
         acpu_r, amem_r, agpu_r, aeph_r, allowed_r, cond_r, mpr_r, dpr_r,
         iuc_r, ium_r, iug_r, iue_r, inzc_r, inzm_r, ipc_r,
         imisc_r) = refs[:30]
        at = 30
        if num_scalars:
            rs_r, ascal_r, ius_r = refs[at:at + 3]
            at += 3
        if has_vol_zone:
            # vol-zone only needs per-pod static rows, not the presence
            # carry (kernels.py skips presence updates for it too)
            vz_r = refs[at]
            at += 1
        if mp is not None:
            mvrow_r = refs[at]     # per-pod volume-mask rows [SUB, Vpad_l]
            iuv_r = refs[at + 1]   # used-vols init carry [Vpad8, Npad]
            at += 2
        if group_bound:
            gid_r = refs[at]
            at += 1
            if has_spread:
                zoh_r = refs[at]
                at += 1
            ipres_r = refs[at]
            at += 1
            if has_ports:
                prow_r = refs[at]
                at += 1
            if has_disk:
                drow_r = refs[at]
                at += 1
            if has_spread:
                ssrow_r = refs[at]
                at += 1
            if ip is not None:
                topo_r = refs[at]      # [Kpad8, Npad] static domain rows
                iprow_r = refs[at + 1]  # per-pod packed interpod rows
                ipd_r = refs[at + 2]   # [Gpad*K, Dpad] presence_dom init
                at += 3
        if pol is not None:
            if pol.has_label:
                ltbl_r = refs[at]      # [Lpad8, Npad] 0/1 pass masks
                at += 1
            if pol.has_prio:
                lprio_r = refs[at]     # [1, Npad] pre-weighted priorities
                at += 1
            if pol.has_image:
                img_r = refs[at]       # per-pod image score rows
                at += 1
            if pol.has_noexec:
                nx_r = refs[at]        # per-pod NoExecute tolerance rows
                at += 1
            if pol.has_saa:
                samrow_r = refs[at]      # SMEM [SUB, gpad] my-service row
                saadom_r = refs[at + 1]  # [Epad8, Npad] label domains
                at += 2
            if pol.has_sa:
                sasig_r = refs[at]        # SMEM [SUB, 1] first-service sig
                sapin_r = refs[at + 1]    # SMEM [SUB, la8] own pins
                samatch_r = refs[at + 2]  # SMEM [SUB, fd8] bind match bits
                saval_r = refs[at + 3]    # [Lapad8, Npad] label values
                at += 4
        (ouc_r, oum_r, oug_r, oue_r, onzc_r, onzm_r, opc_r, omisc_r,
         choice_r, counts_r, adv_r) = refs[at:at + 11]
        at += 11
        if num_scalars:
            ous_r = refs[at]
            at += 1
        if group_bound:
            opres_r = refs[at]
            at += 1
            if ip is not None:
                opd_r = refs[at]
                at += 1
        if mp is not None:
            ouv_r = refs[at]
        p = pl.program_id(0)

        @pl.when(p == 0)
        def _init():
            ouc_r[:] = iuc_r[:]
            oum_r[:] = ium_r[:]
            oug_r[:] = iug_r[:]
            oue_r[:] = iue_r[:]
            onzc_r[:] = inzc_r[:]
            onzm_r[:] = inzm_r[:]
            opc_r[:] = ipc_r[:]
            omisc_r[:] = imisc_r[:]
            if num_scalars:
                ous_r[:] = ius_r[:]
            if group_bound:
                opres_r[:] = ipres_r[:]
                if ip is not None:
                    opd_r[:] = ipd_r[:]
            if mp is not None:
                ouv_r[:] = iuv_r[:]

        acpu = acpu_r[:]
        amem = amem_r[:]
        agpu = agpu_r[:]
        aeph = aeph_r[:]
        allowed = allowed_r[:]
        cond = cond_r[:]
        fail_cond = cond != 0
        mpr = mpr_r[:] != 0
        dpr_fail = dpr_r[:] != 0
        if num_scalars:
            asc = ascal_r[:]

        for j in range(group):
            rc = rc_r[j, 0]
            rm = rm_r[j, 0]
            rg = rg_r[j, 0]
            re = re_r[j, 0]
            nzc = nzc_r[j, 0]
            nzm = nzm_r[j, 0]
            check_res = zr_r[j, 0] == 0
            best_effort = be_r[j, 0] != 0
            rr = omisc_r[0, 0]

            used_c = ouc_r[:]
            used_m = oum_r[:]
            used_g = oug_r[:]
            used_e = oue_r[:]
            nz_c = onzc_r[:]
            nz_m = onzm_r[:]
            pc = opc_r[:]

            # ---- filter stages, predicatesOrdering (kernels._evaluate).
            # Stage gating mirrors _evaluate's on()/part_on(): a policy
            # (baked statically into this variant) enables GeneralPredicates
            # and/or its individually-named parts, each a separate stage at
            # its ordering slot; ps None = the provider's full pipeline ----
            general_on = on(GENERAL_PRED)
            need_res = general_on or part(POD_FITS_RESOURCES_PRED)
            need_host = general_on or part(HOSTNAME_PRED)
            need_sel = general_on or part(MATCH_NODE_SELECTOR_PRED)
            if need_res:
                insuff_pods = (pc + 1) > allowed
                insuff_cpu = check_res & (acpu < used_c + rc)
                insuff_mem = check_res & (amem < used_m + rm)
                insuff_gpu = check_res & (agpu < used_g + rg)
                insuff_eph = check_res & (aeph < used_e + re)
                fail_res = (insuff_pods | insuff_cpu | insuff_mem
                            | insuff_gpu | insuff_eph)
                bits_res = (
                    insuff_pods.astype(jnp.int32) << BIT_INSUFFICIENT_PODS
                    | insuff_cpu.astype(jnp.int32) << BIT_INSUFFICIENT_CPU
                    | insuff_mem.astype(jnp.int32)
                    << BIT_INSUFFICIENT_MEMORY
                    | insuff_gpu.astype(jnp.int32) << BIT_INSUFFICIENT_GPU
                    | insuff_eph.astype(jnp.int32)
                    << BIT_INSUFFICIENT_EPHEMERAL)
                if num_scalars:
                    us = ous_r[:]
                    for si in range(num_scalars):
                        ins = check_res & (asc[si:si + 1, :]
                                           < us[si:si + 1, :] + rs_r[j, si])
                        fail_res = fail_res | ins
                        bits_res = bits_res | (
                            ins.astype(jnp.int32)
                            << (NUM_FIXED_BITS + si))
            elif num_scalars:
                us = ous_r[:]
            if need_host:
                host_bad = host_r[j:j + 1, :] == 0
            if need_sel:
                sel_bad = sel_r[j:j + 1, :] == 0
            if general_on:
                fail_general = fail_res | host_bad | sel_bad
                bits_general = (
                    bits_res
                    | host_bad.astype(jnp.int32) << BIT_HOSTNAME_MISMATCH
                    | sel_bad.astype(jnp.int32)
                    << BIT_NODE_SELECTOR_MISMATCH)
            if group_bound:
                gid_s = gid_r[j, 0]
                pres_rows = [opres_r[g2:g2 + 1, :] for g2 in range(gpad)]
            if ip is not None:
                K, D = ip.k_keys, ip.d_doms
                L = IpLayout(ip.ta, ip.tb, ip.tp, gpad)
                pd_rows = [opd_r[r:r + 1, :] for r in range(gpad * K)]
                topo_k = [topo_r[k:k + 1, :] for k in range(K)]

                def ip_own_term(match_off, key_off, t):
                    """One own term: (mcount row, dc_at row, domsel row).
                    dc_at[n] == the XLA path's take-along of _seg_rows —
                    the per-domain sum of matched presence broadcast back
                    to nodes. Computed directly from mcount with D scalar
                    segment reductions (pd[g,k,d] is the domain-d sum of
                    presence[g], so Σ_g match·pd == Σ_{n∈d} mcount):
                    pad nodes carry domain 0 and zero presence, so they
                    never contaminate a real domain's sum."""
                    mcount = jnp.zeros_like(cond)
                    for g2 in range(gpad):
                        mcount = mcount + jnp.where(
                            iprow_r[j, match_off + t * gpad + g2] != 0,
                            pres_rows[g2], 0)
                    key_t = iprow_r[j, key_off + t]
                    domsel = jnp.zeros_like(cond)
                    for k in range(K):
                        domsel = jnp.where(key_t == k, topo_k[k], domsel)
                    dc_at = jnp.zeros_like(cond)
                    for d in range(D):
                        in_d = domsel == d
                        seg_d = jnp.sum(jnp.where(in_d, mcount, 0),
                                        dtype=jnp.int32)
                        dc_at = dc_at + jnp.where(in_d, seg_d, 0)
                    return mcount, dc_at, domsel
            ports_alias_on = ps is not None and bool(ps.ports_slots)
            if has_ports and (general_on or part(POD_FITS_HOST_PORTS_PRED)
                              or ports_alias_on):
                # PodFitsHostPorts (predicates.go:1019-1039), part of
                # GeneralPredicates (or re-emitted at an alias tail slot):
                # my port set conflicts with the port set of any group
                # present on the node
                port_bad = fail_cond & False
                for g2 in range(gpad):
                    port_bad = port_bad | jnp.where(
                        prow_r[j, g2] != 0, pres_rows[g2] > 0, False)
                if general_on:
                    fail_general = fail_general | port_bad
                    bits_general = bits_general | (
                        port_bad.astype(jnp.int32) << BIT_HOST_PORTS)

            # ---- ServiceAffinity shared prelude (kernels._evaluate): the
            # lock is the entry-independent first-matching-pod node index
            # for MY first-service signature (-1 unlocked, -2 permanently
            # unpinned, >= 0 a node), read fresh from the misc carry lanes
            # so pod j sees pod j-1's bind ----
            if pol is not None and pol.has_sa:
                sasig = sasig_r[j, 0]
                sa_lock = jnp.int32(-1)
                for f in range(pol.fd):
                    sa_lock = jnp.where(sasig == f, omisc_r[0, 1 + f],
                                        sa_lock)
                sa_li = jnp.maximum(sa_lock, 0)
                idx_n = jax.lax.broadcasted_iota(jnp.int32, cond.shape, 1)
                sa_own_l = []
                sa_lock_l = []
                for l_ in range(pol.la):
                    val_l = saval_r[l_:l_ + 1, :]
                    pin_l = sapin_r[j, l_]
                    unres = pin_l == 0
                    # own: my pin (any value when unresolved); lock: the
                    # locked node's value, binding only when I'm unresolved
                    # and the locked node actually carries the label
                    sa_own_l.append(unres | (val_l == pin_l))
                    locked_v = jnp.sum(
                        jnp.where(idx_n == sa_li, val_l, 0),
                        dtype=jnp.int32)
                    pinned = unres & (locked_v > 0)
                    sa_lock_l.append(~pinned | (val_l == locked_v))
                sa_off = [0]
                for seg in ps.sa_segs:
                    sa_off.append(sa_off[-1] + seg)

                def sa_fail(e):
                    ok_own = fail_cond | True
                    ok_lock = fail_cond | True
                    for l_ in range(sa_off[e], sa_off[e + 1]):
                        ok_own = ok_own & sa_own_l[l_]
                        ok_lock = ok_lock & sa_lock_l[l_]
                    return ~(ok_own & (ok_lock | (sa_lock < 0)))

            # policy label-presence / ServiceAffinity / ports-alias stages
            # fire at the ordering slot they were registered under,
            # mirroring kernels._evaluate's emit_label
            stages = []
            label_at = {}
            if ps is not None:
                for i_l, slot in enumerate(ps.label_rows):
                    label_at.setdefault(slot, []).append(i_l)

            def emit_label(slot_name):
                if ps is None:
                    return
                for i_l in label_at.get(slot_name, ()):
                    stages.append(
                        (ltbl_r[i_l:i_l + 1, :] == 0,
                         jnp.int32(1) << BIT_NODE_LABEL_PRESENCE))
                for e, slot in enumerate(ps.sa_slots):
                    if slot == slot_name:
                        stages.append(
                            (sa_fail(e),
                             jnp.int32(1) << BIT_SERVICE_AFFINITY))
                if slot_name in ps.ports_slots and has_ports:
                    stages.append(
                        (port_bad, jnp.int32(1) << BIT_HOST_PORTS))

            # short-circuit reason selection: first failing stage wins in
            # predicatesOrdering (cond -> general -> hostname -> ports ->
            # selector -> resources -> NoDiskConflict -> taints ->
            # NoExecute -> MaxPD -> NoVolumeZoneConflict -> memory pressure
            # -> disk pressure -> interpod, matching kernels._evaluate
            # incl. policy part slots and every emit_label ordering slot)
            stages.append((fail_cond, cond))
            if aca and en is not None \
                    and CHECK_NODE_UNSCHEDULABLE_PRED in en:
                # count mode re-reports unschedulable as its own stage on
                # top of the condition stage (kernels._evaluate)
                stages.append(
                    ((cond & (jnp.int32(1) << BIT_NODE_UNSCHEDULABLE)) != 0,
                     jnp.int32(1) << BIT_NODE_UNSCHEDULABLE))
            emit_label(CHECK_NODE_UNSCHEDULABLE_PRED)
            if general_on:
                stages.append((fail_general, bits_general))
            emit_label(GENERAL_PRED)
            if part(HOSTNAME_PRED):
                stages.append(
                    (host_bad, jnp.int32(1) << BIT_HOSTNAME_MISMATCH))
            emit_label(HOSTNAME_PRED)
            if part(POD_FITS_HOST_PORTS_PRED) and has_ports:
                stages.append((port_bad, jnp.int32(1) << BIT_HOST_PORTS))
            emit_label(POD_FITS_HOST_PORTS_PRED)
            if part(MATCH_NODE_SELECTOR_PRED):
                stages.append(
                    (sel_bad, jnp.int32(1) << BIT_NODE_SELECTOR_MISMATCH))
            emit_label(MATCH_NODE_SELECTOR_PRED)
            if part(POD_FITS_RESOURCES_PRED):
                stages.append((fail_res, bits_res))
            emit_label(POD_FITS_RESOURCES_PRED)
            if has_disk and on(NO_DISK_CONFLICT_PRED):
                # NoDiskConflict (predicates.go:266-276): my volume set
                # conflicts with the volume set of any group present
                fail_disk = fail_cond & False
                for g2 in range(gpad):
                    fail_disk = fail_disk | jnp.where(
                        drow_r[j, g2] != 0, pres_rows[g2] > 0, False)
                stages.append(
                    (fail_disk, jnp.int32(1) << BIT_DISK_CONFLICT))
            emit_label(NO_DISK_CONFLICT_PRED)
            if on(POD_TOLERATES_NODE_TAINTS_PRED):
                fail_taint = tol_r[j:j + 1, :] == 0
                stages.append(
                    (fail_taint, jnp.int32(1) << BIT_TAINTS_NOT_TOLERATED))
            emit_label(POD_TOLERATES_NODE_TAINTS_PRED)
            if pol is not None and pol.has_noexec:
                # the NoExecute-only taint predicate shares the taint
                # reason bit (kernels._evaluate's noexec stage)
                stages.append(
                    (nx_r[j:j + 1, :] == 0,
                     jnp.int32(1) << BIT_TAINTS_NOT_TOLERATED))
            emit_label(POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED)
            emit_label(CHECK_NODE_LABEL_PRESENCE_PRED)
            emit_label(CHECK_SERVICE_AFFINITY_PRED)
            if mp is not None:
                # Max{EBS,GCEPD,AzureDisk}VolumeCount (predicates.go:422
                # -460): unique relevant volume ids on the node incl. mine
                # vs the per-type limit; a pod adding no relevant volumes
                # passes regardless. Type triples are static, so only
                # typed volumes generate code.
                uv_rows = [ouv_r[v:v + 1, :] for v in range(mp.n_vols)]
                fail_maxpd = fail_cond & False
                for t3 in range(3):
                    if not mp.enabled3[t3]:
                        continue  # policy-disabled type (XLA: limit 2^30)
                    typed = [v for v in range(mp.n_vols)
                             if mp.vol_type3[v * 3 + t3]]
                    if not typed:
                        continue
                    myc = jnp.int32(0)
                    cnt = jnp.zeros_like(cond)
                    for v in typed:
                        mb = mvrow_r[j, v] != 0
                        myc = myc + mb.astype(jnp.int32)
                        cnt = cnt + jnp.where(mb, 1, uv_rows[v])
                    fail_maxpd = fail_maxpd | (
                        (myc > 0) & (cnt > mp.limits[t3]))
                stages.append(
                    (fail_maxpd, jnp.int32(1) << BIT_MAX_VOLUME_COUNT))
            emit_label(MAX_EBS_VOLUME_COUNT_PRED)
            emit_label(MAX_GCE_PD_VOLUME_COUNT_PRED)
            emit_label(MAX_AZURE_DISK_VOLUME_COUNT_PRED)
            emit_label(CHECK_VOLUME_BINDING_PRED)
            if has_vol_zone and on(NO_VOLUME_ZONE_CONFLICT_PRED):
                # NoVolumeZoneConflict (predicates.go:510-533): static per
                # (volume-set, node) row, pregathered per pod
                fail_vz = vz_r[j:j + 1, :] == 0
                stages.append(
                    (fail_vz, jnp.int32(1) << BIT_VOLUME_ZONE_CONFLICT))
            emit_label(NO_VOLUME_ZONE_CONFLICT_PRED)
            if on(CHECK_NODE_MEMORY_PRESSURE_PRED):
                stages.append((mpr & best_effort,
                               jnp.int32(1) << BIT_MEMORY_PRESSURE))
            emit_label(CHECK_NODE_MEMORY_PRESSURE_PRED)
            if on(CHECK_NODE_DISK_PRESSURE_PRED):
                stages.append((dpr_fail,
                               jnp.int32(1) << BIT_DISK_PRESSURE))
            emit_label(CHECK_NODE_DISK_PRESSURE_PRED)
            if ip is not None and on(MATCH_INTERPOD_AFFINITY_PRED):
                # MatchInterPodAffinity (predicates.go:1125-1450) — last in
                # predicatesOrdering; mirrors kernels._evaluate's stage.
                # own required affinity terms
                aff_fail = fail_cond & False
                for t in range(ip.ta):
                    mcount, dc_at, domsel = ip_own_term(
                        L.aff_match, L.aff_key, t)
                    valid_t = iprow_r[j, L.aff_valid + t] != 0
                    host_t = iprow_r[j, L.aff_host + t] != 0
                    self_t = iprow_r[j, L.aff_self + t] != 0
                    unpl_t = iprow_r[j, L.aff_unpl + t] != 0
                    valid_dom = domsel > 0
                    on_node = mcount > 0
                    term_matches = jnp.where(host_t, valid_dom & on_node,
                                             valid_dom & (dc_at > 0))
                    # hostname terms scan only this node's pods; otherwise
                    # "a matching pod exists" is global (incl. unplaced
                    # snapshot pods)
                    exists_any = (jnp.sum(mcount, dtype=jnp.int32) > 0) \
                        | unpl_t
                    exists = jnp.where(host_t, on_node, exists_any)
                    term_ok = term_matches | ((~exists) & self_t)
                    aff_fail = aff_fail | (valid_t & ~term_ok)
                aff_fail = aff_fail | (iprow_r[j, L.aff_err] != 0)
                # own required anti-affinity terms
                anti_fail = fail_cond & False
                for t in range(ip.tb):
                    bmcount, bdc_at, bdomsel = ip_own_term(
                        L.anti_match, L.anti_key, t)
                    valid_t = iprow_r[j, L.anti_valid + t] != 0
                    host_t = iprow_r[j, L.anti_host + t] != 0
                    bvalid_dom = bdomsel > 0
                    b_matches = jnp.where(host_t, bvalid_dom & (bmcount > 0),
                                          bvalid_dom & (bdc_at > 0))
                    anti_fail = anti_fail | (valid_t & b_matches)
                anti_fail = anti_fail | (iprow_r[j, L.anti_err] != 0)
                # existing pods' anti-affinity vs me (symmetric; runs first
                # in the reference's check order). Keys/masks are static:
                # only referenced (group, term) pairs generate any code.
                Bk = [jnp.zeros_like(pd_rows[0]) for _ in range(K)]
                fail_all = jnp.int32(0)
                for g2 in range(gpad):
                    for t in range(ip.tb):
                        idx = g2 * ip.tb + t
                        if ip.exist_anti_mask[idx]:
                            k_gt = ip.exist_anti_key[idx]
                            mbit = iprow_r[j, L.ex_anti + idx] != 0
                            Bk[k_gt] = Bk[k_gt] + jnp.where(
                                mbit, pd_rows[g2 * K + k_gt], 0)
                        if ip.exist_anti_empty[idx]:
                            gp = jnp.sum(pres_rows[g2],
                                         dtype=jnp.int32) > 0
                            mbit = iprow_r[j, L.ex_anti + idx] != 0
                            fail_all = fail_all | (
                                mbit & gp).astype(jnp.int32)
                exist_fail = fail_cond & False
                for k in range(K):
                    for d in range(1, D):
                        exist_fail = exist_fail | (
                            (topo_k[k] == d) & (Bk[k][0, d] > 0))
                exist_fail = exist_fail | (fail_all != 0)
                fail_interpod = exist_fail | aff_fail | anti_fail
                # two reasons per failure: the umbrella + the specific rule
                # in the engine's check order
                ip_bits = (jnp.int32(1) << BIT_AFFINITY_NOT_MATCH) | \
                    jnp.where(
                        exist_fail,
                        jnp.int32(1) << BIT_EXISTING_ANTI_AFFINITY,
                        jnp.where(aff_fail,
                                  jnp.int32(1) << BIT_AFFINITY_RULES,
                                  jnp.int32(1) << BIT_ANTI_AFFINITY_RULES))
                stages.append((fail_interpod, ip_bits))
            emit_label(MATCH_INTERPOD_AFFINITY_PRED)
            if ps is not None:
                # alphabetical-tail alias slots (predicate names the host
                # orders after the known pipeline)
                tail_ks = sorted(
                    int(s_.split(":", 1)[1])
                    for s_ in set(ps.label_rows) | set(ps.sa_slots)
                    | set(ps.ports_slots) if s_.startswith("tail:"))
                for tk in tail_ks:
                    emit_label(f"tail:{tk}")
            feasible = jnp.ones_like(fail_cond)
            reason = jnp.zeros_like(cond)
            if aca:
                # count mode: no short-circuit — every stage's failure
                # bits stay live for the histogram below
                for fail, _ in stages:
                    feasible = feasible & ~fail
            else:
                for fail, bits in reversed(stages):
                    feasible = feasible & ~fail
                    reason = jnp.where(fail, bits, reason)
            n_feasible = jnp.sum(feasible.astype(jnp.int32), dtype=jnp.int32)
            found = n_feasible > 0

            # ---- score (weighted sum, generic_scheduler.go:631-639;
            # int32 throughout — products bounded by plan_fast; weights
            # are compile-time statics, so zero-weight components generate
            # no code, exactly like kernels._evaluate's gating) ----
            score = jnp.zeros_like(cond)
            if w_least or w_most or w_balanced:
                total_c = nz_c + nzc
                total_m = nz_m + nzm

            def ratio(req, cap, most):
                valid = (cap > 0) & (req <= cap)
                if most:
                    expr = (req * MAX_PRIORITY) // jnp.maximum(cap, 1)
                else:
                    expr = ((cap - req) * MAX_PRIORITY) // jnp.maximum(cap, 1)
                return jnp.where(valid, expr, 0)

            if w_least:
                score = score + w_least * (
                    (ratio(total_c, acpu, False)
                     + ratio(total_m, amem, False)) // 2)
            if w_most:
                score = score + w_most * (
                    (ratio(total_c, acpu, True)
                     + ratio(total_m, amem, True)) // 2)
            if w_balanced:
                # balanced (exact rational, DEVIATIONS.md #16): products
                # fit int32
                num = jnp.abs(total_c * amem - total_m * acpu)
                den = acpu * amem
                bal = (MAX_PRIORITY * (den - num)) // jnp.maximum(den, 1)
                bal_zero = ((acpu == 0) | (total_c >= acpu)
                            | (amem == 0) | (total_m >= amem))
                score = score + w_balanced * jnp.where(bal_zero, 0, bal)
            if w_node_aff:
                # NodeAffinityPriority normalize over feasible nodes
                aff = aff_r[j:j + 1, :]
                aff_max = jnp.max(jnp.where(feasible, aff, 0))
                score = score + w_node_aff * jnp.where(
                    aff_max > 0,
                    MAX_PRIORITY * aff // jnp.maximum(aff_max, 1), 0)
            if w_taint:
                # TaintTolerationPriority reversed normalize
                intol = intol_r[j:j + 1, :]
                intol_max = jnp.max(jnp.where(feasible, intol, 0))
                score = score + w_taint * jnp.where(
                    intol_max > 0,
                    MAX_PRIORITY
                    - MAX_PRIORITY * intol // jnp.maximum(intol_max, 1),
                    MAX_PRIORITY)
            if w_avoid:
                score = score + av_r[j:j + 1, :] * w_avoid
            if pol is not None and pol.has_prio:
                # NodeLabel/LabelPreference priorities: static pre-weighted
                # per-node row (kernels._evaluate's st.label_prio)
                score = score + lprio_r[0:1, :]
            if pol is not None and pol.has_image:
                # ImageLocalityPriority: static per (image-set, node) score
                score = score + img_r[j:j + 1, :] * ps.w_image
            if pol is not None and pol.has_saa:
                # ServiceAntiAffinity (selector_spreading.go:176-280): per
                # -node count of pods in MY first service, normalized per
                # label domain; domain 0 = label missing (score stays 0)
                saa_cnt = jnp.zeros_like(score)
                for g2 in range(gpad):
                    saa_cnt = saa_cnt + jnp.where(
                        samrow_r[j, g2] != 0, pres_rows[g2], 0)
                saa_fcnt = jnp.where(feasible, saa_cnt, 0)
                saa_total = jnp.sum(saa_fcnt, dtype=jnp.int32)
                for e, w_saa in enumerate(ps.saa_weights):
                    dom_row = saadom_r[e:e + 1, :]
                    labeled = dom_row > 0
                    grp_at = jnp.zeros_like(score)
                    for d2 in range(1, pol.n_saa_doms):
                        in_d = dom_row == d2
                        seg_d = jnp.sum(jnp.where(in_d, saa_fcnt, 0),
                                        dtype=jnp.int32)
                        grp_at = grp_at + jnp.where(in_d, seg_d, 0)
                    f_sc = jnp.where(
                        saa_total > 0,
                        (MAX_PRIORITY * (saa_total - grp_at))
                        // jnp.maximum(saa_total, 1),
                        MAX_PRIORITY)
                    score = score + jnp.where(labeled, f_sc, 0) * w_saa
            if has_spread and w_spread:
                # SelectorSpreadPriority (selector_spreading.go:66-175):
                # per-node count of pods matched by my services' selectors
                # (groups flagged in my ss row), node/zone-blended exact
                # normalize — int32 products bounded by plan_fast's gate
                cnt = jnp.zeros_like(score)
                for g2 in range(gpad):
                    cnt = cnt + jnp.where(
                        ssrow_r[j, g2] != 0, pres_rows[g2], 0)
                fcnt = jnp.where(feasible, cnt, 0)
                max_node = jnp.max(fcnt)
                row0 = zoh_r[0:1, :]
                zvalid = row0 == 0  # rows 1.. are real zone domains
                zper = jnp.zeros_like(cnt)
                max_zone = jnp.int32(0)
                for z in range(1, zpad):
                    zrow = zoh_r[z:z + 1, :]
                    zc = jnp.sum(zrow * fcnt, dtype=jnp.int32)
                    zper = zper + zc * zrow
                    max_zone = jnp.maximum(max_zone, zc)
                have_zones = jnp.any(feasible & zvalid)
                node_num = jnp.where(max_node > 0, max_node - cnt, 1)
                node_den = jnp.maximum(max_node, 1)
                zone_num = jnp.where(max_zone > 0, max_zone - zper, 1)
                zone_den = jnp.maximum(max_zone, 1)
                plain = (MAX_PRIORITY * node_num) // node_den
                blend = (MAX_PRIORITY
                         * (node_num * zone_den + 2 * zone_num * node_den)
                         ) // (3 * node_den * zone_den)
                score = score + w_spread * jnp.where(
                    have_zones & zvalid, blend, plain)
            if ip is not None and w_interpod:
                # InterPodAffinityPriority (interpod_affinity.go:118+):
                # (a) my preferred terms over existing pods, (b) existing
                # pods' preferred terms over me, (c) their required
                # affinity x hard weight — int32 throughout (plan_fast
                # bounds the weight mass x pod population)
                counts_row = jnp.zeros_like(score)
                for t in range(ip.tp):
                    _, pdc_at, pdomsel = ip_own_term(
                        L.pref_match, L.pref_key, t)
                    w_t = iprow_r[j, L.pref_w + t]
                    counts_row = counts_row + jnp.where(
                        pdomsel > 0, pdc_at, 0) * w_t
                Wk = [jnp.zeros_like(pd_rows[0]) for _ in range(K)]
                for g2 in range(gpad):
                    for t in range(ip.tp):
                        idx = g2 * ip.tp + t
                        w_s = ip.exist_pref_w[idx]
                        if w_s:
                            k_gt = ip.exist_pref_key[idx]
                            mbit = iprow_r[j, L.ex_pref + idx] != 0
                            Wk[k_gt] = Wk[k_gt] + jnp.where(
                                mbit, pd_rows[g2 * K + k_gt] * w_s, 0)
                    for t in range(ip.ta):
                        idx = g2 * ip.ta + t
                        if ip.exist_aff_mask[idx]:
                            k_gt = ip.exist_aff_key[idx]
                            mbit = iprow_r[j, L.ex_aff + idx] != 0
                            Wk[k_gt] = Wk[k_gt] + jnp.where(
                                mbit,
                                pd_rows[g2 * K + k_gt] * ip.hard_weight, 0)
                for k in range(K):
                    for d in range(1, D):
                        counts_row = counts_row + jnp.where(
                            topo_k[k] == d, Wk[k][0, d], 0)
                big_i = jnp.int32(1 << 30)
                maxc = jnp.maximum(
                    jnp.max(jnp.where(feasible, counts_row, -big_i)), 0)
                minc = jnp.minimum(
                    jnp.min(jnp.where(feasible, counts_row, big_i)), 0)
                rng_i = maxc - minc
                score = score + w_interpod * jnp.where(
                    rng_i > 0,
                    (MAX_PRIORITY * (counts_row - minc))
                    // jnp.maximum(rng_i, 1),
                    0)

            # ---- selectHost: stable-desc argmax + round-robin tie pick ----
            masked = jnp.where(feasible, score, -1)
            max_score = jnp.max(masked)
            tie = feasible & (masked == max_score)
            ties = jnp.maximum(
                jnp.sum(tie.astype(jnp.int32), dtype=jnp.int32), 1)
            k = jnp.where(n_feasible > 1, rr % ties, 0)
            rank = (jnp.cumsum(tie.astype(jnp.int32), axis=1,
                               dtype=jnp.int32) - 1)
            pick = tie & (rank == k)
            idx_row = jax.lax.broadcasted_iota(jnp.int32, pick.shape, 1)
            choice = jnp.min(jnp.where(pick, idx_row, jnp.int32(1 << 30)))
            choice_r[j, 0] = jnp.where(found, choice, -1)
            adv_r[j, 0] = (n_feasible > 1).astype(jnp.int32)

            # ---- reason histogram (zeros when scheduled) ----
            if aca:
                # count mode: every failing stage contributes its decoded
                # reasons (the host keeps evaluating past the first
                # failure); pad nodes carry the int32 sentinel bit (the
                # XLA path's bit-62 analog) and must contribute nothing
                live = (cond & (jnp.int32(1) << PAD_SENTINEL_BIT)) == 0
                for b in range(num_bits):
                    tot_b = jnp.int32(0)
                    for fail, bits in stages:
                        tot_b = tot_b + jnp.sum(
                            jnp.where(fail & live, (bits >> b) & 1, 0),
                            dtype=jnp.int32)
                    counts_r[j, b] = jnp.where(found, 0, tot_b)
            else:
                fr = jnp.where(found, jnp.zeros_like(reason), reason)
                for b in range(num_bits):
                    counts_r[j, b] = jnp.sum((fr >> b) & 1, dtype=jnp.int32)
            counts_r[j, num_bits:] = jnp.zeros(
                (counts_r.shape[1] - num_bits,), dtype=jnp.int32)

            # ---- bind: one-hot masked whole-row updates (pick is all-False
            # when nothing is feasible, so no `found` gate is needed) ----
            ouc_r[:] = jnp.where(pick, used_c + rc, used_c)
            oum_r[:] = jnp.where(pick, used_m + rm, used_m)
            oug_r[:] = jnp.where(pick, used_g + rg, used_g)
            oue_r[:] = jnp.where(pick, used_e + re, used_e)
            onzc_r[:] = jnp.where(pick, nz_c + nzc, nz_c)
            onzm_r[:] = jnp.where(pick, nz_m + nzm, nz_m)
            opc_r[:] = jnp.where(pick, pc + 1, pc)
            if num_scalars:
                for si in range(num_scalars):
                    ous_r[si:si + 1, :] = jnp.where(
                        pick, us[si:si + 1, :] + rs_r[j, si],
                        us[si:si + 1, :])
            if group_bound and pres_update:
                # presence[gid, choice] += 1 via (g == gid)-masked row adds
                pick_i = pick.astype(jnp.int32)
                for g2 in range(gpad):
                    opres_r[g2:g2 + 1, :] = jnp.where(
                        gid_s == g2, pres_rows[g2] + pick_i, pres_rows[g2])
            if mp is not None:
                for v in range(mp.n_vols):
                    mb = mvrow_r[j, v] != 0
                    ouv_r[v:v + 1, :] = jnp.where(
                        pick & mb, 1, uv_rows[v])
            if ip is not None:
                # presence_dom[gid, k, dom_k(choice)] += 1: the chosen
                # node's domain id per key is a one-hot-extracted scalar
                # (pick is one-hot), then a lane-one-hot masked row add —
                # all-False pick (no feasible node) adds nothing
                found_i = found.astype(jnp.int32)
                for k in range(K):
                    chosen_dom = jnp.sum(jnp.where(pick, topo_k[k], 0),
                                         dtype=jnp.int32)
                    ohrow = (jax.lax.broadcasted_iota(
                        jnp.int32, pd_rows[0].shape, 1)
                        == chosen_dom).astype(jnp.int32) * found_i
                    for g2 in range(gpad):
                        r = g2 * K + k
                        opd_r[r:r + 1, :] = jnp.where(
                            gid_s == g2, pd_rows[r] + ohrow, pd_rows[r])

            if pol is not None and pol.has_sa and ps.sa_enabled:
                # first matching bind locks each still-unlocked signature
                # to the chosen node (kernels.make_step's sa_lock scatter)
                for f in range(pol.fd):
                    lock_f = omisc_r[0, 1 + f]
                    omisc_r[0, 1 + f] = jnp.where(
                        (lock_f == -1) & (samatch_r[j, f] != 0) & found,
                        choice, lock_f)

            omisc_r[0, 0] = rr + (n_feasible > 1).astype(jnp.int32)

    return kernel


@lru_cache(maxsize=16)
def _build_call(npad: int, k: int, most_requested: bool, num_bits: int,
                counts_w: int, num_scalars: int, srows: int, interpret: bool,
                gpad: int = 0, zpad: int = 0, has_ports: bool = False,
                has_disk: bool = False, has_spread: bool = False,
                has_vol_zone: bool = False, ip: Optional[IpConst] = None,
                mp: Optional[MpConst] = None,
                pol: Optional[PolConst] = None, ps=None):
    """jitted pallas_call for one (node-pad, chunk, scalar, group) shape.

    k must be a multiple of SUBLANES: Mosaic rejects blocks whose sublane
    dim is neither a multiple of 8 nor the whole axis, so per-pod operands
    move in (SUBLANES, …) blocks and the grid covers k/SUBLANES steps of
    SUBLANES statically-unrolled pods each."""
    assert k % SUBLANES == 0, k
    group_bound = gpad > 0
    kernel = _make_kernel(most_requested, num_bits, num_scalars, SUBLANES,
                          gpad, zpad, has_ports, has_disk, has_spread,
                          has_vol_zone, ip, mp, pol, ps)

    def smem_rows(width=1):
        return pl.BlockSpec((SUBLANES, width), lambda p: (p, 0),
                            memory_space=_SMEM) \
            if _SMEM is not None else pl.BlockSpec((SUBLANES, width),
                                                   lambda p: (p, 0))

    def row_per_pod(width=None):
        kw = {"memory_space": _VMEM} if _VMEM is not None else {}
        return pl.BlockSpec((SUBLANES, width or npad), lambda p: (p, 0), **kw)

    def const_row(width=None, rows=1):
        kw = {"memory_space": _VMEM} if _VMEM is not None else {}
        return pl.BlockSpec((rows, width or npad), lambda p: (0, 0), **kw)

    scalar_in = ([row_per_pod(LANES),            # req_scalar row per pod
                  const_row(rows=srows),         # alloc_scalar
                  const_row(rows=srows)]         # init used_scalar
                 if num_scalars else [])
    scalar_out = [const_row(rows=srows)] if num_scalars else []
    # group inputs (order mirrors the kernel's unpack): [zone rows], gid,
    # [zone onehot], presence init, [port rows], [disk rows], [spread rows]
    group_in = []
    group_out = []
    if has_vol_zone:
        group_in.append(row_per_pod())                 # zone_ok rows
    if mp is not None:
        group_in.append(row_per_pod(mp.vpad_l))        # volume-mask rows
        group_in.append(const_row(rows=mp.vpad8))      # used-vols init
    if group_bound:
        group_in.append(smem_rows())                   # gid
        if has_spread:
            group_in.append(const_row(rows=zpad))      # zone onehot
        group_in.append(const_row(rows=gpad))          # presence init
        if has_ports:
            group_in.append(smem_rows(gpad))           # port conflict rows
        if has_disk:
            group_in.append(smem_rows(gpad))           # disk conflict rows
        if has_spread:
            group_in.append(smem_rows(gpad))           # spread-set rows
        if ip is not None:
            group_in.append(const_row(rows=ip.kpad8))  # static topo rows
            group_in.append(row_per_pod(ip.wip))       # per-pod ip rows
            group_in.append(const_row(ip.dpad,
                                      rows=gpad * ip.k_keys))  # pd init
        group_out.append(const_row(rows=gpad))         # presence out
        if ip is not None:
            group_out.append(const_row(ip.dpad, rows=gpad * ip.k_keys))
    if mp is not None:
        group_out.append(const_row(rows=mp.vpad8))     # used-vols out
    # policy-residue inputs (order mirrors the kernel's unpack); the
    # ServiceAffinity locks ride the existing misc carry — no new outputs
    pol_in = []
    if pol is not None:
        if pol.has_label:
            pol_in.append(const_row(rows=pol.lpad8))   # label masks
        if pol.has_prio:
            pol_in.append(const_row())                 # label priority row
        if pol.has_image:
            pol_in.append(row_per_pod())               # image score rows
        if pol.has_noexec:
            pol_in.append(row_per_pod())               # noexec taint rows
        if pol.has_saa:
            pol_in.append(smem_rows(gpad))             # my-service rows
            pol_in.append(const_row(rows=pol.epad8))   # saa label domains
        if pol.has_sa:
            pol_in.append(smem_rows())                 # first-service sig
            pol_in.append(smem_rows(pol.la8))          # own pin rows
            pol_in.append(smem_rows(pol.fd8))          # bind match rows
            pol_in.append(const_row(rows=pol.lapad8))  # sa label values
    grid_spec = pl.GridSpec(
        grid=(k // SUBLANES,),
        in_specs=(
            [smem_rows() for _ in range(8)]             # pod scalars
            + [row_per_pod() for _ in range(6)]         # pregathered rows
            + [const_row() for _ in range(8)]           # statics
            + [const_row() for _ in range(7)]           # init carry
            + [const_row(LANES)]                        # init misc (rr)
            + scalar_in
            + group_in
            + pol_in
        ),
        out_specs=(
            [const_row() for _ in range(7)]             # carry out
            + [const_row(LANES)]                        # misc out
            + [pl.BlockSpec((SUBLANES, 1), lambda p: (p, 0),
                            **({"memory_space": _VMEM} if _VMEM else {}))]
            + [pl.BlockSpec((SUBLANES, counts_w), lambda p: (p, 0),
                            **({"memory_space": _VMEM} if _VMEM else {}))]
            + [pl.BlockSpec((SUBLANES, 1), lambda p: (p, 0),
                            **({"memory_space": _VMEM} if _VMEM else {}))]
            + scalar_out
            + group_out
        ),
    )
    i32 = jnp.int32
    out_shape = (
        [jax.ShapeDtypeStruct((1, npad), i32) for _ in range(7)]
        + [jax.ShapeDtypeStruct((1, LANES), i32)]
        + [jax.ShapeDtypeStruct((k, 1), i32),
           jax.ShapeDtypeStruct((k, counts_w), i32),
           jax.ShapeDtypeStruct((k, 1), i32)]
        + ([jax.ShapeDtypeStruct((srows, npad), i32)] if num_scalars else [])
        + ([jax.ShapeDtypeStruct((gpad, npad), i32)] if group_bound else [])
        + ([jax.ShapeDtypeStruct((gpad * ip.k_keys, ip.dpad), i32)]
           if ip is not None else [])
        + ([jax.ShapeDtypeStruct((mp.vpad8, npad), i32)]
           if mp is not None else [])
    )
    call = pl.pallas_call(kernel, grid_spec=grid_spec,
                          out_shape=out_shape, interpret=interpret)
    return jax.jit(lambda *args: call(*args))


def verify_against_xla(config, compiled, cols, choices, counts,
                       max_pods: int = 512, statics=None,
                       carry=None) -> bool:
    """Replay the first max_pods pods through the XLA scan and compare the
    kernel's choices AND reason histograms bit-for-bit (the AUTO-mode
    guardrail shared by JaxBackend and the what-if fast loop). Histogram
    widths may differ when a what-if batch unifies scalar axes — the
    common prefix must match and the excess columns must be zero.

    statics/carry: device-tree overrides for policies whose host statics
    carry policy tables (label rows, image scores, ServiceAffinity state)
    that the bare compiled-cluster trees lack."""
    from tpusim.jaxe.kernels import (
        _tree_to_device,
        carry_init,
        pod_columns_to_host,
        schedule_scan,
        statics_to_device,
    )

    m = min(max_pods, len(np.asarray(cols.req_cpu)))
    xs_h = pod_columns_to_host(cols)
    xs_head = _tree_to_device(type(xs_h)(*(a[:m] for a in xs_h)))
    if statics is None:
        statics = statics_to_device(compiled)
    if carry is None:
        carry = carry_init(compiled)
    _, vch, vcnt, _ = schedule_scan(config, carry, statics, xs_head)
    vch = np.asarray(vch)
    vcnt = np.asarray(vcnt)
    fch = np.asarray(choices)[:m]
    fcnt = np.asarray(counts)[:m]
    if not np.array_equal(vch, fch):
        return False
    w = min(vcnt.shape[1], fcnt.shape[1])
    return (np.array_equal(vcnt[:, :w], fcnt[:, :w])
            and not vcnt[:, w:].any() and not fcnt[:, w:].any())


def fast_scan(plan: FastPlan, chunk: int = 0,
              interpret: Optional[bool] = None, progress=None,
              start: int = 0, stop: Optional[int] = None,
              carry_in: Optional[FastCarry] = None,
              return_carry: bool = False, fixed_chunk: bool = False):
    """Run pods [start, stop) of the plan; returns (choices, counts,
    advanced) over that span, plus the FastCarry out when return_carry.

    chunk: pods per kernel invocation (TPUSIM_FAST_CHUNK, default 512 — each
    chunk pregathers its signature rows as [chunk, Npad] int32 arrays, so the
    chunk size bounds that transient HBM footprint). interpret=None
    auto-selects interpreter mode off-TPU (tests run on CPU).

    carry_in: resume from an explicit carry (a previous call's carry_out or
    rearm_carry after preemption churn) instead of the plan's initial state.
    fixed_chunk: keep the kernel chunk at exactly `chunk` even when the span
    is shorter — the preemption hybrid's pow2 buckets then reuse one
    compiled kernel per bucket size instead of tracing per tail length
    (ghost padding rows are infeasible everywhere: no carry/rr effect).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not chunk:
        try:
            chunk = int(os.environ.get("TPUSIM_FAST_CHUNK", 512))
        except ValueError:
            chunk = 512
    chunk = max(chunk, 1)
    p = plan.num_pods
    if stop is None:
        stop = p
    span = stop - start
    npad = plan.alloc_cpu.shape[1]
    num_bits = NUM_FIXED_BITS + plan.num_scalars
    counts_w = LANES  # lane-aligned histogram row; decode slices [:num_bits]
    srows = plan.alloc_scalar.shape[0] if plan.num_scalars else 0
    # round the chunk up to a SUBLANES multiple (Mosaic block granularity);
    # tail rows ride the existing GHOST_REQ padding (infeasible everywhere,
    # no carry/rr effect)
    k = -(-(chunk if fixed_chunk else min(chunk, max(span, 1)))
          // SUBLANES) * SUBLANES
    gpad = plan.num_groups
    ipc = ip_const_of(plan)
    mpc = mp_const_of(plan)
    polc = pol_const_of(plan)
    call = _build_call(npad, k, plan.most_requested, num_bits, counts_w,
                       plan.num_scalars, srows, interpret,
                       gpad, plan.n_zone_doms, plan.has_ports,
                       plan.has_disk, plan.has_spread, plan.has_vol_zone,
                       ipc, mpc, polc, plan.policy)

    statics = [jnp.asarray(a) for a in (
        plan.alloc_cpu, plan.alloc_mem, plan.alloc_gpu, plan.alloc_eph,
        plan.allowed, plan.cond_bits, plan.mem_pressure, plan.disk_pressure)]
    tables = [jnp.asarray(a) for a in (
        plan.selector_ok, plan.taint_ok, plan.intolerable,
        plan.aff_count, plan.avoid_score, plan.host_ok)]
    if carry_in is None:
        carry_in = init_carry(plan)
    carry = [jnp.asarray(a) for a in carry_in.rows]
    misc = jnp.asarray(carry_in.misc)
    if plan.num_scalars:
        ascal = jnp.asarray(plan.alloc_scalar)
        scal_carry = jnp.asarray(carry_in.scal)
    if gpad:
        pres_carry = jnp.asarray(carry_in.pres)
        zone_oh = (jnp.asarray(plan.zone_onehot)
                   if plan.has_spread else None)
    if ipc is not None:
        topo_dev = jnp.asarray(plan.topo_rows)
        ip_tbl_dev = jnp.asarray(plan.ipod)
        pd_carry = jnp.asarray(carry_in.pd)
    if mpc is not None:
        vol_tbl_dev = jnp.asarray(plan.vol_tbl)
        uv_carry = jnp.asarray(carry_in.uv)
    zone_tbl = (jnp.asarray(plan.zone_ok_tbl)
                if plan.has_vol_zone else None)
    if polc is not None:
        if polc.has_label:
            ltbl_dev = jnp.asarray(plan.label_tbl)
        if polc.has_prio:
            lprio_dev = jnp.asarray(plan.label_prio_row)
        if polc.has_image:
            img_tbl_dev = jnp.asarray(plan.image_tbl)
        if polc.has_noexec:
            nx_tbl_dev = jnp.asarray(plan.noexec_tbl)
        if polc.has_saa:
            saadom_dev = jnp.asarray(plan.saa_dom_tbl)
        if polc.has_sa:
            saval_dev = jnp.asarray(plan.sa_val_tbl)

    def col(a, fill):
        out = np.full(k, fill, dtype=np.int32)
        out[:a.shape[0]] = a
        return out.reshape(k, 1)

    def grow(a, w=None):
        # per-pod [*, W] group rows for one chunk; ghost rows all-zero
        out = np.zeros((k, w or gpad), dtype=np.int32)
        out[:a.shape[0]] = a
        return out

    # Dispatch chunks ahead of fetching their per-pod outputs: the carry
    # chains device-to-device (out[:7] feed the next call unmaterialized),
    # so jax's async dispatch pipelines the chunk sequence — a synchronous
    # np.asarray per chunk would instead pay one full host<->device round
    # trip per chunk (~0.15s over the axon tunnel; ~29s of pure latency
    # for 100k pods at the default 512 chunk). The pipeline depth is
    # bounded: once more than SYNC_EVERY chunks are in flight, the OLDEST
    # chunk's outputs are materialized to host (freeing its device
    # buffers), so (a) retained HBM stays O(sync_every * chunk), not
    # O(num_pods), (b) the caller's progress/stall watchdog trails real
    # completion by at most sync_every chunks.
    # clamp to >= 1: 0 would silently disable the drain and retain every
    # chunk's output buffers on device for the whole run — O(num_pods) HBM,
    # contradicting the documented O(sync_every * chunk) bound (ADVICE r4)
    sync_every = max(1, int(os.environ.get("TPUSIM_FAST_SYNC_EVERY", "64")))
    results = []   # host triples (choices[n], counts[n,B], adv[n])
    pending = []   # FIFO of (choices_dev, counts_dev, adv_dev, n_real)

    def drain_one():
        och, ocnt, oadv, n_real = pending.pop(0)
        results.append((np.asarray(och)[:n_real, 0],
                        np.asarray(ocnt)[:n_real, :num_bits],
                        np.asarray(oadv)[:n_real, 0] != 0))

    num_chunks = -(-span // k) if span > 0 else 0
    for ci in range(num_chunks):
        sl = slice(start + ci * k, min(start + (ci + 1) * k, stop))
        # ghost padding: infeasible everywhere, no carry/rr effect
        scalars = [
            col(plan.req_cpu[sl], GHOST_REQ), col(plan.req_mem[sl], 0),
            col(plan.req_gpu[sl], 0), col(plan.req_eph[sl], 0),
            col(plan.nz_cpu[sl], 0), col(plan.nz_mem[sl], 0),
            col(plan.zero_request[sl], 0), col(plan.best_effort[sl], 0)]
        ids = [col(plan.sel_id[sl], 0), col(plan.tol_id[sl], 0),
               col(plan.aff_id[sl], 0), col(plan.avoid_id[sl], 0),
               col(plan.host_id[sl], 0)]
        # pregather the signature rows for this chunk (XLA gather, [k, Npad])
        sel_rows = tables[0][ids[0][:, 0]]
        tol_rows = tables[1][ids[1][:, 0]]
        intol_rows = tables[2][ids[1][:, 0]]
        aff_rows = tables[3][ids[2][:, 0]]
        av_rows = tables[4][ids[3][:, 0]]
        host_rows = tables[5][ids[4][:, 0]]
        args = ([jnp.asarray(a) for a in scalars]
                + [sel_rows, tol_rows, intol_rows, aff_rows, av_rows,
                   host_rows]
                + statics + carry + [misc])
        if plan.num_scalars:
            rs = np.zeros((k, LANES), dtype=np.int32)
            rs[:sl.stop - sl.start, :plan.num_scalars] = plan.req_scalar[sl]
            args += [jnp.asarray(rs), ascal, scal_carry]
        if gpad or plan.has_vol_zone or mpc is not None:
            gids = col(plan.gid[sl], 0)
        if plan.has_vol_zone:
            args.append(zone_tbl[gids[:, 0]])
        if mpc is not None:
            args.append(vol_tbl_dev[gids[:, 0]])
            args.append(uv_carry)
        if gpad:
            args.append(jnp.asarray(gids))
            if plan.has_spread:
                args.append(zone_oh)
            args.append(pres_carry)
            if plan.has_ports:
                args.append(jnp.asarray(grow(plan.port_row[sl])))
            if plan.has_disk:
                args.append(jnp.asarray(grow(plan.disk_row[sl])))
            if plan.has_spread:
                args.append(jnp.asarray(grow(plan.ss_row[sl])))
            if ipc is not None:
                args.append(topo_dev)
                # per-pod interpod rows: device gather from the per-group
                # table (pad rows gather row 0 of a zero-padded table;
                # ghost pods are infeasible everywhere regardless)
                args.append(ip_tbl_dev[gids[:, 0]])
                args.append(pd_carry)
        if polc is not None:
            # residue-class policy operands (ghost pods gather row 0 /
            # all-zero rows; they are infeasible everywhere regardless)
            if polc.has_label:
                args.append(ltbl_dev)
            if polc.has_prio:
                args.append(lprio_dev)
            if polc.has_image:
                iid = col(plan.img_id[sl], 0)
                args.append(img_tbl_dev[iid[:, 0]])
            if polc.has_noexec:
                args.append(nx_tbl_dev[ids[1][:, 0]])
            if polc.has_saa:
                args.append(jnp.asarray(grow(plan.saa_row[sl])))
                args.append(saadom_dev)
            if polc.has_sa:
                args.append(jnp.asarray(col(plan.sa_sig[sl], 0)))
                args.append(jnp.asarray(grow(plan.sa_pin_row[sl],
                                             polc.la8)))
                args.append(jnp.asarray(grow(plan.sa_match_row[sl],
                                             polc.fd8)))
                args.append(saval_dev)
        out = call(*args)
        carry = list(out[:7])
        misc = out[7]
        oat = 11
        if plan.num_scalars:
            scal_carry = out[oat]
            oat += 1
        if gpad:
            pres_carry = out[oat]
            oat += 1
        if ipc is not None:
            pd_carry = out[oat]
            oat += 1
        if mpc is not None:
            uv_carry = out[oat]
        pending.append((out[8], out[9], out[10], sl.stop - sl.start))
        if sync_every and len(pending) > sync_every:
            drain_one()
        if progress is not None:
            # dispatch-front progress; completion trails by <= sync_every
            progress(ci + 1, num_chunks, sl.stop)

    while pending:
        drain_one()
    if not results:
        out3 = (np.zeros(0, np.int32), np.zeros((0, num_bits), np.int32),
                np.zeros(0, bool))
    else:
        out3 = (np.concatenate([r[0] for r in results]),
                np.concatenate([r[1] for r in results]),
                np.concatenate([r[2] for r in results]))
    if not return_carry:
        return out3
    carry_out = FastCarry(
        rows=list(carry), misc=misc,
        scal=scal_carry if plan.num_scalars else None,
        pres=pres_carry if gpad else None,
        pd=pd_carry if ipc is not None else None,
        uv=uv_carry if mpc is not None else None)
    return out3 + (carry_out,)

"""Multi-snapshot what-if: batch independent cluster scenarios over the mesh.

BASELINE.json config 5 ("Multi-tenant what-if: 50 concurrent cluster snapshots
× 20k pods each, batched over TPU"). The reference has no analog — each run is
one process over one snapshot; what-if studies mean re-running the binary
(SURVEY.md §5 checkpoint note). Here scenarios are compiled to a common array
shape, stacked on a leading snapshot axis, and dispatched as ONE device
program: vmap over the snapshot axis, with the axis sharded over the mesh's
"snap" dimension (zero cross-snapshot communication — the dp analog) and node
columns over "node" (ICI collectives inserted by GSPMD).

Shape unification:
  * node axis — padded to the common max (and the mesh's node-shard multiple)
    with sentinel-infeasible nodes (sharding.pad_node_axis).
  * signature tables — padded on the signature axis with unreferenced rows.
  * scalar-resource columns — padded to the widest scenario; a scenario's
    reason-bit space stays its own (unused high bits never fire).
  * pod axis — padded with ghost pods whose CPU request exceeds any node
    (infeasible everywhere: no bind scatter, no round-robin advance), dropped
    on decode.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Pod
from tpusim.backends import Placement
from tpusim.jaxe import ensure_x64
from tpusim.jaxe.backend import (
    _KNOWN_PROVIDERS,
    _MOST_REQUESTED_PROVIDERS,
    decode_placements,
)
from tpusim.jaxe.kernels import (
    CARRY_AXES,
    PODX_AXES,
    STATICS_AXES,
    Carry,
    PodX,
    Statics,
    carry_init_host,
    config_for,
    _schedule_scan_impl,
    pod_columns_to_host,
    statics_to_host,
)
from tpusim.jaxe.sharding import (
    mesh_kind,
    pad_node_axis,
    scenario_shardings,
    scenario_specs,
    snap_shardings,
    stage_tree,
)
from tpusim.jaxe.state import NUM_FIXED_BITS, compile_cluster, reason_strings

log = logging.getLogger(__name__)

GHOST_CPU = np.int64(1) << 61  # larger than any allocatable: never feasible

# Trace-time compile tally: the increments below run while jax TRACES a
# program (cache miss), not when a cached executable re-runs — so the delta
# across two calls says whether the second paid a compile. The serve
# executor's warm-cache stamps and the bench config-8 `compile_cache_hit`
# field are both read off this counter.
_COMPILE_COUNTS = {"batched": 0, "scenario_sharded": 0}


def compile_count() -> int:
    """Total what-if program traces this process (see _COMPILE_COUNTS)."""
    return sum(_COMPILE_COUNTS.values())


@partial(jax.jit, static_argnames=("config",))
def _batched(config, carries, statics_b, xs_b):
    """vmap of the exact scan over the scenario axis, jitted at module level
    so jax's compile cache persists across run_what_if invocations: repeated
    what-if studies with matching shapes+config skip the (minutes-long on
    TPU) XLA compile that dominates a cold call (BASELINE.md config 5)."""
    _COMPILE_COUNTS["batched"] += 1

    def one(carry, st, xs):
        _, choices, counts, _adv = _schedule_scan_impl(config, carry, st, xs)
        return choices, counts

    return jax.vmap(one)(carries, statics_b, xs_b)


# (config, mesh) -> jitted shard_map program. jax's jit cache would dedupe
# the executables anyway; this dict also dedupes the shard_map/closure
# CONSTRUCTION and gives the serve executor a stable identity to key its
# warm-cache bookkeeping on.
_SCENARIO_PROGRAMS: dict = {}


def _scenario_program(config, mesh):
    """The manual shard_map route: scenarios partitioned over the mesh's
    "scenario" axis, node columns whole per shard (make_scenario_mesh).
    Cross-scenario communication is impossible by construction — each shard
    runs the vmap-of-scan on its own scenario slice. check_rep=False because
    out_specs carry no replicated axes to prove."""
    fn = _SCENARIO_PROGRAMS.get((config, mesh))
    if fn is None:
        from jax.experimental.shard_map import shard_map

        ca_spec, st_spec, xs_spec = scenario_specs()

        def local(carries, statics_b, xs_b):
            _COMPILE_COUNTS["scenario_sharded"] += 1

            def one(carry, st, xs):
                _, choices, counts, _adv = _schedule_scan_impl(
                    config, carry, st, xs)
                return choices, counts

            return jax.vmap(one)(carries, statics_b, xs_b)

        from jax.sharding import PartitionSpec as P

        fn = jax.jit(shard_map(
            local, mesh=mesh, in_specs=(ca_spec, st_spec, xs_spec),
            out_specs=(P("scenario"), P("scenario")), check_rep=False))
        _SCENARIO_PROGRAMS[(config, mesh)] = fn
    return fn


@dataclass
class WhatIfResult:
    """Per-scenario outcome."""

    placements: List[Placement]
    scheduled: int
    unschedulable: int

    @property
    def total(self) -> int:
        return self.scheduled + self.unschedulable


def _pad_axis(a: np.ndarray, axis: int, target: int, fill=0) -> np.ndarray:
    pad = target - a.shape[axis]
    if pad <= 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return np.pad(a, widths, constant_values=fill)


def _axis_targets(host_trees) -> dict:
    """Max size per named (non-node) axis across scenarios, derived from the
    kernels axis registries — new state fields unify automatically."""
    targets: dict = {}
    for statics, carry, xs in host_trees:
        trees = [(statics, STATICS_AXES, 0), (carry, CARRY_AXES, 0),
                 (xs, PODX_AXES, 1)]
        for tree, axes_map, offset in trees:
            for name, arr in tree._asdict().items():
                for i, axis in enumerate(axes_map[name]):
                    if axis == "node":
                        continue
                    size = np.asarray(arr).shape[i + offset]
                    targets[axis] = max(targets.get(axis, 0), size)
    return targets


def _unify_tree(tree, axes_map, targets: dict, axis_offset: int = 0):
    fields = {}
    for name, arr in tree._asdict().items():
        arr = np.asarray(arr)
        for i, axis in enumerate(axes_map[name]):
            if axis == "node":
                continue
            arr = _pad_axis(arr, i + axis_offset, targets[axis])
        fields[name] = arr
    return fields


def _unify(statics: Statics, carry: Carry, xs: PodX, targets: dict,
           p_max: int) -> Tuple[Statics, Carry, PodX]:
    """Pad signature / scalar / pod axes to the common shape (host-side)."""
    st_fields = _unify_tree(statics, STATICS_AXES, targets)
    ca_fields = _unify_tree(carry, CARRY_AXES, targets)

    p = np.asarray(xs.req_cpu).shape[0]
    fields = _unify_tree(xs, PODX_AXES, targets, axis_offset=1)
    fields = {k: _pad_axis(v, 0, p_max) for k, v in fields.items()}
    if p_max > p:
        # ghost pods: infeasible everywhere, never advance rr or bind
        fields["req_cpu"] = fields["req_cpu"].copy()
        fields["req_cpu"][p:] = GHOST_CPU
        fields["zero_request"] = fields["zero_request"].copy()
        fields["zero_request"][p:] = False
    # stays on host: the single device upload happens after scenario stacking
    return Statics(**st_fields), Carry(**ca_fields), PodX(**fields)


def _policy_prep(policy, hard_pod_affinity_symmetric_weight: int):
    """Compile the batch-wide policy once: (cp, need_noexec, need_saa,
    hard_weight). Shared by run_what_if and the serve executor (which keys
    its warm-executable cache on cp.spec — the what-if analog of the fast
    path's plan_signature)."""
    cp = None
    if policy is not None:
        from tpusim.jaxe.policyc import compile_policy

        cp = compile_policy(policy)
        if cp.unsupported:
            detail = "; ".join(sorted(set(cp.unsupported))[:5])
            raise NotImplementedError(
                "what-if batching requires a jax-compilable policy; "
                f"host-bound: {detail}")
        if cp.hard_weight is not None:
            hard_pod_affinity_symmetric_weight = cp.hard_weight
    from tpusim.engine.predicates import (
        POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
    )

    need_noexec = (cp is not None and cp.spec.pred_keys is not None
                   and POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED
                   in cp.spec.pred_keys)
    need_saa = cp is not None and (bool(cp.spec.saa_weights)
                                   or cp.spec.sa_enabled)
    return cp, need_noexec, need_saa, hard_pod_affinity_symmetric_weight


@dataclass
class StagedScenario:
    """One scenario compiled to host trees, ready to batch (run_what_if) or
    bucket (tpusim.serve): the unit the serve snapshot cache stores."""

    compiled: object
    cols: object
    statics: Statics
    carry: Carry
    xs: PodX
    ptabs: object
    n_saa_doms: int


def _stage_scenario(snapshot: ClusterSnapshot, pods: List[Pod], cp,
                    need_noexec: bool, need_saa: bool) -> StagedScenario:
    """Host-stage one (snapshot, pods) scenario: compile_cluster + policy
    tables + host trees. Raises ValueError for a zero-node snapshot (there
    is no node axis to pad onto) and NotImplementedError for scenarios the
    device engine can't express."""
    if not snapshot.nodes:
        raise ValueError(
            "what-if scenario has a zero-node snapshot: nothing can "
            "schedule; run scenarios against at least one node")
    compiled, cols = compile_cluster(snapshot, pods, need_noexec=need_noexec,
                                     need_saa=need_saa)
    if compiled.unsupported:
        detail = "; ".join(sorted(set(compiled.unsupported))[:5])
        raise NotImplementedError(
            "what-if batching requires jax-compilable scenarios; "
            f"unsupported: {detail} (run this scenario on the reference "
            "backend instead)")
    host_statics = statics_to_host(compiled)
    host_carry = carry_init_host(compiled)
    ptabs = None
    n_saa_doms = 1
    if cp is not None:
        # one build per scenario feeds the vmap statics AND the fast
        # loop's plan (the trivial PolicyTables shapes match
        # statics_to_host / carry_init_host, so unconditional replace
        # is byte-identical for features the policy lacks)
        from tpusim.jaxe.policyc import build_policy_tables

        ptabs = build_policy_tables(cp, snapshot, pods, compiled, cols)
        host_statics = host_statics._replace(
            label_ok=ptabs.label_ok, label_prio=ptabs.label_prio,
            image_score=ptabs.image_score, saa_dom=ptabs.saa_dom,
            sa_pin=ptabs.sa_pin, sa_val=ptabs.sa_val)
        host_carry = host_carry._replace(sa_lock=ptabs.sa_lock_init)
        n_saa_doms = ptabs.n_saa_doms
    return StagedScenario(compiled=compiled, cols=cols, statics=host_statics,
                          carry=host_carry, xs=pod_columns_to_host(cols),
                          ptabs=ptabs, n_saa_doms=n_saa_doms)


def batch_config(compiled_list, provider: str, cp, hard_weight: int,
                 n_saa_doms: int, num_scalars: Optional[int] = None):
    """EngineConfig for a batch of compiled scenarios. num_scalars widens
    the reason-bit space beyond the batch's own max (the serve executor
    pins it to the shape class's scalar budget so every bucket of a class
    traces one program; unused high bits never fire)."""
    s_max = max(len(c.scalar_names) for c in compiled_list)
    if num_scalars is not None:
        s_max = max(s_max, num_scalars)
    config = config_for(
        compiled_list,
        most_requested=provider in _MOST_REQUESTED_PROVIDERS,
        num_reason_bits=NUM_FIXED_BITS + s_max,
        hard_weight=hard_weight)
    if cp is not None:
        from dataclasses import replace as _dc_replace

        config = _dc_replace(config, policy=cp.spec, n_saa_doms=n_saa_doms)
    return config


def _prepare_host_batch(scenarios, provider: str,
                        hard_pod_affinity_symmetric_weight: int, policy):
    """Compile the batch on host numpy (shape unification is deferred:
    `_unify_batch` pads the returned host_trees for the vmap program — the
    Pallas fast loop consumes the per-scenario compiled state directly and
    must not pay for padding it would throw away).

    Returns (config, host_trees, compiled_list, ptabs_list) — ptabs_list
    holds each scenario's PolicyTables (None without a policy) for the fast
    loop's planner. Raises ValueError for input shapes that cannot batch
    (empty scenario list, zero-node snapshots) — clear host-side errors
    instead of a failure inside jit.
    """
    if provider not in _KNOWN_PROVIDERS:
        raise KeyError(f"plugin {provider!r} has not been registered")
    if not scenarios:
        raise ValueError(
            "run_what_if needs at least one (snapshot, pods) scenario")
    cp, need_noexec, need_saa, hard_weight = _policy_prep(
        policy, hard_pod_affinity_symmetric_weight)
    ensure_x64()

    staged: List[StagedScenario] = []
    for i, (snapshot, pods) in enumerate(scenarios):
        try:
            staged.append(_stage_scenario(snapshot, pods, cp,
                                          need_noexec, need_saa))
        except ValueError as exc:
            raise ValueError(f"scenario {i}: {exc}") from None

    host_trees = [(s.statics, s.carry, s.xs) for s in staged]
    compiled_list = [(s.compiled, s.cols) for s in staged]
    ptabs_list = [s.ptabs for s in staged]
    config = batch_config(
        [s.compiled for s in staged], provider, cp, hard_weight,
        n_saa_doms=max(s.n_saa_doms for s in staged))
    return config, host_trees, compiled_list, ptabs_list


def _unify_batch(host_trees, n_snap_shards: int, n_node_shards: int):
    """Shape-unify + pad the compiled host trees for the batched vmap
    program; returns per_scenario (carry, statics, xs) tuples padded to
    the snap-shard multiple."""
    targets = _axis_targets(host_trees)
    p_max = max(np.asarray(xs.req_cpu).shape[0] for _, _, xs in host_trees)
    n_max = max(s.alloc_cpu.shape[0] for s, _, _ in host_trees)
    # one pad target: max nodes rounded up to the node-shard multiple
    n_target = -(-n_max // n_node_shards) * n_node_shards

    per_scenario = []
    for statics, carry, xs in host_trees:
        statics, carry, xs = _unify(statics, carry, xs, targets, p_max)
        statics, carry, _ = pad_node_axis(statics, carry, n_target)
        per_scenario.append((carry, statics, xs))

    # pad the scenario axis to the snap-shard multiple with replicas
    while len(per_scenario) % n_snap_shards != 0:
        per_scenario.append(per_scenario[0])
    return per_scenario


def _stack_host(per_scenario):
    """Stacked host-numpy trees (carries, statics_b, xs_b)."""
    stack = lambda trees: jax.tree.map(  # noqa: E731
        lambda *a: np.stack([np.asarray(x) for x in a]), *trees)
    return (stack([t[0] for t in per_scenario]),
            stack([t[1] for t in per_scenario]),
            stack([t[2] for t in per_scenario]))


def decode_one(pods: List[Pod], compiled, choices, counts) -> WhatIfResult:
    """Decode one scenario's device outputs back to placements (shared with
    the serve executor, whose buckets decode only their REAL entries — ghost
    scenarios and pod-axis padding never reach here)."""
    placements, scheduled = decode_placements(
        pods, choices, counts, compiled.statics.names,
        reason_strings(compiled.scalar_names))
    return WhatIfResult(placements=placements, scheduled=scheduled,
                        unschedulable=len(pods) - scheduled)


def _decode_batch(scenarios, compiled_list, choices_b,
                  counts_b) -> List[WhatIfResult]:
    # the batch may be longer than the scenario list (scenario-axis padding
    # replicas); iterate the real scenarios only
    return [decode_one(scenarios[b][1], compiled_list[b][0], choices_b[b],
                       counts_b[b])
            for b in range(len(scenarios))]


def _try_fast_loop(scenarios, config, compiled_list, ptabs_list, host_trees):
    """Run every scenario through the Pallas fast path sequentially;
    returns the decoded results, or None to fall back to the batched vmap
    program (ineligible scenario, fast path off/disabled, kernel failure,
    or a failed AUTO self-verification)."""
    from tpusim.framework.metrics import register
    from tpusim.jaxe.backend import (
        _FAST_AUTO,
        _auto_verify_and_pin,
        _fast_path_enabled,
        _note_fast_failure,
        _note_fast_fallback,
        plan_signature,
    )
    from tpusim.jaxe.fastscan import fast_scan, plan_fast

    fast_on, auto_mode = _fast_path_enabled()
    if not fast_on:
        return None
    plans = []
    for b, (compiled, cols) in enumerate(compiled_list):
        plan, why = plan_fast(config, compiled, cols,
                              ptabs=ptabs_list[b])
        if plan is None:
            _note_fast_fallback(register(), why)
            log.info("what-if fast loop ineligible (scenario %d: %s); "
                     "using the batched vmap program", b, why)
            return None
        plans.append(plan)
    choices_list = []
    counts_list = []
    for b, plan in enumerate(plans):
        try:
            choices, counts, _adv = fast_scan(plan)
        except Exception as exc:
            log.warning("what-if fast loop failed (%s: %s); falling back "
                        "to the batched vmap program",
                        type(exc).__name__, exc)
            _note_fast_failure(exc)
            return None
        _FAST_AUTO["transient"] = 0
        sig = plan_signature(plan)
        if auto_mode and sig not in _FAST_AUTO["verified_sigs"]:
            # every scenario verifies until its kernel variant is trusted —
            # a small scenario 0 passing trivially must not exempt the rest
            # of the batch (trust pins only at TPUSIM_FAST_VERIFY_MIN+ pods)
            compiled, cols = compiled_list[b]
            # replay against the same policy-grafted statics/carry the
            # batched vmap program would use for this scenario
            from tpusim.jaxe.kernels import _tree_to_device

            hs, hc, _ = host_trees[b]
            if not _auto_verify_and_pin(
                    config, compiled, cols, choices, counts, sig,
                    statics=_tree_to_device(hs), carry=hc):
                return None
        choices_list.append(choices)
        counts_list.append(counts)
    return _decode_batch(scenarios, compiled_list, choices_list, counts_list)


def run_what_if(scenarios: Sequence[Tuple[ClusterSnapshot, List[Pod]]],
                provider: str = "DefaultProvider",
                mesh: Optional[object] = None,
                hard_pod_affinity_symmetric_weight: int = 10,
                policy=None) -> List[WhatIfResult]:
    """Run independent (snapshot, pods) scenarios as one batched device
    program. Pods are fed in podspec order (callers wanting reference LIFO
    parity pass the reversed list, as run_simulation does).

    mesh: an optional jax.sharding.Mesh; None runs single-device. A
    ("snap", "node") mesh (sharding.make_mesh) runs the GSPMD route: the
    scenario axis sharded over "snap", node columns over "node" with XLA
    collectives. A ("scenario", "node") mesh (sharding.make_scenario_mesh)
    runs the manual shard_map route: scenarios partitioned with node columns
    whole per shard — the serving shape, where scenario throughput is the
    axis that matters. Any other axis names raise ValueError. The scenario
    count need not divide the scenario/snap axis — the batch is padded with
    a replica of the first scenario and the padding dropped on decode.

    policy: an engine.policy.Policy applied to EVERY scenario (one jitted
    program serves the batch, so the policy is batch-wide); host-bound policy
    features raise — what-if has no per-scenario host fallback.

    Raises ValueError for inputs that cannot batch — empty scenario list,
    zero-node snapshots, unknown mesh axes — before anything reaches jit.
    """
    # mesh validation runs BEFORE any host prep or staging: axis names via
    # mesh_kind, then device membership — a mesh built over devices this
    # process can't see used to surface as an opaque device_put failure
    # after the whole batch was already unified and stacked
    kind = mesh_kind(mesh) if mesh is not None else None
    if mesh is not None:
        visible = set(jax.devices())
        missing = [d for d in mesh.devices.flat if d not in visible]
        if missing:
            raise ValueError(
                f"what-if mesh spans {len(missing)} device(s) not visible "
                f"to this process (e.g. {missing[0]}); rebuild the mesh "
                "from jax.devices()")
    n_snap_shards = 1 if mesh is None else (
        mesh.shape["snap"] if kind == "snap" else mesh.shape["scenario"])
    # the shard_map route keeps node columns whole per shard: no node pad
    n_node_shards = mesh.shape["node"] if kind == "snap" else 1
    config, host_trees, compiled_list, ptabs_list = _prepare_host_batch(
        scenarios, provider, hard_pod_affinity_symmetric_weight, policy)

    if mesh is None:
        # Pallas fast loop: per-scenario kernels instead of the single
        # vmap(S)xscan(P) program, whose XLA compile alone costs ~2min at
        # the 50x20k BASELINE config-5 shape. Engages only when EVERY
        # scenario is fast-eligible and the fast path is on for this
        # process (AUTO on TPU, sharing the backend's self-verification
        # state); anything else keeps the batched program. Runs BEFORE the
        # shape unification below, which the fast loop never needs.
        fast = _try_fast_loop(scenarios, config, compiled_list, ptabs_list,
                              host_trees)
        if fast is not None:
            return fast

    per_scenario = _unify_batch(host_trees, n_snap_shards, n_node_shards)
    host_carries, host_statics, host_xs = _stack_host(per_scenario)
    if mesh is not None:
        # sharded upload straight from host numpy — materializing on the
        # default device first would double the transfer and peak memory
        if kind == "snap":
            st_spec, ca_spec, xs_spec = snap_shardings(mesh)
        else:
            ca_spec, st_spec, xs_spec = scenario_shardings(mesh)
        xs_b = stage_tree(host_xs, xs_spec)
        carries = stage_tree(host_carries, ca_spec)
        statics_b = stage_tree(host_statics, st_spec)
    else:
        carries, statics_b, xs_b = (stage_tree(host_carries),
                                    stage_tree(host_statics),
                                    stage_tree(host_xs))

    if kind == "scenario":
        choices_b, counts_b = _scenario_program(config, mesh)(
            carries, statics_b, xs_b)
        choices_b = np.asarray(choices_b)
    elif kind == "snap":
        with mesh:
            choices_b, counts_b = _batched(config, carries, statics_b, xs_b)
            choices_b = np.asarray(choices_b)
    else:
        choices_b, counts_b = _batched(config, carries, statics_b, xs_b)
        choices_b = np.asarray(choices_b)
    counts_b = np.asarray(counts_b)
    return _decode_batch(scenarios, compiled_list, choices_b, counts_b)


def run_what_if_multihost(scenarios: Sequence[Tuple[ClusterSnapshot, List[Pod]]],
                          provider: str = "DefaultProvider",
                          hard_pod_affinity_symmetric_weight: int = 10,
                          policy=None) -> List[WhatIfResult]:
    """Multi-process what-if: one global batched program over every
    participating host's devices (the DCN analog — SURVEY.md §5
    "distributed communication backend").

    EVERY process (after `jax.distributed.initialize`) calls this with an
    IDENTICAL, deterministically-built scenario list. The global
    ("snap", "node") mesh puts one snap shard per process (scenarios are
    data-parallel across hosts; node columns shard across each host's local
    devices), array shards are placed via `jax.make_array_from_callback`
    (host data is replicated, placement is distributed), and the results
    are replicated back so every process decodes the full batch.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from tpusim.jaxe.sharding import make_mesh

    nproc = jax.process_count()
    n_node = jax.local_device_count()
    config, host_trees, compiled_list, _ptabs_list = _prepare_host_batch(
        scenarios, provider, hard_pod_affinity_symmetric_weight, policy)
    per_scenario = _unify_batch(host_trees, n_snap_shards=nproc,
                                n_node_shards=n_node)

    # jax.devices() orders process 0's devices first, then process 1's, ...
    # so reshaping to (nproc, n_node) gives each process its own snap row
    mesh = make_mesh(nproc * n_node, snap=nproc)
    st_spec, ca_spec, xs_spec = snap_shardings(mesh)
    host_carries, host_statics, host_xs = _stack_host(per_scenario)

    def _global(full, sharding):
        return jax.make_array_from_callback(
            full.shape, sharding, lambda idx: full[idx])

    carries = jax.tree.map(_global, host_carries, ca_spec)
    statics_b = jax.tree.map(_global, host_statics, st_spec)
    xs_b = jax.tree.map(lambda a: _global(a, xs_spec), host_xs)

    replicate = jax.jit(lambda x: x,
                         out_shardings=NamedSharding(mesh, PartitionSpec()))
    with mesh:
        choices_b, counts_b = _batched(config, carries, statics_b, xs_b)
        # fully replicated -> every shard addressable on every process
        choices_b = np.asarray(replicate(choices_b))
        counts_b = np.asarray(replicate(counts_b))
    return _decode_batch(scenarios, compiled_list, choices_b, counts_b)

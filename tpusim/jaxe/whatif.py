"""Multi-snapshot what-if: batch independent cluster scenarios over the mesh.

BASELINE.json config 5 ("Multi-tenant what-if: 50 concurrent cluster snapshots
× 20k pods each, batched over TPU"). The reference has no analog — each run is
one process over one snapshot; what-if studies mean re-running the binary
(SURVEY.md §5 checkpoint note). Here scenarios are compiled to a common array
shape, stacked on a leading snapshot axis, and dispatched as ONE device
program: vmap over the snapshot axis, with the axis sharded over the mesh's
"snap" dimension (zero cross-snapshot communication — the dp analog) and node
columns over "node" (ICI collectives inserted by GSPMD).

Shape unification:
  * node axis — padded to the common max (and the mesh's node-shard multiple)
    with sentinel-infeasible nodes (sharding.pad_node_axis).
  * signature tables — padded on the signature axis with unreferenced rows.
  * scalar-resource columns — padded to the widest scenario; a scenario's
    reason-bit space stays its own (unused high bits never fire).
  * pod axis — padded with ghost pods whose CPU request exceeds any node
    (infeasible everywhere: no bind scatter, no round-robin advance), dropped
    on decode.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Pod
from tpusim.backends import Placement, bind_pod, mark_unschedulable
from tpusim.jaxe import ensure_x64
from tpusim.jaxe.backend import (
    _KNOWN_PROVIDERS,
    _MOST_REQUESTED_PROVIDERS,
    format_fit_error,
)
from tpusim.jaxe.kernels import (
    Carry,
    EngineConfig,
    PodX,
    Statics,
    carry_init,
    make_step,
    pod_columns_to_device,
    statics_to_device,
)
from tpusim.jaxe.sharding import pad_node_axis, snap_shardings
from tpusim.jaxe.state import NUM_FIXED_BITS, compile_cluster, reason_strings

GHOST_CPU = np.int64(1) << 61  # larger than any allocatable: never feasible


@dataclass
class WhatIfResult:
    """Per-scenario outcome."""

    placements: List[Placement]
    scheduled: int
    unschedulable: int

    @property
    def total(self) -> int:
        return self.scheduled + self.unschedulable


def _pad_axis(a: np.ndarray, axis: int, target: int, fill=0) -> np.ndarray:
    pad = target - a.shape[axis]
    if pad <= 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return np.pad(a, widths, constant_values=fill)


def _unify(statics: Statics, carry: Carry, xs: PodX, sig_max: dict,
           s_max: int, p_max: int) -> Tuple[Statics, Carry, PodX]:
    """Pad signature / scalar / pod axes to the common shape (host-side)."""
    st = statics._replace(
        alloc_scalar=jnp.asarray(_pad_axis(np.asarray(statics.alloc_scalar), 1, s_max)),
        selector_ok=jnp.asarray(_pad_axis(np.asarray(statics.selector_ok), 0,
                                          sig_max["sel"])),
        taint_ok=jnp.asarray(_pad_axis(np.asarray(statics.taint_ok), 0,
                                       sig_max["tol"])),
        intolerable=jnp.asarray(_pad_axis(np.asarray(statics.intolerable), 0,
                                          sig_max["tol"])),
        affinity_count=jnp.asarray(_pad_axis(np.asarray(statics.affinity_count), 0,
                                             sig_max["aff"])),
        avoid_score=jnp.asarray(_pad_axis(np.asarray(statics.avoid_score), 0,
                                          sig_max["avoid"])),
        host_ok=jnp.asarray(_pad_axis(np.asarray(statics.host_ok), 0,
                                      sig_max["host"])))
    ca = carry._replace(
        used_scalar=jnp.asarray(_pad_axis(np.asarray(carry.used_scalar), 1, s_max)))

    p = xs.req_cpu.shape[0]
    fields = {}
    for name, arr in xs._asdict().items():
        arr = np.asarray(arr)
        if name == "req_scalar":
            arr = _pad_axis(arr, 1, s_max)
        fields[name] = _pad_axis(arr, 0, p_max)
    if p_max > p:
        # ghost pods: infeasible everywhere, never advance rr or bind
        fields["req_cpu"][p:] = GHOST_CPU
        fields["zero_request"][p:] = False
    return st, ca, PodX(**{k: jnp.asarray(v) for k, v in fields.items()})


def run_what_if(scenarios: Sequence[Tuple[ClusterSnapshot, List[Pod]]],
                provider: str = "DefaultProvider",
                mesh: Optional[object] = None) -> List[WhatIfResult]:
    """Run independent (snapshot, pods) scenarios as one batched device
    program. Pods are fed in podspec order (callers wanting reference LIFO
    parity pass the reversed list, as run_simulation does).

    mesh: an optional ("snap", "node") jax.sharding.Mesh (sharding.make_mesh);
    None runs single-device. The scenario count need not divide the snap axis —
    the batch is padded with a replica of the first scenario and the padding
    dropped on decode.
    """
    if provider not in _KNOWN_PROVIDERS:
        raise KeyError(f"plugin {provider!r} has not been registered")
    if not scenarios:
        return []
    ensure_x64()

    compiled_list = []
    for snapshot, pods in scenarios:
        compiled, cols = compile_cluster(snapshot, pods)
        if compiled.unsupported:
            detail = "; ".join(sorted(set(compiled.unsupported))[:5])
            raise NotImplementedError(
                "what-if batching requires jax-compilable scenarios; "
                f"unsupported: {detail} (run this scenario on the reference "
                "backend instead)")
        compiled_list.append((compiled, cols))

    n_snap_shards = mesh.shape["snap"] if mesh is not None else 1
    n_node_shards = mesh.shape["node"] if mesh is not None else 1

    # common shapes
    sig_max = {
        "sel": max(c.tables.selector_ok.shape[0] for c, _ in compiled_list),
        "tol": max(c.tables.taint_ok.shape[0] for c, _ in compiled_list),
        "aff": max(c.tables.affinity_count.shape[0] for c, _ in compiled_list),
        "avoid": max(c.tables.avoid_score.shape[0] for c, _ in compiled_list),
        "host": max(c.tables.host_ok.shape[0] for c, _ in compiled_list),
    }
    s_max = max(len(c.scalar_names) for c, _ in compiled_list)
    p_max = max(len(pods) for _, pods in scenarios)
    n_max = max(c.statics.alloc_cpu.shape[0] for c, _ in compiled_list)
    # one pad target: max nodes rounded up to the node-shard multiple
    n_target = -(-n_max // n_node_shards) * n_node_shards

    per_scenario = []
    for compiled, cols in compiled_list:
        statics = statics_to_device(compiled)
        carry = carry_init(compiled)
        statics, carry, xs = _unify(statics, carry, pod_columns_to_device(cols),
                                    sig_max, s_max, p_max)
        statics, carry, _ = pad_node_axis(statics, carry, n_target)
        per_scenario.append((carry, statics, xs))

    # pad the scenario axis to the snap-shard multiple with replicas
    real_count = len(per_scenario)
    while len(per_scenario) % n_snap_shards != 0:
        per_scenario.append(per_scenario[0])

    stack = lambda trees: jax.tree.map(lambda *a: jnp.stack(a), *trees)  # noqa: E731
    carries = stack([t[0] for t in per_scenario])
    statics_b = stack([t[1] for t in per_scenario])
    xs_b = stack([t[2] for t in per_scenario])

    if mesh is not None:
        st_spec, ca_spec, xs_spec = snap_shardings(mesh)
        carries = jax.tree.map(jax.device_put, carries, ca_spec)
        statics_b = jax.tree.map(jax.device_put, statics_b, st_spec)
        xs_b = jax.tree.map(lambda a: jax.device_put(a, xs_spec), xs_b)

    config = EngineConfig(
        most_requested=provider in _MOST_REQUESTED_PROVIDERS,
        num_reason_bits=NUM_FIXED_BITS + s_max)
    step = make_step(config)

    @jax.jit
    def batched(carries, statics_b, xs_b):
        def one(carry, st, xs):
            (final_carry, _), (choices, counts) = jax.lax.scan(
                step, (carry, st), xs)
            return choices, counts
        return jax.vmap(one)(carries, statics_b, xs_b)

    if mesh is not None:
        with mesh:
            choices_b, counts_b = batched(carries, statics_b, xs_b)
            choices_b = np.asarray(choices_b)
    else:
        choices_b, counts_b = batched(carries, statics_b, xs_b)
        choices_b = np.asarray(choices_b)
    counts_b = np.asarray(counts_b)

    results: List[WhatIfResult] = []
    for i in range(real_count):
        compiled, _ = compiled_list[i]
        _, pods = scenarios[i]
        names = compiled.statics.names
        strings = reason_strings(compiled.scalar_names)
        placements: List[Placement] = []
        scheduled = 0
        for j, pod in enumerate(pods):
            c = int(choices_b[i, j])
            if c >= 0:
                scheduled += 1
                placements.append(Placement(pod=bind_pod(pod, names[c]),
                                            node_name=names[c]))
            else:
                msg = format_fit_error(len(names), counts_b[i, j], strings)
                placements.append(Placement(pod=mark_unschedulable(pod, msg),
                                            reason="Unschedulable", message=msg))
        results.append(WhatIfResult(placements=placements, scheduled=scheduled,
                                    unschedulable=len(pods) - scheduled))
    return results

"""Packed int64 ordering keys shared by the selection/top-k kernels.

Three call sites historically re-implemented the same encoding — the
analytics top-k (`kernels._analytics_reduce_impl` + its numpy mirror in
obs/analytics.py), the gang rank key (`kernels._gang_select_impl` + the
numpy oracle in gang/oracle.py), and now the cross-shard top-k merge.
One drifted shift constant would silently break device-vs-host bit parity,
so the encode/decode lives here once and every mirror imports it.

All helpers are arithmetic-only (shifts, masks, method-form `astype`/
`clip`) so the SAME source line evaluates identically over numpy arrays
and jax tracers — the host mirrors are bit-exact by construction, not by
careful duplication. Invalid lanes encode as -1, strictly below every
valid key (valid keys are nonnegative), so masked argmax/top_k never
selects one.

Tie-break contract (property-locked by tests/test_packing.py): keys are
unique per index, and a HIGHER key means (better score, then LOWER index).
Descending top-k of encoded keys therefore equals a stable descending
sort over (score, first-occurrence), and argmax picks the first index
among score ties — matching numpy's and XLA's first-occurrence argmax.
"""

from __future__ import annotations

# score occupies the high bits; the low TIE_BITS hold the inverted index
# tiebreak. Node/index counts must stay below 2**TIE_BITS (4.3B — far above
# the 100k-node north star).
TIE_BITS = 32
TIE_MASK = (1 << TIE_BITS) - 1

# Gang rank-key layout: zone-mate count, then rack-mate count, then the
# clipped scan score; first-occurrence argmax resolves remaining ties.
GANG_ZONE_SHIFT = 52
GANG_RACK_SHIFT = 32
GANG_SCORE_MASK = (1 << 32) - 1


def encode_topk_keys(score, index, valid):
    """``(score << TIE_BITS) | (TIE_MASK - index)`` where valid, else -1.

    `score` int64 in [0, 2**(63-TIE_BITS)); `index` int64 in [0, TIE_MASK];
    `valid` bool. Works elementwise on numpy arrays and jax tracers alike.
    Every valid key is unique (the index term) and nonnegative, so top-k
    over keys is a total order and -1 sentinels sort last."""
    key = (score << TIE_BITS) | (TIE_MASK - index)
    v = valid.astype(key.dtype)
    return v * key - (1 - v)


def decode_topk_key(key):
    """Inverse of `encode_topk_keys` for valid keys: (score, index)."""
    return key >> TIE_BITS, TIE_MASK - (key & TIE_MASK)


def encode_gang_rank(zone_bonus, rack_bonus, score, ok):
    """The gang packer's int64 rank key: zone mates, then rack mates, then
    the clipped score; -1 where `ok` is false. `score` must be int64; the
    bonuses are small nonnegative counts (< 2**11 zone, < 2**20 rack)."""
    rank = ((zone_bonus.astype(score.dtype) << GANG_ZONE_SHIFT)
            + (rack_bonus.astype(score.dtype) << GANG_RACK_SHIFT)
            + score.clip(0, GANG_SCORE_MASK))
    v = ok.astype(score.dtype)
    return v * rank - (1 - v)

"""Columnar cluster state: SoA arrays + signature interning + static tables.

Reference mapping (SURVEY.md §7 step 2): NodeInfo's cached aggregates
(schedulercache/node_info.go:35-76) become per-node column vectors; the
symbolic pod features become interned signature ids with precompiled
[signature, node] tables (see tpusim/jaxe/__init__.py design note).
"""

from __future__ import annotations

from dataclasses import dataclass, field
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import (
    LABEL_HOSTNAME,
    TAINT_PREFER_NO_SCHEDULE,
    Node,
    Pod,
    find_matching_untolerated_taint,
    tolerations_tolerate_taint,
)
from tpusim.engine.predicates import (
    _ZONE_LABELS,
    DEFAULT_MAXPD_LIMITS,
    effective_maxpd_limits,
    get_namespaces_from_pod_affinity_term,
    get_pod_affinity_terms,
    get_pod_anti_affinity_terms,
    pod_matches_node_labels,
    pod_matches_term_namespace_and_selector,
)
from tpusim.engine.priorities import (
    calculate_node_affinity_priority_map,
    calculate_node_prefer_avoid_pods_priority_map,
    get_zone_key,
)
from tpusim.engine.resources import (
    NodeInfo,
    get_nonzero_pod_request,
    get_resource_request,
    is_pod_best_effort,
)

# ---------------------------------------------------------------------------
# failure reason bit layout (decoded back to error.go strings for the report)
# ---------------------------------------------------------------------------

BIT_NODE_NOT_READY = 0
BIT_NODE_OUT_OF_DISK = 1
BIT_NODE_NETWORK_UNAVAILABLE = 2
BIT_NODE_UNSCHEDULABLE = 3
BIT_INSUFFICIENT_PODS = 4
BIT_INSUFFICIENT_CPU = 5
BIT_INSUFFICIENT_MEMORY = 6
BIT_INSUFFICIENT_GPU = 7
BIT_INSUFFICIENT_EPHEMERAL = 8
BIT_HOSTNAME_MISMATCH = 9
BIT_NODE_SELECTOR_MISMATCH = 10
BIT_TAINTS_NOT_TOLERATED = 11
BIT_MEMORY_PRESSURE = 12
BIT_DISK_PRESSURE = 13
BIT_HOST_PORTS = 14
BIT_AFFINITY_NOT_MATCH = 15     # MatchInterPodAffinity umbrella reason
BIT_EXISTING_ANTI_AFFINITY = 16
BIT_AFFINITY_RULES = 17
BIT_ANTI_AFFINITY_RULES = 18
BIT_DISK_CONFLICT = 19          # NoDiskConflict (error.go ErrDiskConflict)
BIT_MAX_VOLUME_COUNT = 20       # MaxPDVolumeCount
BIT_VOLUME_ZONE_CONFLICT = 21   # NoVolumeZoneConflict
BIT_NODE_LABEL_PRESENCE = 22    # CheckNodeLabelPresence (policy-configured)
BIT_SERVICE_AFFINITY = 23       # CheckServiceAffinity (policy-configured)
NUM_FIXED_BITS = 24
# bits >= NUM_FIXED_BITS: Insufficient <scalar resource s>, per interned name

REASON_STRINGS = [
    "node(s) were not ready",
    "node(s) were out of disk space",
    "node(s) had unavailable network",
    "node(s) were unschedulable",
    "Insufficient pods",
    "Insufficient cpu",
    "Insufficient memory",
    "Insufficient alpha.kubernetes.io/nvidia-gpu",
    "Insufficient ephemeral-storage",
    "node(s) didn't match the requested hostname",
    "node(s) didn't match node selector",
    "node(s) had taints that the pod didn't tolerate",
    "node(s) had memory pressure",
    "node(s) had disk pressure",
    "node(s) didn't have free ports for the requested pod ports",
    "node(s) didn't match pod affinity/anti-affinity",
    "node(s) didn't satisfy existing pods anti-affinity rules",
    "node(s) didn't match pod affinity rules",
    "node(s) didn't match pod anti-affinity rules",
    "node(s) had no available disk",
    "node(s) exceed max volume count",
    "node(s) had no available volume zone",
    "node(s) didn't have the requested labels",
    "node(s) didn't match service affinity",
]

# Pod-group budgets (env-overridable). Groups are merged by match profile and
# every pairwise table is factored through interned matcher spaces, so the
# limits bound device memory / host precompute, not workload diversity:
#   MAX_GROUPS          — merged groups (presence rows)
#   MAX_RAW_GROUPS      — distinct raw signatures before merging
#   MAX_MATCH_WORK      — host matcher evaluations ((Td + Sd) * Graw)
#   MAX_PRESENCE_BYTES  — presence[G, N] carry size
MAX_GROUPS = 8192
MAX_RAW_GROUPS = 262_144
MAX_MATCH_WORK = 8_000_000
MAX_PRESENCE_BYTES = 1 << 30


def _group_budgets():
    import os

    def env_int(name: str, default: int) -> int:
        try:
            return int(os.environ.get(name, default))
        except ValueError:
            return default

    return (env_int("TPUSIM_MAX_GROUPS", MAX_GROUPS),
            env_int("TPUSIM_MAX_RAW_GROUPS", MAX_RAW_GROUPS),
            env_int("TPUSIM_MAX_MATCH_WORK", MAX_MATCH_WORK),
            env_int("TPUSIM_MAX_PRESENCE_BYTES", MAX_PRESENCE_BYTES))


_DICT_TAG = object()  # can never equal any JSON value


def _freeze(x):
    """Signature -> hashable canonical key. Same dedup power as the previous
    sorted-key json.dumps at a fraction of the cost (interning is the
    host-compile hot loop: 5 signatures per pod); at least as discriminating,
    which only ever splits a group, never merges one. Leaf types first —
    most signature nodes are strings."""
    t = type(x)
    if t is str or x is None:
        return x
    if t is int or t is bool or t is float:
        # type-tagged: Python cross-type equality (True == 1 == 1.0) would
        # otherwise merge keys json.dumps kept distinct ("true" vs "1")
        return (t.__name__, x)
    if t is dict:
        try:
            items = sorted(x.items())
        except TypeError:  # mixed-type keys: order by a stable stringification
            items = sorted(x.items(), key=lambda kv: (str(type(kv[0])),
                                                      str(kv[0])))
        # the sentinel keeps {} distinct from [] (and any dict distinct from
        # a list that happens to freeze to the same item tuple)
        return (_DICT_TAG,) + tuple((k, _freeze(v)) for k, v in items)
    if t is list or t is tuple:
        return tuple(_freeze(v) for v in x)
    if isinstance(x, (bool, int, float)):  # numeric subclasses
        return (type(x).__name__, x)
    if isinstance(x, str):
        return str(x)
    if isinstance(x, dict):
        return _freeze(dict(x))
    if isinstance(x, (list, tuple)):
        return tuple(_freeze(v) for v in x)
    return str(x)  # json default=str analog for exotic leaves


class Interner:
    """Canonical signature -> dense id."""

    def __init__(self):
        self._ids: Dict[object, int] = {}
        self.representatives: List[Pod] = []

    def intern(self, signature, representative) -> int:
        key = _freeze(signature)
        if key not in self._ids:
            self._ids[key] = len(self.representatives)
            self.representatives.append(representative)
        return self._ids[key]

    def __len__(self) -> int:
        return len(self.representatives)


@dataclass
class NodeStatics:
    """Per-node static columns (never mutated by binds)."""

    names: List[str]
    alloc_cpu: np.ndarray        # [N] int64, milli
    alloc_mem: np.ndarray        # [N] int64, bytes
    alloc_gpu: np.ndarray        # [N] int64
    alloc_eph: np.ndarray        # [N] int64
    allowed_pods: np.ndarray     # [N] int64
    alloc_scalar: np.ndarray     # [N, S] int64
    cond_fail_bits: np.ndarray   # [N] int64 (condition+unschedulable reason bits)
    mem_pressure: np.ndarray     # [N] bool
    disk_pressure: np.ndarray    # [N] bool


@dataclass
class SignatureTables:
    """[signature, node] static evaluation tables."""

    selector_ok: np.ndarray      # [Csel, N] bool — nodeSelector + required node affinity
    taint_ok: np.ndarray         # [Ctol, N] bool — NoSchedule/NoExecute taints tolerated
    taint_ok_noexec: np.ndarray  # [Ctol, N] bool — NoExecute-only variant (policy pred)
    intolerable: np.ndarray      # [Ctol, N] int64 — PreferNoSchedule intolerable count
    affinity_count: np.ndarray   # [Caff, N] int64 — preferred node-affinity weight sum
    avoid_score: np.ndarray      # [Cavoid, N] int64 — NodePreferAvoidPods (0 or 10)
    host_ok: np.ndarray          # [Chost, N] bool — spec.nodeName pin


@dataclass
class GroupTables:
    """Pod-group tables for the features whose state depends on which pods sit
    where: host ports (predicates.go:1019-1039), SelectorSpreadPriority
    (selector_spreading.go:66-175), and inter-pod (anti)affinity
    (predicates.go:1125-1450, interpod_affinity.go).

    A "group" is an interned (namespace, labels, pod-(anti)affinity, host-ports)
    pod signature over new + placed-existing pods, MERGED by match profile:
    raw signatures that every compiled matcher treats identically (same term
    matches, same service-selector matches, same port behavior, same actor
    terms) collapse into one group, so thousands of distinct label sets cost
    only as many groups as there are behaviorally distinct classes.

    The pairwise group tables are FACTORED through interned matcher spaces so
    nothing is O(G^2):
      term_match[Td, G]   — distinct (namespaces, selector) term signatures vs
                            groups; row 0 reserved all-False (invalid/padding)
      ss_rows[Sd, G]      — distinct (namespace, service-selector-set) spread
                            signatures vs groups; row 0 all-False
      port_conflict[Pp,Pp]— distinct sanitized host-port sets vs each other;
                            index 0 = "no ports"
    Per-group tensors then hold ids into those spaces (aff_term/anti_term/
    pref_term -> Td, ss_sig -> Sd, port_sig -> Pp).

    Topology domains: for each used topologyKey k, topo_dom[k, n] interns the
    node's label value, with 0 reserved for "label missing" (never matches,
    NodesHaveSameTopologyKey semantics). zone_dom likewise interns
    utilnode.GetZoneKey with 0 = no zone. Term tensors are padded on the term
    axis with valid=False rows."""

    group_of_pod: np.ndarray     # [P] int32 — new pods' group ids
    presence: np.ndarray         # [G, N] int32 — placed existing pods per group
    port_conflict: np.ndarray    # [Pp, Pp] bool — wanted ports of a hit ports of b
    port_sig: np.ndarray         # [G] int32 — group -> port-set id (0 = none)
    # volume predicates (device-native; see _compile_volumes)
    disk_conflict: np.ndarray    # [Dv, Dv] bool — volume-set a conflicts with b
    disk_sig: np.ndarray         # [G] int32 — group -> volume-set id (0 = none)
    vol_mask: np.ndarray         # [G, V] bool — MaxPD-relevant volume ids used
    vol_type: np.ndarray         # [V, 3] bool — id counts toward (EBS,GCE,Azure)
    zone_ok: np.ndarray          # [G, N] bool — NoVolumeZoneConflict passes
    used_vols_init: np.ndarray   # [N, V] bool — placed pods' volume ids per node
    ss_rows: np.ndarray          # [Sd, G] bool — b counts toward spread sig s
    ss_sig: np.ndarray           # [G] int32 — group -> its spread sig (0 = none)
    # ServiceAntiAffinity (policy): first-matching-service selector signatures
    # (getFirstServiceSelector is lister-order-first, and services are static
    # during a run, so "first" is a compile-time property)
    saa_rows: np.ndarray         # [Fd, G] bool — b counts toward first-sel f
    saa_sig: np.ndarray          # [G] int32 — group -> its first-sel sig (0 = none)
    term_match: np.ndarray       # [Td, G] bool — term t matches a pod of group b
    zone_dom: np.ndarray         # [N] int32
    topo_dom: np.ndarray         # [K, N] int32
    aff_valid: np.ndarray        # [G, Ta] bool — required pod-affinity terms
    aff_err: np.ndarray          # [G] bool — any term with empty topologyKey
    aff_empty: np.ndarray        # [G, Ta] bool — per-term empty topologyKey
    aff_term: np.ndarray         # [G, Ta] int32 (into Td)
    aff_key: np.ndarray          # [G, Ta] int32 (into K)
    aff_hostname: np.ndarray     # [G, Ta] bool — topologyKey == kubernetes.io/hostname
    aff_self: np.ndarray         # [G, Ta] bool — the pod matches its own term
    aff_unplaced: np.ndarray     # [G, Ta] bool — an unplaced snapshot pod matches
    anti_valid: np.ndarray       # [G, Tb] bool — required pod-anti-affinity terms
    anti_err: np.ndarray         # [G] bool
    anti_empty: np.ndarray       # [G, Tb] bool
    anti_term: np.ndarray        # [G, Tb] int32 (into Td)
    anti_key: np.ndarray         # [G, Tb] int32
    anti_hostname: np.ndarray    # [G, Tb] bool
    pref_w: np.ndarray           # [G, Tp] float64 — preferred terms, signed weight
    pref_term: np.ndarray        # [G, Tp] int32 (into Td)
    pref_key: np.ndarray         # [G, Tp] int32
    # (namespace, selector) per first-sel sig, index 0 = None; the backend's
    # ServiceAffinity first-POD analysis resolves locks against these
    saa_defs: list = field(default_factory=list)


@dataclass
class PodColumns:
    """Per-pod numeric columns + signature ids (the scan's xs)."""

    req_cpu: np.ndarray          # [P] int64 milli
    req_mem: np.ndarray          # [P] int64
    req_gpu: np.ndarray          # [P] int64
    req_eph: np.ndarray          # [P] int64
    req_scalar: np.ndarray       # [P, S] int64
    nz_cpu: np.ndarray           # [P] int64 (non-zero-default cpu, priorities only)
    nz_mem: np.ndarray           # [P] int64
    zero_request: np.ndarray     # [P] bool (PodFitsResources fast path)
    best_effort: np.ndarray      # [P] bool
    sel_id: np.ndarray           # [P] int32
    tol_id: np.ndarray           # [P] int32
    aff_id: np.ndarray           # [P] int32
    avoid_id: np.ndarray         # [P] int32
    host_id: np.ndarray          # [P] int32
    group_id: np.ndarray         # [P] int32 — pod-group id (GroupTables)
    # pod-image-set signature id (ImageLocalityPriority table; zeros unless a
    # policy enables the priority — jaxe.policyc fills it then)
    img_id: np.ndarray           # [P] int32
    # ServiceAffinity predicate column (policy-only; policyc fills it)
    sa_self_id: np.ndarray       # [P] int32 — own-nodeSelector-pin signature


@dataclass
class DynamicInit:
    """Mutable aggregates seeded from pre-scheduled snapshot pods
    (NodeInfo.AddPod accounting, node_info.go:318-398)."""

    used_cpu: np.ndarray         # [N] int64
    used_mem: np.ndarray
    used_gpu: np.ndarray
    used_eph: np.ndarray
    used_scalar: np.ndarray      # [N, S] int64
    nonzero_cpu: np.ndarray      # [N] int64
    nonzero_mem: np.ndarray
    pod_count: np.ndarray        # [N] int64


@dataclass
class CompiledCluster:
    statics: NodeStatics
    tables: SignatureTables
    groups: GroupTables
    dynamic: DynamicInit
    scalar_names: List[str]
    node_index: Dict[str, int]
    has_ports: bool = False
    has_services: bool = False
    has_interpod: bool = False
    has_disk_conflict: bool = False
    has_maxpd: bool = False
    has_vol_zone: bool = False
    # taint_ok_noexec / saa tables hold real rows (vs the dummies the
    # no-policy path ships); jaxe.backend recompiles when a policy needs them
    has_noexec_table: bool = False
    has_saa_table: bool = False
    maxpd_limits: tuple = DEFAULT_MAXPD_LIMITS   # (EBS, GCE PD, AzureDisk)
    n_topo_doms: int = 1         # segment count for topo_dom (incl. invalid 0)
    n_zone_doms: int = 1
    unsupported: List[str] = field(default_factory=list)  # features needing fallback


def _selector_signature(pod: Pod):
    aff = pod.spec.affinity
    na = aff.node_affinity.to_obj() if (aff and aff.node_affinity) else None
    return {"nodeSelector": pod.spec.node_selector,
            "required": (na or {}).get("requiredDuringSchedulingIgnoredDuringExecution")}


def _toleration_signature(pod: Pod):
    return {"tolerations": [t.to_obj() for t in pod.spec.tolerations]}


def _affinity_signature(pod: Pod):
    aff = pod.spec.affinity
    na = aff.node_affinity.to_obj() if (aff and aff.node_affinity) else None
    return {"preferred": (na or {}).get("preferredDuringSchedulingIgnoredDuringExecution")}


def _avoid_signature(pod: Pod):
    ref = pod.metadata.controller_ref()
    if ref is None or ref.kind not in ("ReplicationController", "ReplicaSet"):
        return None
    return {"kind": ref.kind, "uid": ref.uid}


def _host_signature(pod: Pod):
    return pod.spec.node_name or None


# ---------------------------------------------------------------------------
# pod-group compilation (host ports / selector spreading / inter-pod affinity)
# ---------------------------------------------------------------------------

_ANY_IP = "0.0.0.0"


def _sanitized_ports(pod: Pod) -> list:
    """Wanted (ip, protocol, port) triples, HostPortInfo-sanitized
    (util/utils.go:51-137: ip defaults 0.0.0.0, protocol TCP, port>0 only)."""
    out = set()
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                out.add((p.host_ip or _ANY_IP, p.protocol or "TCP", p.host_port))
    return sorted(out)


def _ports_conflict(wants: list, occupied: list) -> bool:
    """check_conflict over a full pod pair: 0.0.0.0 wildcards either side."""
    for wip, wproto, wport in wants:
        for oip, oproto, oport in occupied:
            if (wport == oport and wproto == oproto
                    and (wip == _ANY_IP or oip == _ANY_IP or wip == oip)):
                return True
    return False


def _group_signature(pod: Pod):
    aff = pod.spec.affinity
    return {
        "ns": pod.namespace,
        "labels": pod.metadata.labels,
        "aff": aff.pod_affinity.to_obj() if (aff and aff.pod_affinity) else None,
        "anti": (aff.pod_anti_affinity.to_obj()
                 if (aff and aff.pod_anti_affinity) else None),
        "ports": _sanitized_ports(pod),
        # volumes drive NoDiskConflict/MaxPDVolumeCount/NoVolumeZoneConflict;
        # [] keeps volume-less pods in one signature class
        "vols": sorted(json.dumps(v.to_obj(), sort_keys=True)
                       for v in pod.spec.volumes),
    }


def _has_interpod_terms(pod: Pod) -> bool:
    a = pod.spec.affinity
    return a is not None and (a.pod_affinity is not None
                              or a.pod_anti_affinity is not None)


def _req_aff_terms(pod: Pod) -> list:
    a = pod.spec.affinity
    return get_pod_affinity_terms(a.pod_affinity) if a else []


def _req_anti_terms(pod: Pod) -> list:
    a = pod.spec.affinity
    return get_pod_anti_affinity_terms(a.pod_anti_affinity) if a else []


def _pref_terms(pod: Pod) -> list:
    """Signed (weight, term): preferred affinity positive, anti negative
    (interpod_affinity.go processWeightedTerms multipliers)."""
    a = pod.spec.affinity
    out = []
    if a and a.pod_affinity:
        out += [(wt.weight, wt.pod_affinity_term) for wt in a.pod_affinity.preferred]
    if a and a.pod_anti_affinity:
        out += [(-wt.weight, wt.pod_affinity_term)
                for wt in a.pod_anti_affinity.preferred]
    return out


class _VolumeFallback(Exception):
    """Raised during volume compilation when the workload needs host-side
    semantics (resolution errors the reference reports per pod) or exceeds a
    budget; routes the batch to the parity engine."""


_MAXPD_TYPES = ("EBS", "GCE", "AzureDisk")
MAX_VOLUME_IDS = 4096


def _compile_volumes(raw_reps: List[Pod], nodes: List[Node],
                     snapshot: ClusterSnapshot, max_work: int):
    """Device tables for NoDiskConflict / MaxPDVolumeCount /
    NoVolumeZoneConflict (predicates.go:266-276, 288-460, 510-533).

    Volume sets are interned per (namespace, volumes) signature; PVC->PV
    resolution happens here against the snapshot, so the device only carries
    a per-node used-volume-id matrix and static conflict/zone tables.
    Returns (vsig_raw[Graw], disk_conflict[Dv,Dv], vol_mask[Dv,V],
    vol_type[V,3], zone_rows[Dv,N], limits, flags)."""
    import os

    from tpusim.engine.predicates import (
        _VOLUME_FILTERS,
        is_volume_conflict,
        label_zones_to_set,
    )

    graw = len(raw_reps)
    n = len(nodes)
    pvcs = {pvc.key(): pvc for pvc in snapshot.pvcs}
    pvs = {pv.name: pv for pv in snapshot.pvs}
    node_constraints = [
        {k: v for k, v in node.metadata.labels.items() if k in _ZONE_LABELS}
        for node in nodes]
    any_zone_nodes = any(node_constraints)

    # --- volume-set signature interning over raw groups ---
    vsig_ids: Dict[str, int] = {"": 0}
    vsig_reps: List[Optional[Pod]] = [None]
    vsig_raw = np.zeros(graw, np.int32)
    for b, rep in enumerate(raw_reps):
        if not rep.spec.volumes:
            continue
        key = json.dumps([rep.namespace,
                          sorted(json.dumps(v.to_obj(), sort_keys=True)
                                 for v in rep.spec.volumes)])
        vid = vsig_ids.get(key)
        if vid is None:
            vid = len(vsig_reps)
            vsig_ids[key] = vid
            vsig_reps.append(rep)
        vsig_raw[b] = vid
    dv = len(vsig_reps)
    if dv * dv + dv * n > max_work:
        raise _VolumeFallback(
            f"volume-set precompute ({dv} sets, {n} nodes) exceeds the jax "
            f"backend work budget ({max_work})")

    # --- NoDiskConflict: pairwise conflicts between volume sets ---
    disk_conflict = np.zeros((dv, dv), dtype=bool)
    for a in range(1, dv):
        for b in range(1, dv):
            disk_conflict[a, b] = any(
                is_volume_conflict(v, vsig_reps[b])
                for v in vsig_reps[a].spec.volumes)
    has_disk = bool(disk_conflict.any())

    # --- MaxPDVolumeCount: per-set relevant volume ids (resolved via PVC->PV;
    # unresolvable claims count conservatively toward every filter type) ---
    vol_ids: Dict[tuple, int] = {}
    set_ids: List[List[int]] = [[] for _ in range(dv)]
    id_types: List[set] = []

    def intern_vol(key: tuple, types: set) -> int:
        vid = vol_ids.get(key)
        if vid is None:
            vid = len(id_types)
            vol_ids[key] = vid
            id_types.append(set())
        id_types[vid] |= types
        return vid

    for s in range(1, dv):
        rep = vsig_reps[s]
        for vol in rep.spec.volumes:
            direct = False
            for t, name in enumerate(_MAXPD_TYPES):
                vol_src, _, id_field, _ = _VOLUME_FILTERS[name]
                src = vol_src(vol)
                if src is not None:
                    set_ids[s].append(intern_vol(
                        (name, src.get(id_field, "")), {t}))
                    direct = True
                    break
            if direct:
                continue
            pvc_name = vol.pvc_name
            if pvc_name is None:
                continue
            if pvc_name == "":
                raise _VolumeFallback(
                    "a pod volume has a PersistentVolumeClaim with no name")
            pvc = pvcs.get(f"{rep.namespace}/{pvc_name}")
            pv = pvs.get(pvc.volume_name) if (pvc and pvc.volume_name) else None
            if pv is None:
                # missing PVC / unbound PVC / missing PV: conservative id
                # counted toward every type (predicates.go:379-410); the zone
                # predicate would error on these when zone constraints exist
                if any_zone_nodes:
                    raise _VolumeFallback(
                        f'unresolvable PersistentVolumeClaim "{pvc_name}" with '
                        "zone-constrained nodes (NoVolumeZoneConflict errors "
                        "host-side)")
                set_ids[s].append(intern_vol(
                    ("pvc", f"{rep.namespace}/{pvc_name}"), {0, 1, 2}))
                continue
            for t, name in enumerate(_MAXPD_TYPES):
                _, pv_src, id_field, _ = _VOLUME_FILTERS[name]
                src = pv_src(pv)
                if src is not None:
                    set_ids[s].append(intern_vol(
                        (name, src.get(id_field, "")), {t}))
                    break
    v_count = len(id_types)
    max_vol_ids = int(os.environ.get("TPUSIM_MAX_VOLUME_IDS", MAX_VOLUME_IDS))
    if v_count > max_vol_ids:
        raise _VolumeFallback(
            f"{v_count} distinct MaxPD volume ids exceed the jax backend "
            f"limit ({max_vol_ids})")
    v_dim = max(v_count, 1)
    vol_mask = np.zeros((dv, v_dim), dtype=bool)
    for s in range(dv):
        for vid in set_ids[s]:
            vol_mask[s, vid] = True
    vol_type = np.zeros((v_dim, 3), dtype=bool)
    for vid, types in enumerate(id_types):
        for t in types:
            vol_type[vid, t] = True
    has_maxpd = v_count > 0
    limits = effective_maxpd_limits()

    # --- NoVolumeZoneConflict: static (volume set, node) pass/fail ---
    zone_rows = np.ones((dv, n), dtype=bool)
    has_zone = False
    if any_zone_nodes:
        for s in range(1, dv):
            rep = vsig_reps[s]
            for vol in rep.spec.volumes:
                pvc_name = vol.pvc_name
                if not pvc_name:
                    continue
                pvc = pvcs[f"{rep.namespace}/{pvc_name}"]  # resolved above
                pv = pvs[pvc.volume_name]
                for k, v in pv.metadata.labels.items():
                    if k not in _ZONE_LABELS:
                        continue
                    try:
                        allowed = label_zones_to_set(v)
                    except ValueError:
                        continue  # unparsable label ignored
                    for i, constraints in enumerate(node_constraints):
                        if not constraints:
                            continue  # zone-label-less node passes trivially
                        # a constrained node missing the PV's label fails too
                        # (nodeConstraints[k] yields "" in the reference)
                        if constraints.get(k) not in allowed:
                            zone_rows[s, i] = False
                            has_zone = True
    return (vsig_raw, disk_conflict, vol_mask, vol_type, zone_rows, limits,
            has_disk, has_maxpd, has_zone)


def _trivial_groups(num_pods: int, n: int) -> "GroupTables":
    z = np.zeros
    return GroupTables(
        group_of_pod=z(num_pods, np.int32), presence=z((1, n), np.int32),
        port_conflict=z((1, 1), bool), port_sig=z(1, np.int32),
        disk_conflict=z((1, 1), bool), disk_sig=z(1, np.int32),
        vol_mask=z((1, 1), bool), vol_type=z((1, 3), bool),
        zone_ok=np.ones((1, n), bool), used_vols_init=z((n, 1), bool),
        ss_rows=z((1, 1), bool), ss_sig=z(1, np.int32),
        saa_rows=z((1, 1), bool), saa_sig=z(1, np.int32),
        term_match=z((1, 1), bool),
        zone_dom=z(n, np.int32), topo_dom=z((1, n), np.int32),
        aff_valid=z((1, 1), bool), aff_err=z(1, bool), aff_empty=z((1, 1), bool),
        aff_term=z((1, 1), np.int32), aff_key=z((1, 1), np.int32),
        aff_hostname=z((1, 1), bool), aff_self=z((1, 1), bool),
        aff_unplaced=z((1, 1), bool),
        anti_valid=z((1, 1), bool), anti_err=z(1, bool), anti_empty=z((1, 1), bool),
        anti_term=z((1, 1), np.int32), anti_key=z((1, 1), np.int32),
        anti_hostname=z((1, 1), bool),
        pref_w=z((1, 1), np.float64), pref_term=z((1, 1), np.int32),
        pref_key=z((1, 1), np.int32))


def _compile_groups(snapshot: ClusterSnapshot, pods: List[Pod],
                    nodes: List[Node], node_index: Dict[str, int],
                    need_saa: bool = False):
    """Build GroupTables + feature flags. Returns
    (tables, has_ports, has_services, has_interpod, n_topo_doms, n_zone_doms,
    unsupported, sig_to_gid, vol_meta) where sig_to_gid maps each raw
    canonical group signature key to its merged group id (used by the
    incremental path) and vol_meta = (has_disk_conflict, has_maxpd,
    has_vol_zone, maxpd_limits)."""
    n = len(nodes)
    no_vol_meta = (False, False, False, DEFAULT_MAXPD_LIMITS)
    placed = [p for p in snapshot.pods if p.spec.node_name in node_index]
    # pods with an unknown-but-set nodeName still count for "matching pod
    # exists"; nodeName-less (pending) pods are dropped by the reference's pod
    # lister (backends.py scheduled-pod filter) and must not count
    unplaced = [p for p in snapshot.pods
                if p.spec.node_name and p.spec.node_name not in node_index]

    has_ports = any(_sanitized_ports(p) for p in pods) \
        or any(_sanitized_ports(p) for p in placed)
    has_interpod = any(_has_interpod_terms(p) for p in pods) \
        or any(_has_interpod_terms(p) for p in placed)
    has_services = bool(snapshot.services)
    has_volumes = any(p.spec.volumes for p in pods) \
        or any(p.spec.volumes for p in placed)
    if not (has_ports or has_interpod or has_services or has_volumes):
        return (_trivial_groups(len(pods), n), False, False, False, 1, 1, [],
                {}, no_vol_meta)

    max_groups, max_raw, max_work, max_presence = _group_budgets()

    def fallback(reason: str):
        return (_trivial_groups(len(pods), n), False, False, False, 1, 1,
                [reason], {}, no_vol_meta)

    # --- 1. raw signature interning ---
    gi = Interner()
    raw_of_pod = [gi.intern(_group_signature(p), p) for p in pods]
    placed_raw = [gi.intern(_group_signature(p), p) for p in placed]
    graw = len(gi)
    if graw > max_raw:
        return fallback(f"{graw} distinct raw pod groups exceed the jax "
                        f"backend limit ({max_raw})")
    raw_reps = gi.representatives
    raw_keys = list(gi._ids.keys())  # insertion-ordered: index == raw id

    # --- volume tables (NoDiskConflict / MaxPDVolumeCount / NoVolumeZone) ---
    if has_volumes:
        try:
            (vsig_raw, disk_conflict, vsig_mask, vol_type, zone_rows,
             maxpd_limits, has_disk, has_maxpd, has_zone) = _compile_volumes(
                 raw_reps, nodes, snapshot, max_work)
        except _VolumeFallback as exc:
            return fallback(str(exc))
    else:
        vsig_raw = np.zeros(graw, np.int32)
        disk_conflict = np.zeros((1, 1), bool)
        vsig_mask = np.zeros((1, 1), bool)
        vol_type = np.zeros((1, 3), bool)
        zone_rows = np.ones((1, n), bool)
        maxpd_limits = DEFAULT_MAXPD_LIMITS
        has_disk = has_maxpd = has_zone = False

    # --- 2. intern matcher spaces: terms, port sets, spread signatures ---
    # term signature = (resolved namespaces, selector): that pair fully
    # determines which pods a term matches (predicates.go
    # podMatchesTermNamespaceAndSelector)
    term_defs: List[tuple] = [None]  # index 0 reserved: matches nothing
    term_ids: Dict[str, int] = {}

    def intern_term(rep: Pod, term) -> int:
        namespaces = get_namespaces_from_pod_affinity_term(rep, term)
        sel = term.label_selector
        key = json.dumps([sorted(namespaces),
                          sel.to_obj() if sel is not None else None],
                         sort_keys=True)
        tid = term_ids.get(key)
        if tid is None:
            tid = len(term_defs)
            term_ids[key] = tid
            term_defs.append((namespaces, sel))
        return tid

    # raw per-group actor term lists: [(tid, topology_key, weight)] per kind
    aff_of: List[list] = []
    anti_of: List[list] = []
    pref_of: List[list] = []
    if has_interpod:
        for rep in raw_reps:
            aff_of.append([(intern_term(rep, t), t.topology_key)
                           for t in _req_aff_terms(rep)])
            anti_of.append([(intern_term(rep, t), t.topology_key)
                            for t in _req_anti_terms(rep)])
            pref_of.append([(intern_term(rep, t), t.topology_key, w)
                            for w, t in _pref_terms(rep)])
    else:
        aff_of = anti_of = pref_of = [[] for _ in raw_reps]
    td = len(term_defs)

    # spread signature = (namespace, selected service selectors); 0 = none
    spread_defs: List[tuple] = [None]
    spread_ids: Dict[str, int] = {}
    ss_sig_raw = np.zeros(graw, np.int32)
    if has_services and len(snapshot.services) * graw > max_work:
        # the service->group scan below is O(services * graw); budget it like
        # the matcher rows so a huge snapshot can't hang host compile
        return fallback(
            f"pod-group service scan ({len(snapshot.services)} services x "
            f"{graw} raw groups) exceeds the jax backend work budget "
            f"({max_work})")
    # ServiceAntiAffinity first-service signature rides the same scan: the
    # spread loop builds `sels` in lister order, so the FIRST matching
    # service's selector (priorities.ServiceAntiAffinity
    # ._first_service_selector) is sels[0]; policy-only (need_saa)
    saa_defs: List[tuple] = [None]
    saa_ids: Dict[str, int] = {}
    saa_sig_raw = np.zeros(graw, np.int32)
    if has_services:
        for b, rep in enumerate(raw_reps):
            sels = [dict(svc.selector) for svc in snapshot.services
                    if (svc.namespace == rep.namespace and svc.selector
                        and all(rep.metadata.labels.get(k) == v
                                for k, v in svc.selector.items()))]
            if not sels:
                continue
            key = json.dumps([rep.namespace,
                              sorted(json.dumps(s, sort_keys=True) for s in sels)])
            sid = spread_ids.get(key)
            if sid is None:
                sid = len(spread_defs)
                spread_ids[key] = sid
                spread_defs.append((rep.namespace, sels))
            ss_sig_raw[b] = sid
            if need_saa:
                fkey = json.dumps([rep.namespace,
                                   json.dumps(sels[0], sort_keys=True)])
                fid = saa_ids.get(fkey)
                if fid is None:
                    fid = len(saa_defs)
                    saa_ids[fkey] = fid
                    saa_defs.append((rep.namespace, sels[0]))
                saa_sig_raw[b] = fid
    sd = len(spread_defs)
    fd = len(saa_defs)

    if (td + sd + (fd - 1)) * graw > max_work:
        return fallback(
            f"pod-group matcher precompute ({td} terms + {sd} spread sigs + "
            f"{fd - 1} service-anti-affinity sigs x {graw} raw groups) "
            f"exceeds the jax backend work budget ({max_work})")

    # port-set interning; 0 = no ports
    port_defs: List[list] = [[]]
    port_ids: Dict[tuple, int] = {(): 0}
    port_sig_raw = np.zeros(graw, np.int32)
    if has_ports:
        for b, rep in enumerate(raw_reps):
            ports = tuple(_sanitized_ports(rep))
            pid = port_ids.get(ports)
            if pid is None:
                pid = len(port_defs)
                port_ids[ports] = pid
                port_defs.append(list(ports))
            port_sig_raw[b] = pid
    pp = len(port_defs)
    port_conflict = np.zeros((pp, pp), dtype=bool)
    for a in range(1, pp):
        for b in range(1, pp):
            port_conflict[a, b] = _ports_conflict(port_defs[a], port_defs[b])

    # --- 3. matcher rows over raw groups ---
    term_match_raw = np.zeros((td, graw), dtype=bool)
    unplaced_match = np.zeros(td, dtype=bool)
    for tid in range(1, td):
        namespaces, sel = term_defs[tid]
        for b, rep in enumerate(raw_reps):
            term_match_raw[tid, b] = pod_matches_term_namespace_and_selector(
                rep, namespaces, sel)
        unplaced_match[tid] = any(
            pod_matches_term_namespace_and_selector(u, namespaces, sel)
            for u in unplaced)

    ss_rows_raw = np.zeros((sd, graw), dtype=bool)
    for sid in range(1, sd):
        ns, sels = spread_defs[sid]
        for b, rep in enumerate(raw_reps):
            ss_rows_raw[sid, b] = rep.namespace == ns and any(
                all(rep.metadata.labels.get(k) == v for k, v in sel.items())
                for sel in sels)

    saa_rows_raw = np.zeros((fd, graw), dtype=bool)
    for fid in range(1, fd):
        ns, sel = saa_defs[fid]
        for b, rep in enumerate(raw_reps):
            saa_rows_raw[fid, b] = rep.namespace == ns and all(
                rep.metadata.labels.get(k) == v for k, v in sel.items())

    # --- 4. merge raw groups by match profile ---
    # two raw groups are indistinguishable when every matcher treats them the
    # same (same term/spread columns, same port set) AND they act identically
    # (same own terms with the same topology keys/weights, same spread sig)
    merged: Dict[tuple, int] = {}
    gid_of_raw = np.zeros(graw, np.int32)
    rep_raw_idx: List[int] = []
    for b in range(graw):
        profile = (term_match_raw[:, b].tobytes(), ss_rows_raw[:, b].tobytes(),
                   saa_rows_raw[:, b].tobytes(),
                   int(port_sig_raw[b]), int(ss_sig_raw[b]),
                   int(saa_sig_raw[b]), int(vsig_raw[b]),
                   tuple(aff_of[b]), tuple(anti_of[b]), tuple(pref_of[b]))
        gid = merged.get(profile)
        if gid is None:
            gid = len(rep_raw_idx)
            merged[profile] = gid
            rep_raw_idx.append(b)
        gid_of_raw[b] = gid
    g = len(rep_raw_idx)
    if g > max_groups:
        return fallback(f"{g} distinct pod groups exceed the jax backend "
                        f"limit ({max_groups})")
    if g * n * 4 > max_presence:
        return fallback(
            f"pod-group presence state ({g} groups x {n} nodes) exceeds the "
            f"jax backend memory budget ({max_presence} bytes)")
    sig_to_gid = {key: int(gid_of_raw[b]) for b, key in enumerate(raw_keys)}

    group_of_pod = gid_of_raw[np.array(raw_of_pod, dtype=np.int64)] \
        if raw_of_pod else np.zeros(0, np.int32)
    group_of_pod = group_of_pod.astype(np.int32)
    reps = [raw_reps[b] for b in rep_raw_idx]
    sel_cols = np.array(rep_raw_idx, dtype=np.int64)
    term_match = term_match_raw[:, sel_cols] if graw else term_match_raw
    ss_rows = ss_rows_raw[:, sel_cols] if graw else ss_rows_raw
    saa_rows = saa_rows_raw[:, sel_cols] if graw else saa_rows_raw
    port_sig = port_sig_raw[sel_cols].astype(np.int32)
    ss_sig = ss_sig_raw[sel_cols].astype(np.int32)
    saa_sig = saa_sig_raw[sel_cols].astype(np.int32)

    disk_sig = vsig_raw[sel_cols].astype(np.int32)
    vol_mask = vsig_mask[vsig_raw[sel_cols]]        # [G, V]
    zone_ok = zone_rows[vsig_raw[sel_cols]]         # [G, N]

    presence = np.zeros((g, n), dtype=np.int32)
    used_vols_init = np.zeros((n, vsig_mask.shape[1]), dtype=bool)
    for raw_id, p in zip(placed_raw, placed):
        i = node_index[p.spec.node_name]
        presence[gid_of_raw[raw_id], i] += 1
        if has_maxpd:
            used_vols_init[i] |= vsig_mask[vsig_raw[raw_id]]

    zone_dom = np.zeros(n, dtype=np.int32)
    n_zone_doms = 1
    if has_services:
        zvals: Dict[str, int] = {}
        for i, node in enumerate(nodes):
            z = get_zone_key(node)
            if z:
                zone_dom[i] = zvals.setdefault(z, len(zvals) + 1)
        n_zone_doms = len(zvals) + 1

    # --- 5. topology keys + per-group actor tensors over merged groups ---
    topo_keys: List[str] = []
    if has_interpod:
        seen_keys = set()
        for b in rep_raw_idx:
            for tid, key in aff_of[b] + anti_of[b]:
                if key and key not in seen_keys:
                    seen_keys.add(key)
                    topo_keys.append(key)
            for tid, key, w in pref_of[b]:
                if key and key not in seen_keys:
                    seen_keys.add(key)
                    topo_keys.append(key)
    k_count = max(len(topo_keys), 1)
    key_idx = {key: i for i, key in enumerate(topo_keys)}

    topo_dom = np.zeros((k_count, n), dtype=np.int32)
    n_topo_doms = 1
    for k, key in enumerate(topo_keys):
        vals: Dict[str, int] = {}
        for i, node in enumerate(nodes):
            v = node.metadata.labels.get(key)
            if v is not None:
                topo_dom[k, i] = vals.setdefault(v, len(vals) + 1)
        n_topo_doms = max(n_topo_doms, len(vals) + 1)

    ta = max([1] + [len(aff_of[b]) for b in rep_raw_idx])
    tb = max([1] + [len(anti_of[b]) for b in rep_raw_idx])
    tp = max([1] + [len(pref_of[b]) for b in rep_raw_idx])
    aff_valid = np.zeros((g, ta), bool)
    aff_err = np.zeros(g, bool)
    aff_empty = np.zeros((g, ta), bool)
    aff_term = np.zeros((g, ta), np.int32)
    aff_key = np.zeros((g, ta), np.int32)
    aff_hostname = np.zeros((g, ta), bool)
    aff_self = np.zeros((g, ta), bool)
    aff_unplaced = np.zeros((g, ta), bool)
    anti_valid = np.zeros((g, tb), bool)
    anti_err = np.zeros(g, bool)
    anti_empty = np.zeros((g, tb), bool)
    anti_term = np.zeros((g, tb), np.int32)
    anti_key = np.zeros((g, tb), np.int32)
    anti_hostname = np.zeros((g, tb), bool)
    pref_w = np.zeros((g, tp), np.float64)
    pref_term = np.zeros((g, tp), np.int32)
    pref_key = np.zeros((g, tp), np.int32)

    if has_interpod:
        for a, b in enumerate(rep_raw_idx):
            for t, (tid, key) in enumerate(aff_of[b]):
                aff_valid[a, t] = True
                aff_term[a, t] = tid
                if not key:
                    # _any_pod_matches_term raises -> whole predicate fails
                    aff_empty[a, t] = True
                    aff_err[a] = True
                else:
                    aff_key[a, t] = key_idx[key]
                    aff_hostname[a, t] = key == LABEL_HOSTNAME
                aff_self[a, t] = term_match[tid, a]
                aff_unplaced[a, t] = unplaced_match[tid]
            for t, (tid, key) in enumerate(anti_of[b]):
                anti_valid[a, t] = True
                anti_term[a, t] = tid
                if not key:
                    anti_empty[a, t] = True
                    anti_err[a] = True
                else:
                    anti_key[a, t] = key_idx[key]
                    anti_hostname[a, t] = key == LABEL_HOSTNAME
            for t, (tid, key, w) in enumerate(pref_of[b]):
                if not key:
                    continue  # NodesHaveSameTopologyKey("") is always False
                pref_w[a, t] = float(w)
                pref_term[a, t] = tid
                pref_key[a, t] = key_idx[key]

    tables = GroupTables(
        group_of_pod=group_of_pod, presence=presence,
        port_conflict=port_conflict, port_sig=port_sig,
        disk_conflict=disk_conflict, disk_sig=disk_sig,
        vol_mask=vol_mask, vol_type=vol_type, zone_ok=zone_ok,
        used_vols_init=used_vols_init,
        ss_rows=ss_rows, ss_sig=ss_sig,
        saa_rows=saa_rows, saa_sig=saa_sig, saa_defs=list(saa_defs),
        term_match=term_match,
        zone_dom=zone_dom, topo_dom=topo_dom,
        aff_valid=aff_valid, aff_err=aff_err, aff_empty=aff_empty,
        aff_term=aff_term, aff_key=aff_key, aff_hostname=aff_hostname,
        aff_self=aff_self, aff_unplaced=aff_unplaced,
        anti_valid=anti_valid, anti_err=anti_err, anti_empty=anti_empty,
        anti_term=anti_term, anti_key=anti_key, anti_hostname=anti_hostname,
        pref_w=pref_w, pref_term=pref_term, pref_key=pref_key)
    return (tables, has_ports, has_services, has_interpod,
            n_topo_doms, n_zone_doms, [], sig_to_gid,
            (has_disk, has_maxpd, has_zone, maxpd_limits))


def node_static_row(node: Node, ni: NodeInfo, scalar_idx: Dict[str, int],
                    s: int):
    """One node's static column values (shared with the incremental path):
    (cpu, mem, gpu, eph, pods, scalar_row[s], cond_bits, mem_p, disk_p)."""
    r = ni.allocatable_resource
    scalar_row = np.zeros(s, dtype=np.int64)
    for name, v in r.scalar.items():
        scalar_row[scalar_idx[name]] = v
    bits = 0
    for cond in node.status.conditions:
        if cond.type == "Ready" and cond.status != "True":
            bits |= 1 << BIT_NODE_NOT_READY
        elif cond.type == "OutOfDisk" and cond.status != "False":
            bits |= 1 << BIT_NODE_OUT_OF_DISK
        elif cond.type == "NetworkUnavailable" and cond.status != "False":
            bits |= 1 << BIT_NODE_NETWORK_UNAVAILABLE
    if node.spec.unschedulable:
        bits |= 1 << BIT_NODE_UNSCHEDULABLE
    return (r.milli_cpu, r.memory, r.nvidia_gpu, r.ephemeral_storage,
            r.allowed_pod_number, scalar_row, bits, ni.memory_pressure,
            ni.disk_pressure)


def signature_row_fns(nodes: List[Node], node_infos: List["NodeInfo"]):
    """Per-signature-table row evaluators: kind -> (fn(rep, node_idx), dtype).

    Shared by compile_cluster and the incremental delta path (delta.py), so
    both compute table cells with exactly the same engine matchers. The
    sel/tol/aff/avoid/host interner each table reads from is fixed:
    selector_ok<-sel, taint_ok+intolerable<-tol, affinity_count<-aff,
    avoid_score<-avoid, host_ok<-host."""

    def selector_fn(rep: Optional[Pod], i: int) -> bool:
        return pod_matches_node_labels(rep, nodes[i])

    def taint_ok_fn(rep: Pod, i: int) -> bool:
        return find_matching_untolerated_taint(
            node_infos[i].taints, rep.spec.tolerations,
            lambda t: t.effect in ("NoSchedule", "NoExecute")) is None

    def taint_ok_noexec_fn(rep: Pod, i: int) -> bool:
        # PodToleratesNodeNoExecuteTaints (policy-registered): NoExecute only
        return find_matching_untolerated_taint(
            node_infos[i].taints, rep.spec.tolerations,
            lambda t: t.effect == "NoExecute") is None

    def intolerable_fn(rep: Pod, i: int) -> int:
        tols = [t for t in rep.spec.tolerations
                if not t.effect or t.effect == TAINT_PREFER_NO_SCHEDULE]
        return sum(1 for taint in node_infos[i].taints
                   if taint.effect == TAINT_PREFER_NO_SCHEDULE
                   and not tolerations_tolerate_taint(tols, taint))

    def affinity_fn(rep: Pod, i: int) -> int:
        return calculate_node_affinity_priority_map(rep, None, node_infos[i]).score

    def avoid_fn(rep: Pod, i: int) -> int:
        return calculate_node_prefer_avoid_pods_priority_map(rep, None, node_infos[i]).score

    def host_fn(rep: Pod, i: int) -> bool:
        return (not rep.spec.node_name) or rep.spec.node_name == nodes[i].name

    return {
        "selector_ok": (selector_fn, bool),
        "taint_ok": (taint_ok_fn, bool),
        "taint_ok_noexec": (taint_ok_noexec_fn, bool),
        "intolerable": (intolerable_fn, np.int64),
        "affinity_count": (affinity_fn, np.int64),
        "avoid_score": (avoid_fn, np.int64),
        "host_ok": (host_fn, bool),
    }


def fill_pod_request_row(cols: PodColumns, j: int, pod: Pod, req,
                         scalar_idx: Dict[str, int]) -> None:
    """Fill one pod's numeric request columns (shared with delta.py so the
    incremental path can never drift from the fresh-compile semantics)."""
    cols.req_cpu[j] = req.milli_cpu
    cols.req_mem[j] = req.memory
    cols.req_gpu[j] = req.nvidia_gpu
    cols.req_eph[j] = req.ephemeral_storage
    for name, v in req.scalar.items():
        cols.req_scalar[j, scalar_idx[name]] = v
    cols.zero_request[j] = (req.milli_cpu == 0 and req.memory == 0
                            and req.nvidia_gpu == 0 and req.ephemeral_storage == 0
                            and not req.scalar)
    nz = get_nonzero_pod_request(pod)
    cols.nz_cpu[j] = nz.milli_cpu
    cols.nz_mem[j] = nz.memory
    cols.best_effort[j] = is_pod_best_effort(pod)


def compile_cluster(snapshot: ClusterSnapshot, pods: List[Pod],
                    need_noexec: bool = False, need_saa: bool = False
                    ) -> Tuple[CompiledCluster, PodColumns]:
    """Build columnar state for `pods` scheduled against `snapshot`.

    Static matching reuses the parity engine's own functions (semantics match
    by construction); only numeric aggregates stay dynamic. need_noexec:
    compute the PodToleratesNodeNoExecuteTaints table — only a policy can
    enable that predicate, so the default path skips the row work and ships
    an all-pass dummy of the right shape.
    """
    nodes = snapshot.nodes
    n = len(nodes)

    # single pass: NodeInfos, per-pod requests, and the scalar name space
    node_infos: List[NodeInfo] = []
    for node in nodes:
        ni = NodeInfo()
        ni.set_node(node)
        node_infos.append(ni)
    pod_requests = [get_resource_request(pod) for pod in pods]
    existing_requests = [get_resource_request(pod) for pod in snapshot.pods]

    scalar_names: List[str] = []
    seen = set()

    def _note_scalars(names):
        for name in names:
            if name not in seen:
                seen.add(name)
                scalar_names.append(name)

    for req in pod_requests + existing_requests:
        _note_scalars(req.scalar)
    for ni in node_infos:
        _note_scalars(ni.allocatable_resource.scalar)
    s = len(scalar_names)
    scalar_idx = {name: i for i, name in enumerate(scalar_names)}

    # --- node statics ---
    alloc = {k: np.zeros(n, dtype=np.int64)
             for k in ("cpu", "mem", "gpu", "eph", "pods")}
    alloc_scalar = np.zeros((n, s), dtype=np.int64)
    cond_bits = np.zeros(n, dtype=np.int64)
    mem_pressure = np.zeros(n, dtype=bool)
    disk_pressure = np.zeros(n, dtype=bool)
    for i, node in enumerate(nodes):
        ni = node_infos[i]
        row = node_static_row(node, ni, scalar_idx, s)
        alloc["cpu"][i], alloc["mem"][i], alloc["gpu"][i] = row[0], row[1], row[2]
        alloc["eph"][i], alloc["pods"][i] = row[3], row[4]
        alloc_scalar[i] = row[5]
        cond_bits[i], mem_pressure[i], disk_pressure[i] = row[6], row[7], row[8]

    statics = NodeStatics(
        names=[nd.name for nd in nodes],
        alloc_cpu=alloc["cpu"], alloc_mem=alloc["mem"], alloc_gpu=alloc["gpu"],
        alloc_eph=alloc["eph"], allowed_pods=alloc["pods"],
        alloc_scalar=alloc_scalar, cond_fail_bits=cond_bits,
        mem_pressure=mem_pressure, disk_pressure=disk_pressure)

    # --- pod columns + signature interning ---
    p = len(pods)
    cols = PodColumns(
        req_cpu=np.zeros(p, dtype=np.int64), req_mem=np.zeros(p, dtype=np.int64),
        req_gpu=np.zeros(p, dtype=np.int64), req_eph=np.zeros(p, dtype=np.int64),
        req_scalar=np.zeros((p, s), dtype=np.int64),
        nz_cpu=np.zeros(p, dtype=np.int64), nz_mem=np.zeros(p, dtype=np.int64),
        zero_request=np.zeros(p, dtype=bool), best_effort=np.zeros(p, dtype=bool),
        sel_id=np.zeros(p, dtype=np.int32), tol_id=np.zeros(p, dtype=np.int32),
        aff_id=np.zeros(p, dtype=np.int32), avoid_id=np.zeros(p, dtype=np.int32),
        host_id=np.zeros(p, dtype=np.int32), group_id=np.zeros(p, dtype=np.int32),
        img_id=np.zeros(p, dtype=np.int32),
        sa_self_id=np.zeros(p, dtype=np.int32))

    sel_i, tol_i, aff_i, avoid_i, host_i = (Interner() for _ in range(5))
    unsupported: List[str] = []
    for j, pod in enumerate(pods):
        fill_pod_request_row(cols, j, pod, pod_requests[j], scalar_idx)
        cols.sel_id[j] = sel_i.intern(_selector_signature(pod), pod)
        cols.tol_id[j] = tol_i.intern(_toleration_signature(pod), pod)
        cols.aff_id[j] = aff_i.intern(_affinity_signature(pod), pod)
        cols.avoid_id[j] = avoid_i.intern(_avoid_signature(pod), pod)
        cols.host_id[j] = host_i.intern(_host_signature(pod), pod)

    node_index = {nd.name: i for i, nd in enumerate(nodes)}
    (groups, has_ports, has_services, has_interpod, n_topo_doms, n_zone_doms,
     group_unsupported, _, vol_meta) = _compile_groups(snapshot, pods, nodes,
                                                       node_index,
                                                       need_saa=need_saa)
    has_disk_conflict, has_maxpd, has_vol_zone, maxpd_limits = vol_meta
    unsupported.extend(group_unsupported)
    cols.group_id = groups.group_of_pod

    # --- static [signature, node] tables ---
    row_fns = signature_row_fns(nodes, node_infos)

    def table(interner: Interner, kind: str):
        fn, dtype = row_fns[kind]
        t = np.zeros((max(len(interner), 1), n), dtype=dtype)
        for sig_id, rep in enumerate(interner.representatives):
            for i in range(n):
                t[sig_id, i] = fn(rep, i)
        return t

    tables = SignatureTables(
        selector_ok=table(sel_i, "selector_ok"),
        taint_ok=table(tol_i, "taint_ok"),
        taint_ok_noexec=(table(tol_i, "taint_ok_noexec") if need_noexec else
                         np.ones((max(len(tol_i), 1), n), dtype=bool)),
        intolerable=table(tol_i, "intolerable"),
        affinity_count=table(aff_i, "affinity_count"),
        avoid_score=table(avoid_i, "avoid_score"),
        host_ok=table(host_i, "host_ok"),
    )

    # --- dynamic aggregates from pre-scheduled pods ---
    dyn = DynamicInit(
        used_cpu=np.zeros(n, dtype=np.int64), used_mem=np.zeros(n, dtype=np.int64),
        used_gpu=np.zeros(n, dtype=np.int64), used_eph=np.zeros(n, dtype=np.int64),
        used_scalar=np.zeros((n, s), dtype=np.int64),
        nonzero_cpu=np.zeros(n, dtype=np.int64), nonzero_mem=np.zeros(n, dtype=np.int64),
        pod_count=np.zeros(n, dtype=np.int64))
    for k, existing in enumerate(snapshot.pods):
        i = node_index.get(existing.spec.node_name)
        if i is None:
            continue
        req = existing_requests[k]
        dyn.used_cpu[i] += req.milli_cpu
        dyn.used_mem[i] += req.memory
        dyn.used_gpu[i] += req.nvidia_gpu
        dyn.used_eph[i] += req.ephemeral_storage
        for name, v in req.scalar.items():
            dyn.used_scalar[i, scalar_idx[name]] += v
        nz = get_nonzero_pod_request(existing)
        dyn.nonzero_cpu[i] += nz.milli_cpu
        dyn.nonzero_mem[i] += nz.memory
        dyn.pod_count[i] += 1

    compiled = CompiledCluster(statics=statics, tables=tables, groups=groups,
                               dynamic=dyn, scalar_names=scalar_names,
                               node_index=node_index,
                               has_ports=has_ports, has_services=has_services,
                               has_interpod=has_interpod,
                               has_disk_conflict=has_disk_conflict,
                               has_maxpd=has_maxpd, has_vol_zone=has_vol_zone,
                               has_noexec_table=need_noexec,
                               has_saa_table=need_saa,
                               maxpd_limits=maxpd_limits,
                               n_topo_doms=n_topo_doms, n_zone_doms=n_zone_doms,
                               unsupported=unsupported)
    return compiled, cols


def reason_strings(scalar_names: List[str]) -> List[str]:
    return REASON_STRINGS + [f"Insufficient {name}" for name in scalar_names]


def victim_order_columns(pods: List, node_index: dict):
    """Victim-ordering columns for device-side preemption (jaxe/preempt.py
    _VictimTable seed): one row per PLACED pod of `pods`, in list order.

    Row order is the parity-critical part: the host oracle's
    sort_by_priority_desc over NodeInfo.pods is a STABLE sort, and
    NodeInfo.pods is append-ordered (snapshot order, then bind order), so a
    table seeded in snapshot order and appended to on every bind reproduces
    the host's victim ordering with a stable (-priority, row) lexsort.

    Returns (node_i int32[R], prio int64[R], req int64[R, 4] —
    cpu/mem/gpu/eph in get_resource_request units — and the row-parallel
    list of pod objects). Pods without a known node are skipped (they can
    never be victims: victim selection only reads NodeInfo.pods)."""
    from tpusim.engine.resources import get_resource_request
    from tpusim.engine.util import get_pod_priority

    rows = [(node_index[p.spec.node_name], p) for p in pods
            if p.spec.node_name and p.spec.node_name in node_index]
    r = len(rows)
    node_i = np.zeros(r, dtype=np.int32)
    prio = np.zeros(r, dtype=np.int64)
    req = np.zeros((r, 4), dtype=np.int64)
    objs = []
    for k, (i, p) in enumerate(rows):
        node_i[k] = i
        prio[k] = get_pod_priority(p)
        pr = get_resource_request(p)
        req[k, 0] = pr.milli_cpu
        req[k, 1] = pr.memory
        req[k, 2] = pr.nvidia_gpu
        req[k, 3] = pr.ephemeral_storage
        objs.append(p)
    return node_i, prio, req, objs

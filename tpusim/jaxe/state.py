"""Columnar cluster state: SoA arrays + signature interning + static tables.

Reference mapping (SURVEY.md §7 step 2): NodeInfo's cached aggregates
(schedulercache/node_info.go:35-76) become per-node column vectors; the
symbolic pod features become interned signature ids with precompiled
[signature, node] tables (see tpusim/jaxe/__init__.py design note).
"""

from __future__ import annotations

from dataclasses import dataclass, field
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import (
    LABEL_HOSTNAME,
    TAINT_PREFER_NO_SCHEDULE,
    Node,
    Pod,
    find_matching_untolerated_taint,
    tolerations_tolerate_taint,
)
from tpusim.engine.predicates import (
    get_namespaces_from_pod_affinity_term,
    get_pod_affinity_terms,
    get_pod_anti_affinity_terms,
    pod_matches_node_labels,
    pod_matches_term_namespace_and_selector,
)
from tpusim.engine.priorities import (
    calculate_node_affinity_priority_map,
    calculate_node_prefer_avoid_pods_priority_map,
    get_zone_key,
)
from tpusim.engine.resources import (
    NodeInfo,
    get_nonzero_pod_request,
    get_resource_request,
    is_pod_best_effort,
)

# ---------------------------------------------------------------------------
# failure reason bit layout (decoded back to error.go strings for the report)
# ---------------------------------------------------------------------------

BIT_NODE_NOT_READY = 0
BIT_NODE_OUT_OF_DISK = 1
BIT_NODE_NETWORK_UNAVAILABLE = 2
BIT_NODE_UNSCHEDULABLE = 3
BIT_INSUFFICIENT_PODS = 4
BIT_INSUFFICIENT_CPU = 5
BIT_INSUFFICIENT_MEMORY = 6
BIT_INSUFFICIENT_GPU = 7
BIT_INSUFFICIENT_EPHEMERAL = 8
BIT_HOSTNAME_MISMATCH = 9
BIT_NODE_SELECTOR_MISMATCH = 10
BIT_TAINTS_NOT_TOLERATED = 11
BIT_MEMORY_PRESSURE = 12
BIT_DISK_PRESSURE = 13
BIT_HOST_PORTS = 14
BIT_AFFINITY_NOT_MATCH = 15     # MatchInterPodAffinity umbrella reason
BIT_EXISTING_ANTI_AFFINITY = 16
BIT_AFFINITY_RULES = 17
BIT_ANTI_AFFINITY_RULES = 18
NUM_FIXED_BITS = 19
# bits >= NUM_FIXED_BITS: Insufficient <scalar resource s>, per interned name

REASON_STRINGS = [
    "node(s) were not ready",
    "node(s) were out of disk space",
    "node(s) had unavailable network",
    "node(s) were unschedulable",
    "Insufficient pods",
    "Insufficient cpu",
    "Insufficient memory",
    "Insufficient alpha.kubernetes.io/nvidia-gpu",
    "Insufficient ephemeral-storage",
    "node(s) didn't match the requested hostname",
    "node(s) didn't match node selector",
    "node(s) had taints that the pod didn't tolerate",
    "node(s) had memory pressure",
    "node(s) had disk pressure",
    "node(s) didn't have free ports for the requested pod ports",
    "node(s) didn't match pod affinity/anti-affinity",
    "node(s) didn't satisfy existing pods anti-affinity rules",
    "node(s) didn't match pod affinity rules",
    "node(s) didn't match pod anti-affinity rules",
]

# pod-group tables become O(G^2)/O(G^2·T): past this the backend falls back
MAX_GROUPS = 512


def volume_unsupported(new_pods: List[Pod], cluster_pods) -> List[str]:
    """Volume predicates are host-side for now (NoDiskConflict /
    MaxPDVolumeCount / NoVolumeZoneConflict read PV/PVC state and per-node
    mounted-volume sets): volume-using workloads route to the parity engine so
    placements stay identical. Shared by compile_cluster and the incremental
    path (delta.py) so the two can't drift."""
    if any(p.spec.volumes for p in new_pods) \
            or any(p.spec.volumes for p in cluster_pods):
        return ["pod volumes (NoDiskConflict/MaxPDVolumeCount/"
                "NoVolumeZoneConflict/CheckVolumeBinding)"]
    return []


class Interner:
    """Canonical-JSON signature -> dense id."""

    def __init__(self):
        self._ids: Dict[str, int] = {}
        self.representatives: List[Pod] = []

    def intern(self, signature, representative) -> int:
        key = json.dumps(signature, sort_keys=True, default=str)
        if key not in self._ids:
            self._ids[key] = len(self.representatives)
            self.representatives.append(representative)
        return self._ids[key]

    def __len__(self) -> int:
        return len(self.representatives)


@dataclass
class NodeStatics:
    """Per-node static columns (never mutated by binds)."""

    names: List[str]
    alloc_cpu: np.ndarray        # [N] int64, milli
    alloc_mem: np.ndarray        # [N] int64, bytes
    alloc_gpu: np.ndarray        # [N] int64
    alloc_eph: np.ndarray        # [N] int64
    allowed_pods: np.ndarray     # [N] int64
    alloc_scalar: np.ndarray     # [N, S] int64
    cond_fail_bits: np.ndarray   # [N] int64 (condition+unschedulable reason bits)
    mem_pressure: np.ndarray     # [N] bool
    disk_pressure: np.ndarray    # [N] bool


@dataclass
class SignatureTables:
    """[signature, node] static evaluation tables."""

    selector_ok: np.ndarray      # [Csel, N] bool — nodeSelector + required node affinity
    taint_ok: np.ndarray         # [Ctol, N] bool — NoSchedule/NoExecute taints tolerated
    intolerable: np.ndarray      # [Ctol, N] int64 — PreferNoSchedule intolerable count
    affinity_count: np.ndarray   # [Caff, N] int64 — preferred node-affinity weight sum
    avoid_score: np.ndarray      # [Cavoid, N] int64 — NodePreferAvoidPods (0 or 10)
    host_ok: np.ndarray          # [Chost, N] bool — spec.nodeName pin


@dataclass
class GroupTables:
    """Pod-group tables for the features whose state depends on which pods sit
    where: host ports (predicates.go:1019-1039), SelectorSpreadPriority
    (selector_spreading.go:66-175), and inter-pod (anti)affinity
    (predicates.go:1125-1450, interpod_affinity.go).

    A "group" is an interned (namespace, labels, pod-(anti)affinity, host-ports)
    pod signature over new + placed-existing pods; the device carries a
    presence[G, N] count matrix plus per-topology-domain sums, and all symbolic
    matching below is precompiled host-side with the parity engine's matchers.

    Topology domains: for each used topologyKey k, topo_dom[k, n] interns the
    node's label value, with 0 reserved for "label missing" (never matches,
    NodesHaveSameTopologyKey semantics). zone_dom likewise interns
    utilnode.GetZoneKey with 0 = no zone. Term tensors are padded on the term
    axis with valid=False rows; match[a, t, b] means "a pod of group b matches
    (namespaces+selector of) term t defined by group a"."""

    group_of_pod: np.ndarray     # [P] int32 — new pods' group ids
    presence: np.ndarray         # [G, N] int32 — placed existing pods per group
    port_conflict: np.ndarray    # [G, G] bool — wanted ports of a hit ports of b
    ss_match: np.ndarray         # [G, G] bool — b counts toward a's spread score
    zone_dom: np.ndarray         # [N] int32
    topo_dom: np.ndarray         # [K, N] int32
    aff_valid: np.ndarray        # [G, Ta] bool — required pod-affinity terms
    aff_err: np.ndarray          # [G] bool — any term with empty topologyKey
    aff_empty: np.ndarray        # [G, Ta] bool — per-term empty topologyKey
    aff_match: np.ndarray        # [G, Ta, G] bool
    aff_key: np.ndarray          # [G, Ta] int32 (into K)
    aff_hostname: np.ndarray     # [G, Ta] bool — topologyKey == kubernetes.io/hostname
    aff_self: np.ndarray         # [G, Ta] bool — the pod matches its own term
    aff_unplaced: np.ndarray     # [G, Ta] bool — an unplaced snapshot pod matches
    anti_valid: np.ndarray       # [G, Tb] bool — required pod-anti-affinity terms
    anti_err: np.ndarray         # [G] bool
    anti_empty: np.ndarray       # [G, Tb] bool
    anti_match: np.ndarray       # [G, Tb, G] bool
    anti_key: np.ndarray         # [G, Tb] int32
    anti_hostname: np.ndarray    # [G, Tb] bool
    pref_w: np.ndarray           # [G, Tp] float64 — preferred terms, signed weight
    pref_match: np.ndarray       # [G, Tp, G] bool
    pref_key: np.ndarray         # [G, Tp] int32


@dataclass
class PodColumns:
    """Per-pod numeric columns + signature ids (the scan's xs)."""

    req_cpu: np.ndarray          # [P] int64 milli
    req_mem: np.ndarray          # [P] int64
    req_gpu: np.ndarray          # [P] int64
    req_eph: np.ndarray          # [P] int64
    req_scalar: np.ndarray       # [P, S] int64
    nz_cpu: np.ndarray           # [P] int64 (non-zero-default cpu, priorities only)
    nz_mem: np.ndarray           # [P] int64
    zero_request: np.ndarray     # [P] bool (PodFitsResources fast path)
    best_effort: np.ndarray      # [P] bool
    sel_id: np.ndarray           # [P] int32
    tol_id: np.ndarray           # [P] int32
    aff_id: np.ndarray           # [P] int32
    avoid_id: np.ndarray         # [P] int32
    host_id: np.ndarray          # [P] int32
    group_id: np.ndarray         # [P] int32 — pod-group id (GroupTables)


@dataclass
class DynamicInit:
    """Mutable aggregates seeded from pre-scheduled snapshot pods
    (NodeInfo.AddPod accounting, node_info.go:318-398)."""

    used_cpu: np.ndarray         # [N] int64
    used_mem: np.ndarray
    used_gpu: np.ndarray
    used_eph: np.ndarray
    used_scalar: np.ndarray      # [N, S] int64
    nonzero_cpu: np.ndarray      # [N] int64
    nonzero_mem: np.ndarray
    pod_count: np.ndarray        # [N] int64


@dataclass
class CompiledCluster:
    statics: NodeStatics
    tables: SignatureTables
    groups: GroupTables
    dynamic: DynamicInit
    scalar_names: List[str]
    node_index: Dict[str, int]
    has_ports: bool = False
    has_services: bool = False
    has_interpod: bool = False
    n_topo_doms: int = 1         # segment count for topo_dom (incl. invalid 0)
    n_zone_doms: int = 1
    unsupported: List[str] = field(default_factory=list)  # features needing fallback


def _selector_signature(pod: Pod):
    aff = pod.spec.affinity
    na = aff.node_affinity.to_obj() if (aff and aff.node_affinity) else None
    return {"nodeSelector": pod.spec.node_selector,
            "required": (na or {}).get("requiredDuringSchedulingIgnoredDuringExecution")}


def _toleration_signature(pod: Pod):
    return {"tolerations": [t.to_obj() for t in pod.spec.tolerations]}


def _affinity_signature(pod: Pod):
    aff = pod.spec.affinity
    na = aff.node_affinity.to_obj() if (aff and aff.node_affinity) else None
    return {"preferred": (na or {}).get("preferredDuringSchedulingIgnoredDuringExecution")}


def _avoid_signature(pod: Pod):
    ref = pod.metadata.controller_ref()
    if ref is None or ref.kind not in ("ReplicationController", "ReplicaSet"):
        return None
    return {"kind": ref.kind, "uid": ref.uid}


def _host_signature(pod: Pod):
    return pod.spec.node_name or None


# ---------------------------------------------------------------------------
# pod-group compilation (host ports / selector spreading / inter-pod affinity)
# ---------------------------------------------------------------------------

_ANY_IP = "0.0.0.0"


def _sanitized_ports(pod: Pod) -> list:
    """Wanted (ip, protocol, port) triples, HostPortInfo-sanitized
    (util/utils.go:51-137: ip defaults 0.0.0.0, protocol TCP, port>0 only)."""
    out = set()
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                out.add((p.host_ip or _ANY_IP, p.protocol or "TCP", p.host_port))
    return sorted(out)


def _ports_conflict(wants: list, occupied: list) -> bool:
    """check_conflict over a full pod pair: 0.0.0.0 wildcards either side."""
    for wip, wproto, wport in wants:
        for oip, oproto, oport in occupied:
            if (wport == oport and wproto == oproto
                    and (wip == _ANY_IP or oip == _ANY_IP or wip == oip)):
                return True
    return False


def _group_signature(pod: Pod):
    aff = pod.spec.affinity
    return {
        "ns": pod.namespace,
        "labels": pod.metadata.labels,
        "aff": aff.pod_affinity.to_obj() if (aff and aff.pod_affinity) else None,
        "anti": (aff.pod_anti_affinity.to_obj()
                 if (aff and aff.pod_anti_affinity) else None),
        "ports": _sanitized_ports(pod),
    }


def _has_interpod_terms(pod: Pod) -> bool:
    a = pod.spec.affinity
    return a is not None and (a.pod_affinity is not None
                              or a.pod_anti_affinity is not None)


def _req_aff_terms(pod: Pod) -> list:
    a = pod.spec.affinity
    return get_pod_affinity_terms(a.pod_affinity) if a else []


def _req_anti_terms(pod: Pod) -> list:
    a = pod.spec.affinity
    return get_pod_anti_affinity_terms(a.pod_anti_affinity) if a else []


def _pref_terms(pod: Pod) -> list:
    """Signed (weight, term): preferred affinity positive, anti negative
    (interpod_affinity.go processWeightedTerms multipliers)."""
    a = pod.spec.affinity
    out = []
    if a and a.pod_affinity:
        out += [(wt.weight, wt.pod_affinity_term) for wt in a.pod_affinity.preferred]
    if a and a.pod_anti_affinity:
        out += [(-wt.weight, wt.pod_affinity_term)
                for wt in a.pod_anti_affinity.preferred]
    return out


def _trivial_groups(num_pods: int, n: int) -> "GroupTables":
    z = np.zeros
    return GroupTables(
        group_of_pod=z(num_pods, np.int32), presence=z((1, n), np.int32),
        port_conflict=z((1, 1), bool), ss_match=z((1, 1), bool),
        zone_dom=z(n, np.int32), topo_dom=z((1, n), np.int32),
        aff_valid=z((1, 1), bool), aff_err=z(1, bool), aff_empty=z((1, 1), bool),
        aff_match=z((1, 1, 1), bool), aff_key=z((1, 1), np.int32),
        aff_hostname=z((1, 1), bool), aff_self=z((1, 1), bool),
        aff_unplaced=z((1, 1), bool),
        anti_valid=z((1, 1), bool), anti_err=z(1, bool), anti_empty=z((1, 1), bool),
        anti_match=z((1, 1, 1), bool), anti_key=z((1, 1), np.int32),
        anti_hostname=z((1, 1), bool),
        pref_w=z((1, 1), np.float64), pref_match=z((1, 1, 1), bool),
        pref_key=z((1, 1), np.int32))


def _compile_groups(snapshot: ClusterSnapshot, pods: List[Pod],
                    nodes: List[Node], node_index: Dict[str, int]):
    """Build GroupTables + feature flags. Returns
    (tables, has_ports, has_services, has_interpod, n_topo_doms, n_zone_doms,
    unsupported)."""
    n = len(nodes)
    placed = [p for p in snapshot.pods if p.spec.node_name in node_index]
    # pods with an unknown-but-set nodeName still count for "matching pod
    # exists"; nodeName-less (pending) pods are dropped by the reference's pod
    # lister (backends.py scheduled-pod filter) and must not count
    unplaced = [p for p in snapshot.pods
                if p.spec.node_name and p.spec.node_name not in node_index]

    has_ports = any(_sanitized_ports(p) for p in pods) \
        or any(_sanitized_ports(p) for p in placed)
    has_interpod = any(_has_interpod_terms(p) for p in pods) \
        or any(_has_interpod_terms(p) for p in placed)
    has_services = bool(snapshot.services)
    if not (has_ports or has_interpod or has_services):
        return _trivial_groups(len(pods), n), False, False, False, 1, 1, []

    gi = Interner()
    group_of_pod = np.array([gi.intern(_group_signature(p), p) for p in pods],
                            dtype=np.int32)
    placed_gid = [gi.intern(_group_signature(p), p) for p in placed]
    g = len(gi)
    if g > MAX_GROUPS:
        return (_trivial_groups(len(pods), n), False, False, False, 1, 1,
                [f"{g} distinct pod groups exceed the jax backend limit "
                 f"({MAX_GROUPS})"])
    reps = gi.representatives

    presence = np.zeros((g, n), dtype=np.int32)
    for gid, p in zip(placed_gid, placed):
        presence[gid, node_index[p.spec.node_name]] += 1

    port_conflict = np.zeros((g, g), dtype=bool)
    if has_ports:
        ports_of = [_sanitized_ports(rep) for rep in reps]
        for a in range(g):
            if not ports_of[a]:
                continue
            for b in range(g):
                port_conflict[a, b] = bool(ports_of[b]) and _ports_conflict(
                    ports_of[a], ports_of[b])

    ss_match = np.zeros((g, g), dtype=bool)
    zone_dom = np.zeros(n, dtype=np.int32)
    n_zone_doms = 1
    if has_services:
        # selectors of group a: services in a's namespace selecting a's labels
        # (selector_spreading.go getSelectors; the simulator wires only the
        # services informer with real data, simulator.go:352-366)
        selectors_of = []
        for rep in reps:
            sels = []
            for svc in snapshot.services:
                if (svc.namespace == rep.namespace and svc.selector
                        and all(rep.metadata.labels.get(k) == v
                                for k, v in svc.selector.items())):
                    sels.append(dict(svc.selector))
            selectors_of.append(sels)
        for a in range(g):
            if not selectors_of[a]:
                continue
            for b in range(g):
                ss_match[a, b] = reps[b].namespace == reps[a].namespace and any(
                    all(reps[b].metadata.labels.get(k) == v for k, v in sel.items())
                    for sel in selectors_of[a])
        zvals: Dict[str, int] = {}
        for i, node in enumerate(nodes):
            z = get_zone_key(node)
            if z:
                zone_dom[i] = zvals.setdefault(z, len(zvals) + 1)
        n_zone_doms = len(zvals) + 1

    # --- inter-pod affinity term tensors ---
    topo_keys: List[str] = []
    if has_interpod:
        seen_keys = set()
        for rep in reps:
            for term in _req_aff_terms(rep) + _req_anti_terms(rep):
                if term.topology_key and term.topology_key not in seen_keys:
                    seen_keys.add(term.topology_key)
                    topo_keys.append(term.topology_key)
            for _, term in _pref_terms(rep):
                if term.topology_key and term.topology_key not in seen_keys:
                    seen_keys.add(term.topology_key)
                    topo_keys.append(term.topology_key)
    k_count = max(len(topo_keys), 1)
    key_idx = {key: i for i, key in enumerate(topo_keys)}

    topo_dom = np.zeros((k_count, n), dtype=np.int32)
    n_topo_doms = 1
    for k, key in enumerate(topo_keys):
        vals: Dict[str, int] = {}
        for i, node in enumerate(nodes):
            v = node.metadata.labels.get(key)
            if v is not None:
                topo_dom[k, i] = vals.setdefault(v, len(vals) + 1)
        n_topo_doms = max(n_topo_doms, len(vals) + 1)

    ta = max([1] + [len(_req_aff_terms(r)) for r in reps])
    tb = max([1] + [len(_req_anti_terms(r)) for r in reps])
    tp = max([1] + [len(_pref_terms(r)) for r in reps])
    aff_valid = np.zeros((g, ta), bool)
    aff_err = np.zeros(g, bool)
    aff_empty = np.zeros((g, ta), bool)
    aff_match = np.zeros((g, ta, g), bool)
    aff_key = np.zeros((g, ta), np.int32)
    aff_hostname = np.zeros((g, ta), bool)
    aff_self = np.zeros((g, ta), bool)
    aff_unplaced = np.zeros((g, ta), bool)
    anti_valid = np.zeros((g, tb), bool)
    anti_err = np.zeros(g, bool)
    anti_empty = np.zeros((g, tb), bool)
    anti_match = np.zeros((g, tb, g), bool)
    anti_key = np.zeros((g, tb), np.int32)
    anti_hostname = np.zeros((g, tb), bool)
    pref_w = np.zeros((g, tp), np.float64)
    pref_match = np.zeros((g, tp, g), bool)
    pref_key = np.zeros((g, tp), np.int32)

    if has_interpod:
        for a, rep in enumerate(reps):
            for t, term in enumerate(_req_aff_terms(rep)):
                aff_valid[a, t] = True
                namespaces = get_namespaces_from_pod_affinity_term(rep, term)
                if not term.topology_key:
                    # _any_pod_matches_term raises -> whole predicate fails
                    aff_empty[a, t] = True
                    aff_err[a] = True
                else:
                    aff_key[a, t] = key_idx[term.topology_key]
                    aff_hostname[a, t] = term.topology_key == LABEL_HOSTNAME
                aff_self[a, t] = pod_matches_term_namespace_and_selector(
                    rep, namespaces, term.label_selector)
                aff_unplaced[a, t] = any(
                    pod_matches_term_namespace_and_selector(
                        u, namespaces, term.label_selector) for u in unplaced)
                for b, other in enumerate(reps):
                    aff_match[a, t, b] = pod_matches_term_namespace_and_selector(
                        other, namespaces, term.label_selector)
            for t, term in enumerate(_req_anti_terms(rep)):
                anti_valid[a, t] = True
                namespaces = get_namespaces_from_pod_affinity_term(rep, term)
                if not term.topology_key:
                    anti_empty[a, t] = True
                    anti_err[a] = True
                else:
                    anti_key[a, t] = key_idx[term.topology_key]
                    anti_hostname[a, t] = term.topology_key == LABEL_HOSTNAME
                for b, other in enumerate(reps):
                    anti_match[a, t, b] = pod_matches_term_namespace_and_selector(
                        other, namespaces, term.label_selector)
            for t, (w, term) in enumerate(_pref_terms(rep)):
                if not term.topology_key:
                    continue  # NodesHaveSameTopologyKey("") is always False
                pref_w[a, t] = float(w)
                pref_key[a, t] = key_idx[term.topology_key]
                namespaces = get_namespaces_from_pod_affinity_term(rep, term)
                for b, other in enumerate(reps):
                    pref_match[a, t, b] = pod_matches_term_namespace_and_selector(
                        other, namespaces, term.label_selector)

    tables = GroupTables(
        group_of_pod=group_of_pod, presence=presence,
        port_conflict=port_conflict, ss_match=ss_match,
        zone_dom=zone_dom, topo_dom=topo_dom,
        aff_valid=aff_valid, aff_err=aff_err, aff_empty=aff_empty,
        aff_match=aff_match, aff_key=aff_key, aff_hostname=aff_hostname,
        aff_self=aff_self, aff_unplaced=aff_unplaced,
        anti_valid=anti_valid, anti_err=anti_err, anti_empty=anti_empty,
        anti_match=anti_match, anti_key=anti_key, anti_hostname=anti_hostname,
        pref_w=pref_w, pref_match=pref_match, pref_key=pref_key)
    return (tables, has_ports, has_services, has_interpod,
            n_topo_doms, n_zone_doms, [])


def node_static_row(node: Node, ni: NodeInfo, scalar_idx: Dict[str, int],
                    s: int):
    """One node's static column values (shared with the incremental path):
    (cpu, mem, gpu, eph, pods, scalar_row[s], cond_bits, mem_p, disk_p)."""
    r = ni.allocatable_resource
    scalar_row = np.zeros(s, dtype=np.int64)
    for name, v in r.scalar.items():
        scalar_row[scalar_idx[name]] = v
    bits = 0
    for cond in node.status.conditions:
        if cond.type == "Ready" and cond.status != "True":
            bits |= 1 << BIT_NODE_NOT_READY
        elif cond.type == "OutOfDisk" and cond.status != "False":
            bits |= 1 << BIT_NODE_OUT_OF_DISK
        elif cond.type == "NetworkUnavailable" and cond.status != "False":
            bits |= 1 << BIT_NODE_NETWORK_UNAVAILABLE
    if node.spec.unschedulable:
        bits |= 1 << BIT_NODE_UNSCHEDULABLE
    return (r.milli_cpu, r.memory, r.nvidia_gpu, r.ephemeral_storage,
            r.allowed_pod_number, scalar_row, bits, ni.memory_pressure,
            ni.disk_pressure)


def signature_row_fns(nodes: List[Node], node_infos: List["NodeInfo"]):
    """Per-signature-table row evaluators: kind -> (fn(rep, node_idx), dtype).

    Shared by compile_cluster and the incremental delta path (delta.py), so
    both compute table cells with exactly the same engine matchers. The
    sel/tol/aff/avoid/host interner each table reads from is fixed:
    selector_ok<-sel, taint_ok+intolerable<-tol, affinity_count<-aff,
    avoid_score<-avoid, host_ok<-host."""

    def selector_fn(rep: Optional[Pod], i: int) -> bool:
        return pod_matches_node_labels(rep, nodes[i])

    def taint_ok_fn(rep: Pod, i: int) -> bool:
        return find_matching_untolerated_taint(
            node_infos[i].taints, rep.spec.tolerations,
            lambda t: t.effect in ("NoSchedule", "NoExecute")) is None

    def intolerable_fn(rep: Pod, i: int) -> int:
        tols = [t for t in rep.spec.tolerations
                if not t.effect or t.effect == TAINT_PREFER_NO_SCHEDULE]
        return sum(1 for taint in node_infos[i].taints
                   if taint.effect == TAINT_PREFER_NO_SCHEDULE
                   and not tolerations_tolerate_taint(tols, taint))

    def affinity_fn(rep: Pod, i: int) -> int:
        return calculate_node_affinity_priority_map(rep, None, node_infos[i]).score

    def avoid_fn(rep: Pod, i: int) -> int:
        return calculate_node_prefer_avoid_pods_priority_map(rep, None, node_infos[i]).score

    def host_fn(rep: Pod, i: int) -> bool:
        return (not rep.spec.node_name) or rep.spec.node_name == nodes[i].name

    return {
        "selector_ok": (selector_fn, bool),
        "taint_ok": (taint_ok_fn, bool),
        "intolerable": (intolerable_fn, np.int64),
        "affinity_count": (affinity_fn, np.int64),
        "avoid_score": (avoid_fn, np.int64),
        "host_ok": (host_fn, bool),
    }


def fill_pod_request_row(cols: PodColumns, j: int, pod: Pod, req,
                         scalar_idx: Dict[str, int]) -> None:
    """Fill one pod's numeric request columns (shared with delta.py so the
    incremental path can never drift from the fresh-compile semantics)."""
    cols.req_cpu[j] = req.milli_cpu
    cols.req_mem[j] = req.memory
    cols.req_gpu[j] = req.nvidia_gpu
    cols.req_eph[j] = req.ephemeral_storage
    for name, v in req.scalar.items():
        cols.req_scalar[j, scalar_idx[name]] = v
    cols.zero_request[j] = (req.milli_cpu == 0 and req.memory == 0
                            and req.nvidia_gpu == 0 and req.ephemeral_storage == 0
                            and not req.scalar)
    nz = get_nonzero_pod_request(pod)
    cols.nz_cpu[j] = nz.milli_cpu
    cols.nz_mem[j] = nz.memory
    cols.best_effort[j] = is_pod_best_effort(pod)


def compile_cluster(snapshot: ClusterSnapshot, pods: List[Pod]) -> Tuple[CompiledCluster, PodColumns]:
    """Build columnar state for `pods` scheduled against `snapshot`.

    Static matching reuses the parity engine's own functions (semantics match
    by construction); only numeric aggregates stay dynamic.
    """
    nodes = snapshot.nodes
    n = len(nodes)

    # single pass: NodeInfos, per-pod requests, and the scalar name space
    node_infos: List[NodeInfo] = []
    for node in nodes:
        ni = NodeInfo()
        ni.set_node(node)
        node_infos.append(ni)
    pod_requests = [get_resource_request(pod) for pod in pods]
    existing_requests = [get_resource_request(pod) for pod in snapshot.pods]

    scalar_names: List[str] = []
    seen = set()

    def _note_scalars(names):
        for name in names:
            if name not in seen:
                seen.add(name)
                scalar_names.append(name)

    for req in pod_requests + existing_requests:
        _note_scalars(req.scalar)
    for ni in node_infos:
        _note_scalars(ni.allocatable_resource.scalar)
    s = len(scalar_names)
    scalar_idx = {name: i for i, name in enumerate(scalar_names)}

    # --- node statics ---
    alloc = {k: np.zeros(n, dtype=np.int64)
             for k in ("cpu", "mem", "gpu", "eph", "pods")}
    alloc_scalar = np.zeros((n, s), dtype=np.int64)
    cond_bits = np.zeros(n, dtype=np.int64)
    mem_pressure = np.zeros(n, dtype=bool)
    disk_pressure = np.zeros(n, dtype=bool)
    for i, node in enumerate(nodes):
        ni = node_infos[i]
        row = node_static_row(node, ni, scalar_idx, s)
        alloc["cpu"][i], alloc["mem"][i], alloc["gpu"][i] = row[0], row[1], row[2]
        alloc["eph"][i], alloc["pods"][i] = row[3], row[4]
        alloc_scalar[i] = row[5]
        cond_bits[i], mem_pressure[i], disk_pressure[i] = row[6], row[7], row[8]

    statics = NodeStatics(
        names=[nd.name for nd in nodes],
        alloc_cpu=alloc["cpu"], alloc_mem=alloc["mem"], alloc_gpu=alloc["gpu"],
        alloc_eph=alloc["eph"], allowed_pods=alloc["pods"],
        alloc_scalar=alloc_scalar, cond_fail_bits=cond_bits,
        mem_pressure=mem_pressure, disk_pressure=disk_pressure)

    # --- pod columns + signature interning ---
    p = len(pods)
    cols = PodColumns(
        req_cpu=np.zeros(p, dtype=np.int64), req_mem=np.zeros(p, dtype=np.int64),
        req_gpu=np.zeros(p, dtype=np.int64), req_eph=np.zeros(p, dtype=np.int64),
        req_scalar=np.zeros((p, s), dtype=np.int64),
        nz_cpu=np.zeros(p, dtype=np.int64), nz_mem=np.zeros(p, dtype=np.int64),
        zero_request=np.zeros(p, dtype=bool), best_effort=np.zeros(p, dtype=bool),
        sel_id=np.zeros(p, dtype=np.int32), tol_id=np.zeros(p, dtype=np.int32),
        aff_id=np.zeros(p, dtype=np.int32), avoid_id=np.zeros(p, dtype=np.int32),
        host_id=np.zeros(p, dtype=np.int32), group_id=np.zeros(p, dtype=np.int32))

    sel_i, tol_i, aff_i, avoid_i, host_i = (Interner() for _ in range(5))
    unsupported: List[str] = []
    unsupported.extend(volume_unsupported(pods, snapshot.pods))
    for j, pod in enumerate(pods):
        fill_pod_request_row(cols, j, pod, pod_requests[j], scalar_idx)
        cols.sel_id[j] = sel_i.intern(_selector_signature(pod), pod)
        cols.tol_id[j] = tol_i.intern(_toleration_signature(pod), pod)
        cols.aff_id[j] = aff_i.intern(_affinity_signature(pod), pod)
        cols.avoid_id[j] = avoid_i.intern(_avoid_signature(pod), pod)
        cols.host_id[j] = host_i.intern(_host_signature(pod), pod)

    node_index = {nd.name: i for i, nd in enumerate(nodes)}
    (groups, has_ports, has_services, has_interpod, n_topo_doms, n_zone_doms,
     group_unsupported) = _compile_groups(snapshot, pods, nodes, node_index)
    unsupported.extend(group_unsupported)
    cols.group_id = groups.group_of_pod

    # --- static [signature, node] tables ---
    row_fns = signature_row_fns(nodes, node_infos)

    def table(interner: Interner, kind: str):
        fn, dtype = row_fns[kind]
        t = np.zeros((max(len(interner), 1), n), dtype=dtype)
        for sig_id, rep in enumerate(interner.representatives):
            for i in range(n):
                t[sig_id, i] = fn(rep, i)
        return t

    tables = SignatureTables(
        selector_ok=table(sel_i, "selector_ok"),
        taint_ok=table(tol_i, "taint_ok"),
        intolerable=table(tol_i, "intolerable"),
        affinity_count=table(aff_i, "affinity_count"),
        avoid_score=table(avoid_i, "avoid_score"),
        host_ok=table(host_i, "host_ok"),
    )

    # --- dynamic aggregates from pre-scheduled pods ---
    dyn = DynamicInit(
        used_cpu=np.zeros(n, dtype=np.int64), used_mem=np.zeros(n, dtype=np.int64),
        used_gpu=np.zeros(n, dtype=np.int64), used_eph=np.zeros(n, dtype=np.int64),
        used_scalar=np.zeros((n, s), dtype=np.int64),
        nonzero_cpu=np.zeros(n, dtype=np.int64), nonzero_mem=np.zeros(n, dtype=np.int64),
        pod_count=np.zeros(n, dtype=np.int64))
    for k, existing in enumerate(snapshot.pods):
        i = node_index.get(existing.spec.node_name)
        if i is None:
            continue
        req = existing_requests[k]
        dyn.used_cpu[i] += req.milli_cpu
        dyn.used_mem[i] += req.memory
        dyn.used_gpu[i] += req.nvidia_gpu
        dyn.used_eph[i] += req.ephemeral_storage
        for name, v in req.scalar.items():
            dyn.used_scalar[i, scalar_idx[name]] += v
        nz = get_nonzero_pod_request(existing)
        dyn.nonzero_cpu[i] += nz.milli_cpu
        dyn.nonzero_mem[i] += nz.memory
        dyn.pod_count[i] += 1

    compiled = CompiledCluster(statics=statics, tables=tables, groups=groups,
                               dynamic=dyn, scalar_names=scalar_names,
                               node_index=node_index,
                               has_ports=has_ports, has_services=has_services,
                               has_interpod=has_interpod,
                               n_topo_doms=n_topo_doms, n_zone_doms=n_zone_doms,
                               unsupported=unsupported)
    return compiled, cols


def reason_strings(scalar_names: List[str]) -> List[str]:
    return REASON_STRINGS + [f"Insufficient {name}" for name in scalar_names]

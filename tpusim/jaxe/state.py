"""Columnar cluster state: SoA arrays + signature interning + static tables.

Reference mapping (SURVEY.md §7 step 2): NodeInfo's cached aggregates
(schedulercache/node_info.go:35-76) become per-node column vectors; the
symbolic pod features become interned signature ids with precompiled
[signature, node] tables (see tpusim/jaxe/__init__.py design note).
"""

from __future__ import annotations

from dataclasses import dataclass, field
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import (
    TAINT_PREFER_NO_SCHEDULE,
    Node,
    Pod,
    find_matching_untolerated_taint,
    tolerations_tolerate_taint,
)
from tpusim.engine.predicates import pod_matches_node_labels
from tpusim.engine.priorities import (
    calculate_node_affinity_priority_map,
    calculate_node_prefer_avoid_pods_priority_map,
)
from tpusim.engine.resources import (
    NodeInfo,
    get_nonzero_pod_request,
    get_resource_request,
    is_pod_best_effort,
)

# ---------------------------------------------------------------------------
# failure reason bit layout (decoded back to error.go strings for the report)
# ---------------------------------------------------------------------------

BIT_NODE_NOT_READY = 0
BIT_NODE_OUT_OF_DISK = 1
BIT_NODE_NETWORK_UNAVAILABLE = 2
BIT_NODE_UNSCHEDULABLE = 3
BIT_INSUFFICIENT_PODS = 4
BIT_INSUFFICIENT_CPU = 5
BIT_INSUFFICIENT_MEMORY = 6
BIT_INSUFFICIENT_GPU = 7
BIT_INSUFFICIENT_EPHEMERAL = 8
BIT_HOSTNAME_MISMATCH = 9
BIT_NODE_SELECTOR_MISMATCH = 10
BIT_TAINTS_NOT_TOLERATED = 11
BIT_MEMORY_PRESSURE = 12
BIT_DISK_PRESSURE = 13
NUM_FIXED_BITS = 14
# bits >= NUM_FIXED_BITS: Insufficient <scalar resource s>, per interned name

REASON_STRINGS = [
    "node(s) were not ready",
    "node(s) were out of disk space",
    "node(s) had unavailable network",
    "node(s) were unschedulable",
    "Insufficient pods",
    "Insufficient cpu",
    "Insufficient memory",
    "Insufficient alpha.kubernetes.io/nvidia-gpu",
    "Insufficient ephemeral-storage",
    "node(s) didn't match the requested hostname",
    "node(s) didn't match node selector",
    "node(s) had taints that the pod didn't tolerate",
    "node(s) had memory pressure",
    "node(s) had disk pressure",
]


class Interner:
    """Canonical-JSON signature -> dense id."""

    def __init__(self):
        self._ids: Dict[str, int] = {}
        self.representatives: List[Pod] = []

    def intern(self, signature, representative) -> int:
        key = json.dumps(signature, sort_keys=True, default=str)
        if key not in self._ids:
            self._ids[key] = len(self.representatives)
            self.representatives.append(representative)
        return self._ids[key]

    def __len__(self) -> int:
        return len(self.representatives)


@dataclass
class NodeStatics:
    """Per-node static columns (never mutated by binds)."""

    names: List[str]
    alloc_cpu: np.ndarray        # [N] int64, milli
    alloc_mem: np.ndarray        # [N] int64, bytes
    alloc_gpu: np.ndarray        # [N] int64
    alloc_eph: np.ndarray        # [N] int64
    allowed_pods: np.ndarray     # [N] int64
    alloc_scalar: np.ndarray     # [N, S] int64
    cond_fail_bits: np.ndarray   # [N] int64 (condition+unschedulable reason bits)
    mem_pressure: np.ndarray     # [N] bool
    disk_pressure: np.ndarray    # [N] bool


@dataclass
class SignatureTables:
    """[signature, node] static evaluation tables."""

    selector_ok: np.ndarray      # [Csel, N] bool — nodeSelector + required node affinity
    taint_ok: np.ndarray         # [Ctol, N] bool — NoSchedule/NoExecute taints tolerated
    intolerable: np.ndarray      # [Ctol, N] int64 — PreferNoSchedule intolerable count
    affinity_count: np.ndarray   # [Caff, N] int64 — preferred node-affinity weight sum
    avoid_score: np.ndarray      # [Cavoid, N] int64 — NodePreferAvoidPods (0 or 10)
    host_ok: np.ndarray          # [Chost, N] bool — spec.nodeName pin


@dataclass
class PodColumns:
    """Per-pod numeric columns + signature ids (the scan's xs)."""

    req_cpu: np.ndarray          # [P] int64 milli
    req_mem: np.ndarray          # [P] int64
    req_gpu: np.ndarray          # [P] int64
    req_eph: np.ndarray          # [P] int64
    req_scalar: np.ndarray       # [P, S] int64
    nz_cpu: np.ndarray           # [P] int64 (non-zero-default cpu, priorities only)
    nz_mem: np.ndarray           # [P] int64
    zero_request: np.ndarray     # [P] bool (PodFitsResources fast path)
    best_effort: np.ndarray      # [P] bool
    sel_id: np.ndarray           # [P] int32
    tol_id: np.ndarray           # [P] int32
    aff_id: np.ndarray           # [P] int32
    avoid_id: np.ndarray         # [P] int32
    host_id: np.ndarray          # [P] int32


@dataclass
class DynamicInit:
    """Mutable aggregates seeded from pre-scheduled snapshot pods
    (NodeInfo.AddPod accounting, node_info.go:318-398)."""

    used_cpu: np.ndarray         # [N] int64
    used_mem: np.ndarray
    used_gpu: np.ndarray
    used_eph: np.ndarray
    used_scalar: np.ndarray      # [N, S] int64
    nonzero_cpu: np.ndarray      # [N] int64
    nonzero_mem: np.ndarray
    pod_count: np.ndarray        # [N] int64


@dataclass
class CompiledCluster:
    statics: NodeStatics
    tables: SignatureTables
    dynamic: DynamicInit
    scalar_names: List[str]
    node_index: Dict[str, int]
    unsupported: List[str] = field(default_factory=list)  # features needing fallback


def _selector_signature(pod: Pod):
    aff = pod.spec.affinity
    na = aff.node_affinity.to_obj() if (aff and aff.node_affinity) else None
    return {"nodeSelector": pod.spec.node_selector,
            "required": (na or {}).get("requiredDuringSchedulingIgnoredDuringExecution")}


def _toleration_signature(pod: Pod):
    return {"tolerations": [t.to_obj() for t in pod.spec.tolerations]}


def _affinity_signature(pod: Pod):
    aff = pod.spec.affinity
    na = aff.node_affinity.to_obj() if (aff and aff.node_affinity) else None
    return {"preferred": (na or {}).get("preferredDuringSchedulingIgnoredDuringExecution")}


def _avoid_signature(pod: Pod):
    ref = pod.metadata.controller_ref()
    if ref is None or ref.kind not in ("ReplicationController", "ReplicaSet"):
        return None
    return {"kind": ref.kind, "uid": ref.uid}


def _host_signature(pod: Pod):
    return pod.spec.node_name or None


def compile_cluster(snapshot: ClusterSnapshot, pods: List[Pod]) -> Tuple[CompiledCluster, PodColumns]:
    """Build columnar state for `pods` scheduled against `snapshot`.

    Static matching reuses the parity engine's own functions (semantics match
    by construction); only numeric aggregates stay dynamic.
    """
    nodes = snapshot.nodes
    n = len(nodes)

    # single pass: NodeInfos, per-pod requests, and the scalar name space
    node_infos: List[NodeInfo] = []
    for node in nodes:
        ni = NodeInfo()
        ni.set_node(node)
        node_infos.append(ni)
    pod_requests = [get_resource_request(pod) for pod in pods]
    existing_requests = [get_resource_request(pod) for pod in snapshot.pods]

    scalar_names: List[str] = []
    seen = set()

    def _note_scalars(names):
        for name in names:
            if name not in seen:
                seen.add(name)
                scalar_names.append(name)

    for req in pod_requests + existing_requests:
        _note_scalars(req.scalar)
    for ni in node_infos:
        _note_scalars(ni.allocatable_resource.scalar)
    s = len(scalar_names)
    scalar_idx = {name: i for i, name in enumerate(scalar_names)}

    # --- node statics ---
    alloc = {k: np.zeros(n, dtype=np.int64)
             for k in ("cpu", "mem", "gpu", "eph", "pods")}
    alloc_scalar = np.zeros((n, s), dtype=np.int64)
    cond_bits = np.zeros(n, dtype=np.int64)
    mem_pressure = np.zeros(n, dtype=bool)
    disk_pressure = np.zeros(n, dtype=bool)
    for i, node in enumerate(nodes):
        ni = node_infos[i]
        r = ni.allocatable_resource
        alloc["cpu"][i] = r.milli_cpu
        alloc["mem"][i] = r.memory
        alloc["gpu"][i] = r.nvidia_gpu
        alloc["eph"][i] = r.ephemeral_storage
        alloc["pods"][i] = r.allowed_pod_number
        for name, v in r.scalar.items():
            alloc_scalar[i, scalar_idx[name]] = v
        bits = 0
        for cond in node.status.conditions:
            if cond.type == "Ready" and cond.status != "True":
                bits |= 1 << BIT_NODE_NOT_READY
            elif cond.type == "OutOfDisk" and cond.status != "False":
                bits |= 1 << BIT_NODE_OUT_OF_DISK
            elif cond.type == "NetworkUnavailable" and cond.status != "False":
                bits |= 1 << BIT_NODE_NETWORK_UNAVAILABLE
        if node.spec.unschedulable:
            bits |= 1 << BIT_NODE_UNSCHEDULABLE
        cond_bits[i] = bits
        mem_pressure[i] = ni.memory_pressure
        disk_pressure[i] = ni.disk_pressure

    statics = NodeStatics(
        names=[nd.name for nd in nodes],
        alloc_cpu=alloc["cpu"], alloc_mem=alloc["mem"], alloc_gpu=alloc["gpu"],
        alloc_eph=alloc["eph"], allowed_pods=alloc["pods"],
        alloc_scalar=alloc_scalar, cond_fail_bits=cond_bits,
        mem_pressure=mem_pressure, disk_pressure=disk_pressure)

    # --- pod columns + signature interning ---
    p = len(pods)
    cols = PodColumns(
        req_cpu=np.zeros(p, dtype=np.int64), req_mem=np.zeros(p, dtype=np.int64),
        req_gpu=np.zeros(p, dtype=np.int64), req_eph=np.zeros(p, dtype=np.int64),
        req_scalar=np.zeros((p, s), dtype=np.int64),
        nz_cpu=np.zeros(p, dtype=np.int64), nz_mem=np.zeros(p, dtype=np.int64),
        zero_request=np.zeros(p, dtype=bool), best_effort=np.zeros(p, dtype=bool),
        sel_id=np.zeros(p, dtype=np.int32), tol_id=np.zeros(p, dtype=np.int32),
        aff_id=np.zeros(p, dtype=np.int32), avoid_id=np.zeros(p, dtype=np.int32),
        host_id=np.zeros(p, dtype=np.int32))

    sel_i, tol_i, aff_i, avoid_i, host_i = (Interner() for _ in range(5))
    unsupported: List[str] = []
    for j, pod in enumerate(pods):
        req = pod_requests[j]
        cols.req_cpu[j] = req.milli_cpu
        cols.req_mem[j] = req.memory
        cols.req_gpu[j] = req.nvidia_gpu
        cols.req_eph[j] = req.ephemeral_storage
        for name, v in req.scalar.items():
            cols.req_scalar[j, scalar_idx[name]] = v
        cols.zero_request[j] = (req.milli_cpu == 0 and req.memory == 0
                                and req.nvidia_gpu == 0 and req.ephemeral_storage == 0
                                and not req.scalar)
        nz = get_nonzero_pod_request(pod)
        cols.nz_cpu[j] = nz.milli_cpu
        cols.nz_mem[j] = nz.memory
        cols.best_effort[j] = is_pod_best_effort(pod)
        cols.sel_id[j] = sel_i.intern(_selector_signature(pod), pod)
        cols.tol_id[j] = tol_i.intern(_toleration_signature(pod), pod)
        cols.aff_id[j] = aff_i.intern(_affinity_signature(pod), pod)
        cols.avoid_id[j] = avoid_i.intern(_avoid_signature(pod), pod)
        cols.host_id[j] = host_i.intern(_host_signature(pod), pod)
        aff = pod.spec.affinity
        if aff is not None and (aff.pod_affinity is not None
                                or aff.pod_anti_affinity is not None):
            unsupported.append(f"pod {pod.name}: inter-pod (anti)affinity")
        for c in pod.spec.containers:
            if any(port.host_port > 0 for port in c.ports):
                unsupported.append(f"pod {pod.name}: host ports")

    for existing in snapshot.pods:
        aff = existing.spec.affinity
        # anti-affinity gates the predicate; required affinity feeds the
        # symmetric hard-affinity weight of InterPodAffinityPriority; preferred
        # terms feed its soft scoring — all need device state we don't carry yet
        if aff is not None and (aff.pod_anti_affinity is not None
                                or aff.pod_affinity is not None):
            unsupported.append(f"existing pod {existing.name}: inter-pod (anti)affinity")
    if snapshot.services:
        unsupported.append("services (SelectorSpreadPriority is non-constant)")

    # --- static [signature, node] tables ---
    def table(interner: Interner, fn, dtype):
        t = np.zeros((max(len(interner), 1), n), dtype=dtype)
        for sig_id, rep in enumerate(interner.representatives):
            for i in range(n):
                t[sig_id, i] = fn(rep, i)
        return t

    def selector_fn(rep: Optional[Pod], i: int) -> bool:
        return pod_matches_node_labels(rep, nodes[i])

    def taint_ok_fn(rep: Pod, i: int) -> bool:
        return find_matching_untolerated_taint(
            node_infos[i].taints, rep.spec.tolerations,
            lambda t: t.effect in ("NoSchedule", "NoExecute")) is None

    def intolerable_fn(rep: Pod, i: int) -> int:
        tols = [t for t in rep.spec.tolerations
                if not t.effect or t.effect == TAINT_PREFER_NO_SCHEDULE]
        return sum(1 for taint in node_infos[i].taints
                   if taint.effect == TAINT_PREFER_NO_SCHEDULE
                   and not tolerations_tolerate_taint(tols, taint))

    def affinity_fn(rep: Pod, i: int) -> int:
        return calculate_node_affinity_priority_map(rep, None, node_infos[i]).score

    def avoid_fn(rep: Pod, i: int) -> int:
        return calculate_node_prefer_avoid_pods_priority_map(rep, None, node_infos[i]).score

    def host_fn(rep: Pod, i: int) -> bool:
        return (not rep.spec.node_name) or rep.spec.node_name == nodes[i].name

    tables = SignatureTables(
        selector_ok=table(sel_i, selector_fn, bool),
        taint_ok=table(tol_i, taint_ok_fn, bool),
        intolerable=table(tol_i, intolerable_fn, np.int64),
        affinity_count=table(aff_i, affinity_fn, np.int64),
        avoid_score=table(avoid_i, avoid_fn, np.int64),
        host_ok=table(host_i, host_fn, bool),
    )

    # --- dynamic aggregates from pre-scheduled pods ---
    node_index = {nd.name: i for i, nd in enumerate(nodes)}
    dyn = DynamicInit(
        used_cpu=np.zeros(n, dtype=np.int64), used_mem=np.zeros(n, dtype=np.int64),
        used_gpu=np.zeros(n, dtype=np.int64), used_eph=np.zeros(n, dtype=np.int64),
        used_scalar=np.zeros((n, s), dtype=np.int64),
        nonzero_cpu=np.zeros(n, dtype=np.int64), nonzero_mem=np.zeros(n, dtype=np.int64),
        pod_count=np.zeros(n, dtype=np.int64))
    for k, existing in enumerate(snapshot.pods):
        i = node_index.get(existing.spec.node_name)
        if i is None:
            continue
        req = existing_requests[k]
        dyn.used_cpu[i] += req.milli_cpu
        dyn.used_mem[i] += req.memory
        dyn.used_gpu[i] += req.nvidia_gpu
        dyn.used_eph[i] += req.ephemeral_storage
        for name, v in req.scalar.items():
            dyn.used_scalar[i, scalar_idx[name]] += v
        nz = get_nonzero_pod_request(existing)
        dyn.nonzero_cpu[i] += nz.milli_cpu
        dyn.nonzero_mem[i] += nz.memory
        dyn.pod_count[i] += 1

    compiled = CompiledCluster(statics=statics, tables=tables, dynamic=dyn,
                               scalar_names=scalar_names, node_index=node_index,
                               unsupported=unsupported)
    return compiled, cols


def reason_strings(scalar_names: List[str]) -> List[str]:
    return REASON_STRINGS + [f"Insufficient {name}" for name in scalar_names]

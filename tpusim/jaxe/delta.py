"""Event-log ingestion: fold watch events into compiled columnar state.

SURVEY.md §5 "distributed communication backend": the reference keeps the
scheduler's view current by streaming watch events (store -> WatchBuffer ->
informer -> cache mutation, restclient.go:218-236, factory.go:596-631). The
TPU-native equivalent is an append-only host-side event log applied to the
device arrays as batched scatter updates — this module is that path.

`IncrementalCluster` owns the mutable cluster picture (nodes, placed pods,
services) plus the compiled column caches, and exposes:

  apply(event_type, obj)   — one ADDED/MODIFIED/DELETED event for a Pod,
                             Node, or Service (store.py event constants)
  ingest(watch_buffer)     — drain a framework.events.WatchBuffer
  compile(pods)            — (CompiledCluster, PodColumns) for a new-pod batch
  schedule(pods, ...)      — compile + run the jax backend

Incremental behaviors (vs. re-running state.compile_cluster):
  * placed-pod add/update/delete: O(1) scatter into the dynamic aggregate and
    group-presence columns — no recompilation at all.
  * signature-table rows ([signature, node] predicate/priority cells) are
    memoized across scheduling rounds and node events patch them column-wise;
    this is the reference's equivalence cache (core/equivalence_cache.go:
    per-node predicate-result LRU) recast for columnar state: keyed by
    (table, signature) instead of (node, predicate, pod-equivalence-hash),
    with node-event invalidation patching single columns instead of dropping
    whole per-node caches.
  * node add/update/delete: per-column patches of the static tables.
  * pod-group tables (ports/services/inter-pod affinity) rebuild lazily only
    when the group structure itself changes (new signature, node/service
    events); presence survives via scatter in the common case.

Equivalence contract (tested): after ANY event sequence, compile(pods) must
schedule identically to a fresh compile of the equivalent snapshot.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import (
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    Service,
)
from tpusim.engine.resources import (
    NodeInfo,
    get_nonzero_pod_request,
    get_resource_request,
)
from tpusim.framework.store import ADDED, DELETED, MODIFIED
from tpusim.jaxe.state import (
    CompiledCluster,
    DynamicInit,
    GroupTables,
    NodeStatics,
    PodColumns,
    SignatureTables,
    _affinity_signature,
    _avoid_signature,
    _compile_groups,
    _freeze,
    _group_signature,
    _host_signature,
    _selector_signature,
    _toleration_signature,
    fill_pod_request_row,
    node_static_row,
    signature_row_fns,
)

_SIG_KINDS = (
    # (pod-column name, signature fn, table kinds fed by that signature)
    ("sel_id", _selector_signature, ("selector_ok",)),
    ("tol_id", _toleration_signature,
     ("taint_ok", "taint_ok_noexec", "intolerable")),
    ("aff_id", _affinity_signature, ("affinity_count",)),
    ("avoid_id", _avoid_signature, ("avoid_score",)),
    ("host_id", _host_signature, ("host_ok",)),
)


# Canonical signature key — MUST be the interner's own key function: the
# incremental path looks ids up in tables keyed by compile_cluster's
# interners (state.py builds sig_to_gid from Interner._ids keys).
_key = _freeze


# signature-row memo bound (the reference's equivalence cache is a 100-entry
# per-node LRU, equivalence_cache.go:33-47; here rows are N-wide so a single
# global FIFO bound keeps memory proportional to live signature diversity)
MAX_SIG_ROWS = 8192


def _needs_groups(pod: Pod) -> bool:
    from tpusim.jaxe.state import _has_interpod_terms, _sanitized_ports
    return bool(_sanitized_ports(pod)) or _has_interpod_terms(pod)


class IncrementalCluster:
    def __init__(self, snapshot: Optional[ClusterSnapshot] = None):
        snapshot = snapshot or ClusterSnapshot()
        self.nodes: List[Node] = list(snapshot.nodes)
        self.services: List[Service] = list(snapshot.services)
        # PV/PVC state: volume tables (disk-conflict/MaxPD/zone) are part of
        # the group tables and rebuild from to_snapshot() when dirty, so
        # carrying the objects here is all the incremental path needs to
        # evaluate the volume predicates natively (no reference fallback)
        self.pvs: Dict[str, PersistentVolume] = {pv.name: pv
                                                 for pv in snapshot.pvs}
        self.pvcs: Dict[str, PersistentVolumeClaim] = {pvc.key(): pvc
                                                       for pvc in snapshot.pvcs}
        self._pods: Dict[str, Pod] = {p.key(): p for p in snapshot.pods}
        # node name -> keys of pods claiming it (placed or parked); lets node
        # events touch only their own pods instead of scanning all P
        self._pods_on_node: Dict[str, set] = {}
        for key, pod in self._pods.items():
            if pod.spec.node_name:
                self._pods_on_node.setdefault(pod.spec.node_name, set()).add(key)

        self._node_index: Dict[str, int] = {}
        self._node_infos: List[NodeInfo] = []
        self._scalar_names: List[str] = []
        self._scalar_idx: Dict[str, int] = {}

        # memoized [signature, node] rows: (table kind, sig key) -> np row [N]
        self._sig_rows: Dict[tuple, np.ndarray] = {}
        self._sig_reps: Dict[tuple, Pod] = {}     # sig key -> representative
        self.sig_row_computations = 0             # cache-effectiveness counter

        # node statics + dynamic aggregates, maintained column-wise
        self._statics: Optional[NodeStatics] = None
        self._dyn: Optional[DynamicInit] = None

        # group tables cache
        self._groups: Optional[GroupTables] = None
        self._groups_meta = None                  # (flags..., doms, unsupported)
        self._groups_sig_keys: Dict[object, int] = {}  # group sig key -> id
        self._groups_batch_keys: Optional[tuple] = None
        self._groups_dirty = True
        self._groups_active = False               # any feature flag set
        self._groups_need_saa = False             # ServiceAffinity defs baked in
        self._presence: Optional[np.ndarray] = None

        # delta journal (ISSUE 7): node indices / presence cells touched by
        # _apply_dynamic since the last drain — the stream runtime
        # (tpusim.stream) turns these into scatter-commit tensors, so a
        # cycle's device update is O(touched), not O(nodes). Entries are
        # only meaningful while the structure is stable (any node/scalar/
        # group change forces a restage, which drops the journal).
        self._journal_nodes: set = set()
        self._journal_presence: set = set()
        self._journal_mark_active = False         # mark-bracket exclusivity
        # label/taint-churned node indices (ISSUE 9): a MODIFIED node whose
        # ONLY delta is metadata.labels / spec.taints leaves the structural
        # caches intact (when no group feature is active) but moves
        # per-(signature, node) and per-(policy-row, node) statics cells; the
        # stream runtime gathers these columns into a statics scatter instead
        # of restaging. Dropped with the journal on drain/restage.
        self._journal_node_columns: set = set()
        # monotone count of signature-row memo evictions (_evict_sig_rows):
        # lets the stream runtime classify a residency miss caused by memo
        # pressure ("sig_evict") apart from genuinely new signatures
        self.sig_evictions = 0
        # the most recent _batch_columns interning (per-kind key lists): a
        # restage records this as the resident row order, against which
        # later batches' ids are remapped (tpusim.stream)
        self.last_batch_key_lists: Optional[Dict[str, List]] = None
        # committed-delta hook (tpusim.stream.persist): called as
        # on_event(event_type, obj) AFTER each apply() dispatches, so a
        # WAL sees every delta exactly when it commits — regardless of
        # whether it arrived via apply/apply_events/ingest/Reflector
        self.on_event = None

        self._rebuild_nodes()
        for pod in self._pods.values():
            self._note_pod_scalars(pod)
            self._apply_dynamic(pod, +1)

    # -- snapshot view ------------------------------------------------------

    def to_snapshot(self) -> ClusterSnapshot:
        """The equivalent point-in-time ClusterSnapshot (shared objects)."""
        return ClusterSnapshot(nodes=list(self.nodes),
                               pods=list(self._pods.values()),
                               services=list(self.services),
                               pvs=list(self.pvs.values()),
                               pvcs=list(self.pvcs.values()))

    # -- node-side caches ---------------------------------------------------

    def _rebuild_nodes(self) -> None:
        self._node_index = {nd.name: i for i, nd in enumerate(self.nodes)}
        self._node_infos = [self._make_node_info(node) for node in self.nodes]
        # the row-fn closures capture self.nodes/_node_infos AS LIST OBJECTS;
        # event paths patch those lists in place, so the closures stay fresh
        # without per-event rebuilds
        self._row_fns = signature_row_fns(self.nodes, self._node_infos)

    @staticmethod
    def _make_node_info(node: Node) -> NodeInfo:
        ni = NodeInfo()
        ni.set_node(node)
        return ni

    def _note_scalar(self, name: str) -> None:
        if name not in self._scalar_idx:
            self._scalar_idx[name] = len(self._scalar_names)
            self._scalar_names.append(name)
            if self._statics is not None:
                n = len(self.nodes)
                self._statics.alloc_scalar = np.concatenate(
                    [self._statics.alloc_scalar,
                     np.zeros((n, 1), dtype=np.int64)], axis=1)
            if self._dyn is not None:
                n = len(self.nodes)
                self._dyn.used_scalar = np.concatenate(
                    [self._dyn.used_scalar, np.zeros((n, 1), dtype=np.int64)],
                    axis=1)

    def _note_pod_scalars(self, pod: Pod) -> None:
        for name in get_resource_request(pod).scalar:
            self._note_scalar(name)

    def _note_node_scalars(self, ni: NodeInfo) -> None:
        for name in ni.allocatable_resource.scalar:
            self._note_scalar(name)

    def _statics_row(self, i: int):
        return node_static_row(self.nodes[i], self._node_infos[i],
                               self._scalar_idx, len(self._scalar_names))

    def _ensure_statics(self) -> NodeStatics:
        if self._statics is None:
            n = len(self.nodes)
            s = len(self._scalar_names)
            st = NodeStatics(
                names=[nd.name for nd in self.nodes],
                alloc_cpu=np.zeros(n, np.int64), alloc_mem=np.zeros(n, np.int64),
                alloc_gpu=np.zeros(n, np.int64), alloc_eph=np.zeros(n, np.int64),
                allowed_pods=np.zeros(n, np.int64),
                alloc_scalar=np.zeros((n, s), np.int64),
                cond_fail_bits=np.zeros(n, np.int64),
                mem_pressure=np.zeros(n, bool), disk_pressure=np.zeros(n, bool))
            for i in range(n):
                self._note_node_scalars(self._node_infos[i])
            # scalar widths may have grown while noting
            st.alloc_scalar = np.zeros((n, len(self._scalar_names)), np.int64)
            for i in range(n):
                self._set_statics_row(st, i, self._statics_row(i))
            self._statics = st
        return self._statics

    @staticmethod
    def _set_statics_row(st: NodeStatics, i: int, row) -> None:
        (st.alloc_cpu[i], st.alloc_mem[i], st.alloc_gpu[i], st.alloc_eph[i],
         st.allowed_pods[i]) = row[0], row[1], row[2], row[3], row[4]
        st.alloc_scalar[i, :len(row[5])] = row[5]
        st.cond_fail_bits[i], st.mem_pressure[i], st.disk_pressure[i] = \
            row[6], row[7], row[8]

    def _ensure_dyn(self) -> DynamicInit:
        if self._dyn is None:
            n = len(self.nodes)
            s = len(self._scalar_names)
            self._dyn = DynamicInit(
                used_cpu=np.zeros(n, np.int64), used_mem=np.zeros(n, np.int64),
                used_gpu=np.zeros(n, np.int64), used_eph=np.zeros(n, np.int64),
                used_scalar=np.zeros((n, s), np.int64),
                nonzero_cpu=np.zeros(n, np.int64),
                nonzero_mem=np.zeros(n, np.int64),
                pod_count=np.zeros(n, np.int64))
        return self._dyn

    # -- pod-side scatter ---------------------------------------------------

    def _apply_dynamic(self, pod: Pod, sign: int) -> None:
        """Add (+1) or remove (-1) a placed pod's aggregate contributions —
        the NodeInfo.AddPod/RemovePod accounting (node_info.go:318-398) as a
        column scatter."""
        i = self._node_index.get(pod.spec.node_name)
        if i is None:
            return
        self._note_pod_scalars(pod)
        dyn = self._ensure_dyn()
        req = get_resource_request(pod)
        nz = get_nonzero_pod_request(pod)
        dyn.used_cpu[i] += sign * req.milli_cpu
        dyn.used_mem[i] += sign * req.memory
        dyn.used_gpu[i] += sign * req.nvidia_gpu
        dyn.used_eph[i] += sign * req.ephemeral_storage
        for name, v in req.scalar.items():
            dyn.used_scalar[i, self._scalar_idx[name]] += sign * v
        dyn.nonzero_cpu[i] += sign * nz.milli_cpu
        dyn.nonzero_mem[i] += sign * nz.memory
        dyn.pod_count[i] += sign
        self._journal_nodes.add(i)

        # group presence fast path: known signature -> scatter, else rebuild
        if self._groups_active and not self._groups_dirty \
                and self._presence is not None:
            gid = self._groups_sig_keys.get(_key(_group_signature(pod)))
            if gid is None:
                self._groups_dirty = True
            else:
                self._presence[gid, i] += sign
                self._journal_presence.add((gid, i))
        elif not self._groups_active and _needs_groups(pod):
            # a ports/affinity pod arriving in a feature-free cluster
            self._groups_dirty = True

    # -- event application --------------------------------------------------

    def apply(self, event_type: str, obj) -> None:
        if isinstance(obj, Pod):
            self._apply_pod(event_type, obj)
        elif isinstance(obj, Node):
            self._apply_node(event_type, obj)
        elif isinstance(obj, Service):
            self._apply_service(event_type, obj)
        elif isinstance(obj, PersistentVolume):
            self._apply_pv(event_type, obj)
        elif isinstance(obj, PersistentVolumeClaim):
            self._apply_pvc(event_type, obj)
        else:
            raise TypeError(f"unsupported event object: {type(obj).__name__}")
        if self.on_event is not None:
            self.on_event(event_type, obj)

    def apply_events(self, events: Iterable[Tuple[str, object]]) -> None:
        for event_type, obj in events:
            self.apply(event_type, obj)

    def ingest(self, watch_buffer) -> int:
        """Drain a framework.events.WatchBuffer (non-blocking); returns the
        number of events applied."""
        count = 0
        for ev in watch_buffer:
            self.apply(ev.type, ev.object)
            count += 1
        return count

    def _apply_pod(self, event_type: str, pod: Pod) -> None:
        key = pod.key()
        old = self._pods.get(key)
        if old is not None and old.spec.node_name:
            self._pods_on_node.get(old.spec.node_name, set()).discard(key)
        if event_type == DELETED:
            if old is not None:
                self._apply_dynamic(old, -1)
                del self._pods[key]
        elif event_type in (ADDED, MODIFIED):
            if old is not None:
                self._apply_dynamic(old, -1)
            self._pods[key] = pod
            if pod.spec.node_name:
                self._pods_on_node.setdefault(pod.spec.node_name, set()).add(key)
            self._apply_dynamic(pod, +1)
        else:
            raise ValueError(f"unknown event type {event_type!r}")
        # pods parked on an unknown-but-set node feed "matching pod exists"
        # (aff_unplaced) — group structure may change
        for p in (old, pod if event_type != DELETED else None):
            if p is not None and p.spec.node_name \
                    and p.spec.node_name not in self._node_index:
                self._groups_dirty = True
            # a placed pod's volumes feed used_vols_init [N, V] (NoDiskConflict
            # occupancy, MaxPD counts), which lives in the cached group tables
            # — no scatter path exists for it, so rebuild
            if p is not None and p.spec.volumes:
                self._groups_dirty = True

    def _apply_node(self, event_type: str, node: Node) -> None:
        i = self._node_index.get(node.name)
        if (event_type in (ADDED, MODIFIED) and i is not None
                and not self._groups_active
                and self._column_only_change(self.nodes[i], node)):
            # label/taint-only churn (ISSUE 9): node statics/aggregates and
            # the memoized signature rows are patched in place by
            # _update_node; with no group feature active the cached (trivial)
            # group tables never read node labels, so the structural caches
            # stay valid and the stream runtime can scatter just this node's
            # statics columns. When a group feature IS active, topology/zone
            # domains may consume these labels — fall through to the
            # conservative rebuild below.
            self._update_node(i, node)
            self._journal_node_columns.add(i)
            return
        self._groups_dirty = True  # topology/zone domains follow the node set
        if event_type == ADDED and i is None:
            self._append_node(node)
        elif event_type in (ADDED, MODIFIED) and i is not None:
            self._update_node(i, node)
        elif event_type == MODIFIED and i is None:
            self._append_node(node)
        elif event_type == DELETED:
            if i is not None:
                self._delete_node(i)
        else:
            raise ValueError(f"unknown event type {event_type!r}")

    @staticmethod
    def _column_only_change(old: Node, node: Node) -> bool:
        """True when the event's entire delta is metadata.labels and/or
        spec.taints — the two inputs that move only per-(signature, node)
        statics cells — INCLUDING the empty delta (a no-op resync MODIFIED,
        which the column path absorbs for free instead of restaging).
        Everything structural (unschedulable, allocatable, conditions,
        images, annotations...) must be byte-identical; compared on the
        to_obj() wire form, the same canonicalization Node.copy()
        round-trips through."""
        a, b = old.to_obj(), node.to_obj()
        a["metadata"].pop("labels", None)
        b["metadata"].pop("labels", None)
        a["spec"].pop("taints", None)
        b["spec"].pop("taints", None)
        return a == b

    def _apply_service(self, event_type: str, svc: Service) -> None:
        self._groups_dirty = True
        self.services = [s for s in self.services
                         if (s.namespace, s.name) != (svc.namespace, svc.name)]
        if event_type in (ADDED, MODIFIED):
            self.services.append(svc)

    def _apply_pv(self, event_type: str, pv: PersistentVolume) -> None:
        # MaxPD volume-id resolution and zone tables read PV objects; any PV
        # churn invalidates them (factory.go wires the same PV handlers to
        # ecache invalidation, factory.go:139-299)
        self._groups_dirty = True
        if event_type == DELETED:
            self.pvs.pop(pv.name, None)
        elif event_type in (ADDED, MODIFIED):
            self.pvs[pv.name] = pv
        else:
            raise ValueError(f"unknown event type {event_type!r}")

    def _apply_pvc(self, event_type: str, pvc: PersistentVolumeClaim) -> None:
        self._groups_dirty = True
        if event_type == DELETED:
            self.pvcs.pop(pvc.key(), None)
        elif event_type in (ADDED, MODIFIED):
            self.pvcs[pvc.key()] = pvc
        else:
            raise ValueError(f"unknown event type {event_type!r}")

    # -- node column patches ------------------------------------------------

    def _append_node(self, node: Node) -> None:
        self._ensure_statics()
        self._ensure_dyn()

        def grow(arr):
            return np.concatenate([arr, np.zeros(1, arr.dtype)])

        # grow the node axis FIRST (while widths still agree), then register
        # the node, then note its scalars (which widens the scalar axis over
        # the already-consistent arrays)
        st, dyn = self._statics, self._dyn
        st.names.append(node.name)
        for field in ("alloc_cpu", "alloc_mem", "alloc_gpu", "alloc_eph",
                      "allowed_pods", "cond_fail_bits", "mem_pressure",
                      "disk_pressure"):
            setattr(st, field, grow(getattr(st, field)))
        st.alloc_scalar = np.concatenate(
            [st.alloc_scalar, np.zeros((1, st.alloc_scalar.shape[1]),
                                       np.int64)], axis=0)
        for field in ("used_cpu", "used_mem", "used_gpu", "used_eph",
                      "nonzero_cpu", "nonzero_mem", "pod_count"):
            setattr(dyn, field, grow(getattr(dyn, field)))
        dyn.used_scalar = np.concatenate(
            [dyn.used_scalar, np.zeros((1, dyn.used_scalar.shape[1]),
                                       np.int64)], axis=0)

        # in-place list patches keep the row-fn closures current
        self.nodes.append(node)
        i = len(self.nodes) - 1
        self._node_infos.append(self._make_node_info(node))
        self._node_index[node.name] = i
        self._note_node_scalars(self._node_infos[i])
        self._set_statics_row(st, i, self._statics_row(i))

        # memoized signature rows gain one computed cell each
        for (kind, sig_key), row_arr in list(self._sig_rows.items()):
            fn, dtype = self._row_fns[kind]
            cell = np.asarray([fn(self._sig_reps[sig_key], i)], dtype=dtype)
            self._sig_rows[(kind, sig_key)] = np.concatenate([row_arr, cell])
            self.sig_row_computations += 1

        # pods that were parked on this node name materialize their aggregates
        for key in self._pods_on_node.get(node.name, ()):
            self._apply_dynamic(self._pods[key], +1)

    def _update_node(self, i: int, node: Node) -> None:
        # remove aggregates computed against the old column, patch, re-add
        # (allocatable may shift scalar space; conditions shift cond bits)
        affected = [self._pods[k] for k in self._pods_on_node.get(node.name, ())]
        for pod in affected:
            self._apply_dynamic(pod, -1)
        self.nodes[i] = node
        self._node_infos[i] = self._make_node_info(node)
        self._note_node_scalars(self._node_infos[i])
        self._ensure_statics()
        self._set_statics_row(self._statics, i, self._statics_row(i))
        for (kind, sig_key), row_arr in self._sig_rows.items():
            fn, _ = self._row_fns[kind]
            row_arr[i] = fn(self._sig_reps[sig_key], i)
            self.sig_row_computations += 1
        for pod in affected:
            self._apply_dynamic(pod, +1)

    def _delete_node(self, i: int) -> None:
        self._ensure_statics()
        self._ensure_dyn()
        del self.nodes[i]
        del self._node_infos[i]
        self._node_index = {nd.name: i for i, nd in enumerate(self.nodes)}
        st, dyn = self._statics, self._dyn
        del st.names[i]
        for field in ("alloc_cpu", "alloc_mem", "alloc_gpu", "alloc_eph",
                      "allowed_pods", "cond_fail_bits", "mem_pressure",
                      "disk_pressure"):
            setattr(st, field, np.delete(getattr(st, field), i))
        st.alloc_scalar = np.delete(st.alloc_scalar, i, axis=0)
        for field in ("used_cpu", "used_mem", "used_gpu", "used_eph",
                      "nonzero_cpu", "nonzero_mem", "pod_count"):
            setattr(dyn, field, np.delete(getattr(dyn, field), i))
        dyn.used_scalar = np.delete(dyn.used_scalar, i, axis=0)
        for key_pair, row_arr in list(self._sig_rows.items()):
            self._sig_rows[key_pair] = np.delete(row_arr, i)

    # -- batch compilation --------------------------------------------------

    def _sig_table(self, kind: str, interned_keys: List[str]) -> np.ndarray:
        """Stack memoized rows for a batch's interned signatures, computing
        only the rows never seen before (the equivalence-cache effect)."""
        fn, dtype = self._row_fns[kind]
        n = len(self.nodes)
        rows = []
        for sig_key in interned_keys:
            cache_key = (kind, sig_key)
            row = self._sig_rows.pop(cache_key, None)
            if row is None:
                rep = self._sig_reps[sig_key]
                row = np.fromiter((fn(rep, i) for i in range(n)),
                                  dtype=dtype, count=n)
                self.sig_row_computations += n
            # re-insert (move-to-end) so eviction is LRU, not FIFO — the
            # upstream equivalence cache this mirrors is an LRU
            self._sig_rows[cache_key] = row
            rows.append(row)
        if not rows:
            return np.zeros((1, n), dtype=dtype)
        return np.stack(rows)

    def _evict_sig_rows(self) -> None:
        """Bound the signature-row memo (LRU: hits are re-inserted at the end
        by _sig_table, so the head is least-recently-used) and drop
        representatives that no cached row references anymore."""
        if len(self._sig_rows) <= MAX_SIG_ROWS:
            return
        overflow = len(self._sig_rows) - MAX_SIG_ROWS
        self.sig_evictions += overflow
        for cache_key in list(self._sig_rows)[:overflow]:
            del self._sig_rows[cache_key]
        live = {sig for (_, sig) in self._sig_rows}
        self._sig_reps = {k: v for k, v in self._sig_reps.items() if k in live}

    def _batch_columns(self, pods: List[Pod]
                       ) -> Tuple[PodColumns, Dict[str, List]]:
        """Pod request columns + batch-local signature interning over the
        memoized rows — the batch-shaped half of compile(), shared with the
        stream fast path (which needs columns WITHOUT the O(nodes) table
        stacking). Returns (cols, per-kind interned key lists); group_id is
        left zero for the caller to assign."""
        for pod in pods:
            self._note_pod_scalars(pod)
        s = len(self._scalar_names)
        p = len(pods)
        cols = PodColumns(
            req_cpu=np.zeros(p, np.int64), req_mem=np.zeros(p, np.int64),
            req_gpu=np.zeros(p, np.int64), req_eph=np.zeros(p, np.int64),
            req_scalar=np.zeros((p, s), np.int64),
            nz_cpu=np.zeros(p, np.int64), nz_mem=np.zeros(p, np.int64),
            zero_request=np.zeros(p, bool), best_effort=np.zeros(p, bool),
            sel_id=np.zeros(p, np.int32), tol_id=np.zeros(p, np.int32),
            aff_id=np.zeros(p, np.int32), avoid_id=np.zeros(p, np.int32),
            host_id=np.zeros(p, np.int32), group_id=np.zeros(p, np.int32),
            img_id=np.zeros(p, np.int32),
            sa_self_id=np.zeros(p, np.int32))
        batch_keys: Dict[str, Dict[str, int]] = {name: {} for name, _, _ in _SIG_KINDS}
        key_lists: Dict[str, List[str]] = {name: [] for name, _, _ in _SIG_KINDS}
        for j, pod in enumerate(pods):
            fill_pod_request_row(cols, j, pod, get_resource_request(pod),
                                 self._scalar_idx)
            for name, sig_fn, _kinds in _SIG_KINDS:
                # family-prefixed tuple: _avoid_signature and _host_signature
                # can freeze to the same key (e.g. both None) — without the
                # prefix one pod would become the representative for BOTH
                # kinds (review finding). Tuple, not f-string: repr-ing the
                # frozen key would reintroduce the serialization cost the
                # _freeze interning removed.
                sig_key = (name, _key(sig_fn(pod)))
                ids = batch_keys[name]
                if sig_key not in ids:
                    ids[sig_key] = len(ids)
                    key_lists[name].append(sig_key)
                    self._sig_reps.setdefault(sig_key, pod)
                getattr(cols, name)[j] = ids[sig_key]
        self.last_batch_key_lists = key_lists
        return cols, key_lists

    def batch_group_keys(self, pods: List[Pod]) -> tuple:
        """The batch's deduped canonical group-signature keys — compile()'s
        group-table reuse test, exposed so the stream fast path can prove
        the cached tables would be reused verbatim."""
        return tuple(dict.fromkeys(
            _key(_group_signature(pod)) for pod in pods))

    def assign_group_ids(self, cols: PodColumns, pods: List[Pod]) -> bool:
        """Fill cols.group_id from the cached signature->merged-group map.
        Only valid while the cached group tables are clean AND the batch's
        group keys match the tables' batch (batch_group_keys ==
        _groups_batch_keys); returns False when that doesn't hold and a
        compile() is required."""
        if self._groups_dirty or self._groups is None:
            return False
        (_hp, _hs, _hi, _nt, _nz, unsupported, _vm) = self._groups_meta
        if self._groups_active and not unsupported:
            try:
                cols.group_id[:] = np.fromiter(
                    (self._groups_sig_keys[_key(_group_signature(pod))]
                     for pod in pods), dtype=np.int32, count=len(pods))
            except KeyError:
                return False
        # else: trivial tables — group_id stays all zero
        return True

    def drain_journal(self) -> Tuple[set, set]:
        """Hand over (touched node indices, touched presence cells) since
        the last drain and reset both. Meaningless after a structural event
        (node indices may have shifted) — callers restage there instead."""
        nodes, cells = self._journal_nodes, self._journal_presence
        self._journal_nodes, self._journal_presence = set(), set()
        self._journal_node_columns = set()
        return nodes, cells

    def journal_mark(self) -> Tuple[set, set]:
        """Snapshot the pod-delta journal. Paired with journal_rollback by
        the pipelined fold-back (stream/runtime._fold_binds) and by overlay
        what-if queries (stream/runtime.overlay_query): the scan already
        applied that cycle's binds to the resident carry with identical
        integer arithmetic, so the fold's MODIFIED replays are journal
        noise — rolling back to the mark keeps the next commit's scatter
        O(watch delta) instead of O(delta + binds), which also keeps the
        commit bucket sizes inside the warmed jit cache.

        Marks are exclusive: a second mark before the first is resolved
        (rollback or release) raises — nesting would silently lose the
        outer bracket's entries on the inner rollback."""
        if self._journal_mark_active:
            raise RuntimeError(
                "journal_mark is exclusive: an unresolved mark is active "
                "(rollback or release it first)")
        self._journal_mark_active = True
        return set(self._journal_nodes), set(self._journal_presence)

    def journal_rollback(self, mark: Tuple[set, set]) -> None:
        """Discard journal entries added since journal_mark (safe only when
        every interim apply targeted state the resident carry already
        holds, i.e. the pipelined bind fold-back / overlay rollback)."""
        self._journal_nodes, self._journal_presence = mark
        self._journal_mark_active = False

    def journal_release(self) -> None:
        """Resolve an active journal_mark WITHOUT restoring the snapshot —
        the success half of a mark bracket whose interim applies should
        stick (gang admission keeps its members' binds journaled)."""
        self._journal_mark_active = False

    def drain_column_journal(self) -> set:
        """Hand over the label/taint-churned node indices since the last
        drain and reset (ISSUE 9). Same stability contract as drain_journal:
        indices are meaningful only while the node set is unchanged."""
        cols = self._journal_node_columns
        self._journal_node_columns = set()
        return cols

    def compile(self, pods: List[Pod], need_noexec: bool = False,
                need_saa: bool = False
                ) -> Tuple[CompiledCluster, PodColumns]:
        """Compile a new-pod batch against the current cluster picture.
        Returns fresh array copies (later events do not mutate the result).
        need_noexec: compute the policy-only NoExecute taint table (the
        default ships an all-pass dummy; see state.compile_cluster).
        need_saa: bake Service(Anti)Affinity defs/rows into the group tables
        (compiled-policy stream staging, ISSUE 9)."""
        cols, key_lists = self._batch_columns(pods)
        statics = self._ensure_statics()
        dyn = self._ensure_dyn()
        p = len(pods)

        tables = SignatureTables(
            selector_ok=self._sig_table("selector_ok", key_lists["sel_id"]),
            taint_ok=self._sig_table("taint_ok", key_lists["tol_id"]),
            taint_ok_noexec=(
                self._sig_table("taint_ok_noexec", key_lists["tol_id"])
                if need_noexec else
                np.ones((max(len(key_lists["tol_id"]), 1), len(self.nodes)),
                        dtype=bool)),
            intolerable=self._sig_table("intolerable", key_lists["tol_id"]),
            affinity_count=self._sig_table("affinity_count", key_lists["aff_id"]),
            avoid_score=self._sig_table("avoid_score", key_lists["avoid_id"]),
            host_ok=self._sig_table("host_ok", key_lists["host_id"]),
        )
        self._evict_sig_rows()

        # --- group tables: rebuild only on structural change ---
        group_keys = self.batch_group_keys(pods)
        if (self._groups_dirty or self._groups is None
                or group_keys != self._groups_batch_keys
                or need_saa != self._groups_need_saa):
            snapshot = self.to_snapshot()
            (groups, has_ports, has_services, has_interpod, n_topo, n_zone,
             unsupported, sig_to_gid, vol_meta) = _compile_groups(
                 snapshot, pods, self.nodes, self._node_index,
                 need_saa=need_saa)
            self._groups = groups
            self._groups_need_saa = need_saa
            self._groups_meta = (has_ports, has_services, has_interpod,
                                 n_topo, n_zone, unsupported, vol_meta)
            self._groups_batch_keys = group_keys
            # volume flags count: disk_sig[G]/vol_mask[G, V] key off group
            # ids, so volume-only workloads still need real group_id columns
            self._groups_active = (has_ports or has_services or has_interpod
                                   or any(vol_meta[:3]))
            self._presence = groups.presence
            # raw canonical signature -> MERGED group id, as produced by
            # _compile_groups' profile merge; an unseen signature later marks
            # the tables dirty (its profile is unknown without the matchers)
            self._groups_sig_keys = dict(sig_to_gid)
            self._groups_dirty = False
        groups = self._groups
        (has_ports, has_services, has_interpod, n_topo, n_zone, unsupported,
         vol_meta) = self._groups_meta
        has_disk_conflict, has_maxpd, has_vol_zone, maxpd_limits = vol_meta
        if self._groups_active and not unsupported:
            group_id = np.fromiter(
                (self._groups_sig_keys[_key(_group_signature(pod))]
                 for pod in pods), dtype=np.int32, count=p)
        else:
            group_id = np.zeros(p, np.int32)  # trivial tables: all group 0
        cols.group_id = group_id
        groups_out = replace(groups, presence=self._presence.copy(),
                             group_of_pod=group_id)

        statics_out = NodeStatics(
            names=list(statics.names),
            alloc_cpu=statics.alloc_cpu.copy(), alloc_mem=statics.alloc_mem.copy(),
            alloc_gpu=statics.alloc_gpu.copy(), alloc_eph=statics.alloc_eph.copy(),
            allowed_pods=statics.allowed_pods.copy(),
            alloc_scalar=statics.alloc_scalar.copy(),
            cond_fail_bits=statics.cond_fail_bits.copy(),
            mem_pressure=statics.mem_pressure.copy(),
            disk_pressure=statics.disk_pressure.copy())
        dyn_out = DynamicInit(
            used_cpu=dyn.used_cpu.copy(), used_mem=dyn.used_mem.copy(),
            used_gpu=dyn.used_gpu.copy(), used_eph=dyn.used_eph.copy(),
            used_scalar=dyn.used_scalar.copy(),
            nonzero_cpu=dyn.nonzero_cpu.copy(),
            nonzero_mem=dyn.nonzero_mem.copy(),
            pod_count=dyn.pod_count.copy())

        compiled = CompiledCluster(
            statics=statics_out, tables=tables, groups=groups_out,
            dynamic=dyn_out, scalar_names=list(self._scalar_names),
            node_index=dict(self._node_index),
            has_ports=has_ports, has_services=has_services,
            has_interpod=has_interpod, has_noexec_table=need_noexec,
            has_saa_table=need_saa,
            has_disk_conflict=has_disk_conflict, has_maxpd=has_maxpd,
            has_vol_zone=has_vol_zone, maxpd_limits=maxpd_limits,
            n_topo_doms=n_topo, n_zone_doms=n_zone,
            unsupported=list(unsupported))
        return compiled, cols

    def refresh_dynamic(self, compiled: CompiledCluster
                        ) -> Optional[CompiledCluster]:
        """Re-snapshot ONLY the dynamic aggregates + group presence of a
        previously compiled batch after placed-pod churn (bind/victim events
        fed through apply()) — the preemption hybrid's fast re-arm path
        (jaxe/preempt.py): a victim deletion invalidates the device carry but
        not the static tables, so rebuilding the carry is a handful of array
        copies instead of an O(remaining-pods) compile().

        Valid only when no structural rebuild is pending: group tables clean,
        node set and scalar universe unchanged since `compiled` was produced.
        Returns None when a full compile() is required."""
        if (self._groups_dirty or self._statics is None or self._dyn is None
                or self._groups is None or self._presence is None
                or len(self.nodes) != len(compiled.statics.names)
                or len(self._scalar_names) != len(compiled.scalar_names)):
            return None
        dyn = self._dyn
        dyn_out = DynamicInit(
            used_cpu=dyn.used_cpu.copy(), used_mem=dyn.used_mem.copy(),
            used_gpu=dyn.used_gpu.copy(), used_eph=dyn.used_eph.copy(),
            used_scalar=dyn.used_scalar.copy(),
            nonzero_cpu=dyn.nonzero_cpu.copy(),
            nonzero_mem=dyn.nonzero_mem.copy(),
            pod_count=dyn.pod_count.copy())
        return replace(compiled, dynamic=dyn_out,
                       groups=replace(compiled.groups,
                                      presence=self._presence.copy()))

    # -- scheduling ---------------------------------------------------------

    def schedule(self, pods: List[Pod], provider: str = "DefaultProvider",
                 fallback: str = "reference",
                 hard_pod_affinity_symmetric_weight: int = 10):
        """Compile the batch against the current picture and run the jax
        backend; placements are NOT folded back into the event log (feed bind
        events through apply() to make them durable, mirroring the
        simulator's Bind->store.Update loop)."""
        from tpusim.jaxe.backend import JaxBackend

        backend = JaxBackend(
            provider=provider, fallback=fallback,
            hard_pod_affinity_symmetric_weight=hard_pod_affinity_symmetric_weight)
        return backend.schedule(pods, self.to_snapshot(),
                                precompiled=self.compile(pods))

"""Multi-chip execution: shard the node axis (and the snapshot axis) over a
device mesh.

Design (SURVEY.md §5 "distributed communication backend"): the reference's
scaling axes are pods × nodes (16 goroutines per pod scan) and independent
cluster snapshots (the multi-tenant what-if). On TPU these map to:

  "node" mesh axis — node-column arrays ([N] carries, [sig, N] tables) are
      sharded over ICI; per-step reductions (max score, tie counts, cumsum
      ranks) become XLA collectives inserted by GSPMD — nothing hand-rolled.
  "snap" mesh axis — the 50-snapshot what-if (BASELINE.json config 5) is
      embarrassingly parallel: snapshots are batched on a leading axis and
      sharded across the mesh; zero cross-snapshot communication.

Single-host multi-chip and multi-host (ICI+DCN) use the same code path: a
jax.sharding.Mesh over jax.devices() — on multi-host, `jax.distributed` brings
up the fleet and the Mesh spans hosts, with XLA routing collectives over
ICI/DCN (this replaces the reference's in-process watch-event fabric; there is
no NCCL/MPI analog to port, SURVEY.md §2 note).

Axis placement is derived from the kernels.STATICS_AXES / CARRY_AXES
registries, so new state fields inherit padding + sharding automatically.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpusim.jaxe.kernels import (
    CARRY_AXES,
    PAD_FILLS,
    PODX_AXES,
    STATICS_AXES,
    Carry,
    PodX,
    Statics,
)

def _infeasible_sentinel():
    # computed lazily: jnp.int64 truncates to int32 before ensure_x64() runs
    return jnp.int64(1) << 62


def stage_tree(tree, sharding=None):
    """Stage a host-numpy pytree onto device — THE device_put shape shared
    by every staging site (mesh placement here, the what-if batcher, the
    preemption hybrid's re-arm, the serve executor, and the stream runtime's
    restage path):

      sharding=None         -> default-device commit (jnp.asarray per leaf)
      a single Sharding     -> that placement applied to every leaf
      a pytree of shardings -> leafwise jax.device_put (tree must match)
    """
    if sharding is None:
        return jax.tree.map(jnp.asarray, tree)
    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree.map(lambda a: jax.device_put(a, sharding), tree)
    return jax.tree.map(jax.device_put, tree, sharding)


def _valid_shard_counts(n: int) -> list:
    """Divisors of n: the shard counts a leading mesh axis can take."""
    return [d for d in range(1, n + 1) if n % d == 0]


def _factoring_error(n: int, first_axis: str, first: int,
                     second_axis: str) -> ValueError:
    """Non-factoring mesh request: name BOTH axes the grid would have had
    and list the shard counts that do divide the device count — the error
    used to name only one axis and leave the caller to factor by hand."""
    return ValueError(
        f"{n} devices do not factor into a ({first_axis!r}, {second_axis!r}) "
        f"mesh with {first_axis}={first} ({second_axis} would not get a "
        f"whole number of devices); valid {first_axis} counts for "
        f"{n} devices: {_valid_shard_counts(n)}")


def make_mesh(n_devices: Optional[int] = None, snap: int = 1,
              devices: Optional[list] = None) -> Mesh:
    """A ("snap", "node") mesh over the first n_devices devices."""
    if devices is None:
        devices = jax.devices()
    devices = devices[: (n_devices or len(devices))]
    n = len(devices)
    if snap < 1 or n % snap != 0:
        raise _factoring_error(n, "snap", snap, "node")
    grid = np.array(devices).reshape(snap, n // snap)
    return Mesh(grid, ("snap", "node"))


def make_scenario_mesh(n_devices: Optional[int] = None,
                       scenario: Optional[int] = None,
                       devices: Optional[list] = None) -> Mesh:
    """A ("scenario", "node") mesh for the shard_map what-if route: the
    scenario axis is partitioned MANUALLY (whatif._scenario_sharded), with
    node columns kept whole inside each shard — the per-step node reductions
    (argmax, tie counts, rank cumsum) stay shard-local instead of becoming
    collectives. `scenario` defaults to every visible device (node dim 1);
    a node dim > 1 replicates the manual program across node rows."""
    if devices is None:
        devices = jax.devices()
    devices = devices[: (n_devices or len(devices))]
    n = len(devices)
    scenario = scenario or n
    if scenario < 1 or n % scenario != 0:
        raise _factoring_error(n, "scenario", scenario, "node")
    grid = np.array(devices).reshape(scenario, n // scenario)
    return Mesh(grid, ("scenario", "node"))


def mesh_kind(mesh: Mesh) -> str:
    """Which what-if route a mesh selects: "snap" (GSPMD vmap: snapshot axis
    over "snap", node columns over "node") or "scenario" (manual shard_map
    over "scenario", node columns whole per shard). Anything else is a
    caller error surfaced here instead of as a KeyError inside dispatch."""
    names = tuple(mesh.axis_names)
    if names == ("snap", "node"):
        return "snap"
    if names == ("scenario", "node"):
        return "scenario"
    raise ValueError(
        f"what-if mesh has axes {names!r}; want ('snap', 'node') "
        "(make_mesh: GSPMD-sharded vmap) or ('scenario', 'node') "
        "(make_scenario_mesh: manual shard_map over scenarios)")


def match_partition_rules(rules: Sequence[Tuple[str, P]],
                          fields: Sequence[str],
                          prefix: str = "") -> Dict[str, P]:
    """Regex-rule PartitionSpec assignment (SNIPPETS [1] idiom): each field
    is matched as "prefix/name" against the rules in order; the first hit
    assigns its PartitionSpec, no hit means replicated (P()). Keeps the
    sharding story declarative as state trees grow fields."""
    out: Dict[str, P] = {}
    for name in fields:
        path = f"{prefix}/{name}" if prefix else name
        for pattern, spec in rules:
            if re.search(pattern, path):
                out[name] = spec
                break
        else:
            out[name] = P()
    return out


# The stacked what-if batch: every statics/carry/xs leaf gains a leading
# scenario axis in _stack_host, so every tree matches its prefix rule; the
# replicated default only catches future scalar/config leaves.
SCENARIO_BATCH_RULES: Tuple[Tuple[str, P], ...] = (
    (r"^statics/", P("scenario")),
    (r"^carry/", P("scenario")),
    (r"^xs/", P("scenario")),
)


def scenario_specs() -> Tuple[Carry, Statics, PodX]:
    """PartitionSpec trees (carry, statics, xs) for the shard_map what-if
    program, derived from the axis registries via the regex rules."""
    ca = match_partition_rules(SCENARIO_BATCH_RULES, CARRY_AXES, "carry")
    st = match_partition_rules(SCENARIO_BATCH_RULES, STATICS_AXES, "statics")
    xs = match_partition_rules(SCENARIO_BATCH_RULES, PODX_AXES, "xs")
    return Carry(**ca), Statics(**st), PodX(**xs)


def scenario_shardings(mesh: Mesh) -> Tuple[Carry, Statics, PodX]:
    """NamedSharding trees matching scenario_specs, for placing the stacked
    host batch so the shard_map program starts without a reshard."""
    ca, st, xs = scenario_specs()
    named = lambda tree: type(tree)(  # noqa: E731
        **{k: NamedSharding(mesh, v) for k, v in tree._asdict().items()})
    return named(ca), named(st), named(xs)


def _pad_to(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


def _pad_node_tree(tree, axes_map, pad: int):
    fields = {}
    for name, arr in tree._asdict().items():
        spec = axes_map[name]
        if "node" not in spec:
            fields[name] = arr
            continue
        # stay on host for numpy inputs (the what-if path pads before upload)
        xp = np if isinstance(arr, np.ndarray) else jnp
        ax = spec.index("node")
        widths = [(0, 0)] * arr.ndim
        widths[ax] = (0, pad)
        if name == "cond_fail_bits":
            sentinel = (np.int64(1) << 62) if xp is np else _infeasible_sentinel()
            fields[name] = xp.concatenate(
                [arr, xp.full(pad, sentinel, dtype=xp.int64)])
        else:
            fields[name] = xp.pad(arr, widths,
                                  constant_values=PAD_FILLS.get(name, 0))
    return type(tree)(**fields)


def pad_node_axis(statics: Statics, carry: Carry, n_shards: int
                  ) -> Tuple[Statics, Carry, int]:
    """Pad the node axis so it divides the mesh.

    Padded nodes are made permanently infeasible through a sentinel condition
    bit (bit 62): feasibility tests cond_fail_bits != 0, while the reason
    histogram only decodes bits [0, num_reason_bits), so the sentinel never
    shows up in failure messages and the padded nodes can never be selected.
    Returns the padded arrays plus the real node count."""
    n = statics.alloc_cpu.shape[0]
    padded = _pad_to(n, n_shards)
    pad = padded - n
    if pad == 0:
        return statics, carry, n
    return (_pad_node_tree(statics, STATICS_AXES, pad),
            _pad_node_tree(carry, CARRY_AXES, pad), n)


def pad_carry_node_axis(carry: Carry, n_shards: int) -> Carry:
    """Pad ONLY the carry's node axis to the mesh multiple (the preemption
    hybrid's re-arm path: statics were padded and placed at compile time and
    are reused; the fresh carry must match their padded node extent)."""
    name = next(n for n, spec in CARRY_AXES.items() if "node" in spec)
    ax = CARRY_AXES[name].index("node")
    n = getattr(carry, name).shape[ax]
    pad = _pad_to(n, n_shards) - n
    return carry if pad == 0 else _pad_node_tree(carry, CARRY_AXES, pad)


def _sharding_tree(tree_cls, axes_map, mesh: Mesh, leading: Optional[str] = None):
    fields = {}
    for name, spec in axes_map.items():
        parts = ([leading] if leading is not None else []) + [
            "node" if a == "node" else None for a in spec]
        fields[name] = NamedSharding(mesh, P(*parts))
    return tree_cls(**fields)


def node_shardings(mesh: Mesh) -> Tuple[Statics, Carry]:
    """NamedShardings for statics/carry pytrees: node axis sharded, signature
    and scalar axes replicated."""
    return (_sharding_tree(Statics, STATICS_AXES, mesh),
            _sharding_tree(Carry, CARRY_AXES, mesh))


def shard_for_mesh(mesh: Mesh, statics: Statics, carry: Carry, xs: PodX
                   ) -> Tuple[Statics, Carry, PodX]:
    """Place arrays: node columns sharded over the "node" axis, pod columns
    replicated (every shard sees every pod; the per-pod work is the reduction
    over its node shard)."""
    n_node_shards = mesh.shape["node"]
    statics, carry, _ = pad_node_axis(statics, carry, n_node_shards)
    st_spec, ca_spec = node_shardings(mesh)
    statics = stage_tree(statics, st_spec)
    carry = stage_tree(carry, ca_spec)
    xs = stage_tree(xs, NamedSharding(mesh, P()))
    return statics, carry, xs


def snap_shardings(mesh: Mesh) -> Tuple[Statics, Carry, object]:
    """Shardings for the multi-snapshot what-if: leading snapshot axis sharded
    over "snap", node axis over "node"."""
    statics = _sharding_tree(Statics, STATICS_AXES, mesh, leading="snap")
    carry = _sharding_tree(Carry, CARRY_AXES, mesh, leading="snap")
    xs_sharding = NamedSharding(mesh, P("snap"))
    return statics, carry, xs_sharding

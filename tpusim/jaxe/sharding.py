"""Multi-chip execution: shard the node axis (and the snapshot axis) over a
device mesh.

Design (SURVEY.md §5 "distributed communication backend"): the reference's
scaling axes are pods × nodes (16 goroutines per pod scan) and independent
cluster snapshots (the multi-tenant what-if). On TPU these map to:

  "node" mesh axis — node-column arrays ([N] carries, [sig, N] tables) are
      sharded over ICI; per-step reductions (max score, tie counts, cumsum
      ranks) become XLA collectives inserted by GSPMD — nothing hand-rolled.
  "snap" mesh axis — the 50-snapshot what-if (BASELINE.json config 5) is
      embarrassingly parallel: snapshots are batched on a leading axis and
      sharded across the mesh; zero cross-snapshot communication.

Single-host multi-chip and multi-host (ICI+DCN) use the same code path: a
jax.sharding.Mesh over jax.devices() — on multi-host, `jax.distributed` brings
up the fleet and the Mesh spans hosts, with XLA routing collectives over
ICI/DCN (this replaces the reference's in-process watch-event fabric; there is
no NCCL/MPI analog to port, SURVEY.md §2 note).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpusim.jaxe.kernels import Carry, PodX, Statics


def make_mesh(n_devices: Optional[int] = None, snap: int = 1,
              devices: Optional[list] = None) -> Mesh:
    """A ("snap", "node") mesh over the first n_devices devices."""
    if devices is None:
        devices = jax.devices()
    devices = devices[: (n_devices or len(devices))]
    n = len(devices)
    if n % snap != 0:
        raise ValueError(f"{n} devices do not factor into snap={snap}")
    grid = np.array(devices).reshape(snap, n // snap)
    return Mesh(grid, ("snap", "node"))


def _pad_to(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


def pad_node_axis(statics: Statics, carry: Carry, n_shards: int
                  ) -> Tuple[Statics, Carry, int]:
    """Pad the node axis so it divides the mesh.

    Padded nodes are made permanently infeasible through a sentinel condition
    bit (bit 62): feasibility tests cond_fail_bits != 0, while the reason
    histogram only decodes bits [0, num_reason_bits), so the sentinel never
    shows up in failure messages and the padded nodes can never be selected.
    Returns the padded arrays plus the real node count."""
    n = statics.alloc_cpu.shape[0]
    padded = _pad_to(n, n_shards)
    pad = padded - n
    if pad == 0:
        return statics, carry, n

    def pad1(a, fill=0):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths, constant_values=fill)

    def pad_last(a, fill=0):
        widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
        return jnp.pad(a, widths, constant_values=fill)

    sentinel = jnp.int64(1) << 62
    statics = Statics(
        alloc_cpu=pad1(statics.alloc_cpu), alloc_mem=pad1(statics.alloc_mem),
        alloc_gpu=pad1(statics.alloc_gpu), alloc_eph=pad1(statics.alloc_eph),
        allowed_pods=pad1(statics.allowed_pods),
        alloc_scalar=pad1(statics.alloc_scalar),
        cond_fail_bits=jnp.concatenate(
            [statics.cond_fail_bits, jnp.full(pad, sentinel, dtype=jnp.int64)]),
        mem_pressure=pad1(statics.mem_pressure),
        disk_pressure=pad1(statics.disk_pressure),
        selector_ok=pad_last(statics.selector_ok),
        taint_ok=pad_last(statics.taint_ok),
        intolerable=pad_last(statics.intolerable),
        affinity_count=pad_last(statics.affinity_count),
        avoid_score=pad_last(statics.avoid_score),
        host_ok=pad_last(statics.host_ok))
    carry = Carry(
        used_cpu=pad1(carry.used_cpu), used_mem=pad1(carry.used_mem),
        used_gpu=pad1(carry.used_gpu), used_eph=pad1(carry.used_eph),
        used_scalar=pad1(carry.used_scalar),
        nonzero_cpu=pad1(carry.nonzero_cpu), nonzero_mem=pad1(carry.nonzero_mem),
        pod_count=pad1(carry.pod_count), rr=carry.rr)
    return statics, carry, n


def node_shardings(mesh: Mesh) -> Tuple[Statics, Carry]:
    """NamedShardings for statics/carry pytrees: node axis sharded, signature
    and scalar axes replicated."""
    node = NamedSharding(mesh, P("node"))
    sig_node = NamedSharding(mesh, P(None, "node"))
    node_scalar = NamedSharding(mesh, P("node", None))
    scalar = NamedSharding(mesh, P())
    statics = Statics(
        alloc_cpu=node, alloc_mem=node, alloc_gpu=node, alloc_eph=node,
        allowed_pods=node, alloc_scalar=node_scalar, cond_fail_bits=node,
        mem_pressure=node, disk_pressure=node, selector_ok=sig_node,
        taint_ok=sig_node, intolerable=sig_node, affinity_count=sig_node,
        avoid_score=sig_node, host_ok=sig_node)
    carry = Carry(used_cpu=node, used_mem=node, used_gpu=node, used_eph=node,
                  used_scalar=node_scalar, nonzero_cpu=node, nonzero_mem=node,
                  pod_count=node, rr=scalar)
    return statics, carry


def shard_for_mesh(mesh: Mesh, statics: Statics, carry: Carry, xs: PodX
                   ) -> Tuple[Statics, Carry, PodX]:
    """Place arrays: node columns sharded over the "node" axis, pod columns
    replicated (every shard sees every pod; the per-pod work is the reduction
    over its node shard)."""
    n_node_shards = mesh.shape["node"]
    statics, carry, _ = pad_node_axis(statics, carry, n_node_shards)
    st_spec, ca_spec = node_shardings(mesh)
    statics = jax.tree.map(jax.device_put, statics, st_spec)
    carry = jax.tree.map(jax.device_put, carry, ca_spec)
    replicated = NamedSharding(mesh, P())
    xs = jax.tree.map(lambda a: jax.device_put(a, replicated), xs)
    return statics, carry, xs


def snap_shardings(mesh: Mesh) -> Tuple[Statics, Carry, object]:
    """Shardings for the multi-snapshot what-if: leading snapshot axis sharded
    over "snap", node axis over "node"."""
    sn = NamedSharding(mesh, P("snap", "node"))
    s_sig_node = NamedSharding(mesh, P("snap", None, "node"))
    s_node_scalar = NamedSharding(mesh, P("snap", "node", None))
    s_only = NamedSharding(mesh, P("snap"))
    statics = Statics(
        alloc_cpu=sn, alloc_mem=sn, alloc_gpu=sn, alloc_eph=sn,
        allowed_pods=sn, alloc_scalar=s_node_scalar, cond_fail_bits=sn,
        mem_pressure=sn, disk_pressure=sn, selector_ok=s_sig_node,
        taint_ok=s_sig_node, intolerable=s_sig_node, affinity_count=s_sig_node,
        avoid_score=s_sig_node, host_ok=s_sig_node)
    carry = Carry(used_cpu=sn, used_mem=sn, used_gpu=sn, used_eph=sn,
                  used_scalar=s_node_scalar, nonzero_cpu=sn, nonzero_mem=sn,
                  pod_count=sn, rr=s_only)
    xs_sharding = NamedSharding(mesh, P("snap"))
    return statics, carry, xs_sharding
